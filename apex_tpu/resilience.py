"""Resilient training driver: watchdog, rollback, retrying checkpoints.

The reference's only built-in robustness is the amp loss-scaler's skip-step
loop and the AutoResume hook (``apex/amp/scaler.py``,
``pipeline_parallel/utils.py:142-144``); everything else — surviving
preemptions, flaky storage, numeric blow-ups — is left to user scripts.
Production pre-training stacks (TorchTitan, arxiv 2410.06511) put that
layer in the framework: async distributed checkpointing, auto-resume and
failure recovery wrapped around the train step. This module is that layer
for apex_tpu, composing the pieces that already exist —
:func:`apex_tpu.training.make_train_step`-style stepping,
:class:`apex_tpu.checkpoint.CheckpointManager` and
:class:`apex_tpu.amp.scaler.LossScaler` — into a run that survives faults:

- :class:`Watchdog` — NaN/divergence detection: consecutive-skip abort
  (the reference amp aborts after repeated overflow skips), plus
  loss-spike and grad-norm anomaly detection against rolling medians.
  Metrics are computed **on device** inside the jitted step; the driver
  polls them in batches every ``poll_interval_steps`` so the host never
  blocks the step loop on a per-step device sync.
- **rollback-to-last-good** — on a verdict, restore the newest checkpoint
  from *before* the first bad step (suspect newer ones are deleted),
  decay the loss scale, advance the data "retry epoch" so the poisoned
  window is re-seeded, and retry under a bounded ``max_rollbacks`` budget.
- **retrying, atomic, async checkpoint I/O** —
  :class:`apex_tpu.checkpoint.RetryingCheckpointManager` over the
  sharded format (default): the step loop blocks only for the
  device→host snapshot, serialization + fsync + checksum run on a
  background writer inside the retry loop; restore verifies per-shard
  checksums and falls back to older steps on corruption, and is
  *elastic* — it reassembles shards onto a different mesh layout
  (``ResilienceConfig.checkpoint_format`` selects ``"orbax"`` for the
  original whole-array format).
- **preemption hook** — SIGTERM flips a flag; the loop flushes an
  emergency (forced) save and returns cleanly with
  ``status="preempted"``, resumable by the next invocation.
- **retrace watchdog** — :class:`apex_tpu.analysis.retrace.
  RetraceWatchdog` wraps ``step_fn`` and counts jit recompilations; a
  recompilation storm (ragged batches, pytree churn after a restore)
  raises after ``retrace_budget`` instead of silently running 10× slow.
- **observability** — attach an :class:`apex_tpu.observability.
  MetricsRegistry` (``ResilienceConfig.metrics``) and the driver mirrors
  every telemetry counter into it, emits incident events next to
  ``log_event``, and records step-time/tokens-per-s/MFU/memory metrics;
  ``python -m apex_tpu.monitor`` folds a JSONL sink's log into a run
  report that reconciles with :attr:`TrainingResult.telemetry`.

Every recovery path is exercised deterministically in tier-1 CPU tests via
:class:`apex_tpu.testing_faults.FaultInjector`.
"""

from __future__ import annotations

import inspect
import math
import signal
import statistics
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.amp.scaler import LossScaler, LossScalerState, all_finite
from apex_tpu.analysis.retrace import RetraceWatchdog
from apex_tpu.checkpoint import (
    CheckpointManager,
    RetryingCheckpointManager,
    ShardedCheckpointManager,
)
from apex_tpu.observability.step_metrics import StepMetrics
from apex_tpu.training import sync_data_parallel_grads
from apex_tpu.transformer.parallel_state import DATA_AXIS
from apex_tpu.utils.logging import get_logger, log_event
from apex_tpu.utils.tree import global_norm

__all__ = [
    "ResilienceConfig",
    "Watchdog",
    "WatchdogVerdict",
    "TrainingDiverged",
    "TrainingResult",
    "make_train_state",
    "make_resilient_train_step",
    "run_training",
]


class TrainingDiverged(RuntimeError):
    """Raised when the rollback retry budget is exhausted (the analog of
    the reference amp's abort after repeated overflow skips) or no healthy
    checkpoint exists to roll back to. Carries ``telemetry``."""

    def __init__(self, message: str, telemetry: Optional[dict] = None):
        super().__init__(message)
        self.telemetry = dict(telemetry or {})


@dataclass
class ResilienceConfig:
    """Knobs for :func:`run_training`. Defaults are conservative; tests
    shrink the windows to trip every path in a few steps."""

    # -- watchdog ---------------------------------------------------------
    #: consecutive skipped/non-finite steps before declaring divergence
    #: (the reference amp's repeated-overflow abort).
    max_consecutive_skips: int = 8
    #: loss deviation above the rolling median, in units of
    #: ``max(|median|, spike_floor)``, that counts as an anomaly.
    loss_spike_factor: float = 10.0
    #: same for the gradient norm (norms drift more; keep this loose).
    grad_spike_factor: float = 100.0
    spike_floor: float = 1e-3
    #: consecutive anomalous (but finite) steps before declaring divergence.
    anomaly_patience: int = 2
    history_window: int = 64
    #: spike detection stays silent until this much healthy history exists.
    min_history: int = 8
    #: device→host metric sync cadence; larger = cheaper, slower detection.
    poll_interval_steps: int = 8
    # -- rollback ---------------------------------------------------------
    max_rollbacks: int = 3
    #: divide the restored loss scale by this on every rollback (floored
    #: at 1.0) — re-diverging at the same scale is the common failure.
    rollback_scale_decay: float = 2.0
    #: pass an incremented retry-epoch to ``batch_fn(step, epoch)`` so the
    #: data pipeline can re-seed past the poisoned window.
    reseed_data_on_rollback: bool = True
    # -- checkpointing ----------------------------------------------------
    save_interval_steps: int = 50
    max_to_keep: int = 5
    save_final: bool = True
    resume: bool = True
    save_retries: int = 3
    save_backoff_base: float = 0.5
    save_backoff_max: float = 8.0
    delete_corrupt: bool = True
    #: on-disk format when the driver builds the manager from
    #: ``checkpoint_dir``: ``"sharded"`` (elastic mesh-reshape restore,
    #: per-shard checksums, async-capable) or ``"orbax"`` (the original
    #: whole-array format).
    checkpoint_format: str = "sharded"
    #: with the sharded format, run serialization + fsync + checksum on a
    #: background writer — the step loop blocks only for the device→host
    #: snapshot. ``False`` forces fully synchronous saves.
    checkpoint_async: bool = True
    #: emergency (preemption) saves first quiesce the async writer:
    #: ``True`` drains pending writes to commit, ``False`` abandons
    #: queued ones (the running write still commits atomically).
    preemption_drain: bool = True
    # -- retrace watchdog -------------------------------------------------
    #: recompilations of ``step_fn`` allowed beyond the warmup trace
    #: before :class:`~apex_tpu.analysis.retrace.RetraceBudgetExceeded`
    #: aborts the run (a recompilation storm means a 10× slowdown that
    #: would otherwise pass silently).  ``None`` disables the watchdog.
    retrace_budget: Optional[int] = 8
    # -- observability ----------------------------------------------------
    #: a :class:`apex_tpu.observability.MetricsRegistry`; when attached,
    #: the driver mirrors every ``TrainingResult.telemetry`` counter into
    #: it, emits incident events alongside ``log_event``, and feeds a
    #: :class:`~apex_tpu.observability.StepMetrics` layer (step time,
    #: tokens/s, MFU, memory gauges). ``python -m apex_tpu.monitor`` then
    #: reports the run from a JSONL sink's log.
    metrics: Optional[Any] = None
    #: global tokens per step — enables the ``tokens_per_s`` metric.
    tokens_per_step: Optional[int] = None
    #: model FLOPs per step (see :mod:`apex_tpu.utils.flops`) — enables
    #: ``model_tflops`` and, with a known/overridden peak, ``mfu``.
    model_flops_per_step: Optional[float] = None
    #: per-chip peak FLOP/s override; default auto-detects from the chip
    #: table (None on CPU/unknown — MFU then stays unset).
    peak_flops: Optional[float] = None
    #: device ``memory_stats()`` gauge cadence in steps (0 disables).
    memory_stats_interval_steps: int = 50
    #: a :class:`apex_tpu.observability.ProfilerCapture`; the driver
    #: advances its schedule each step and triggers a capture on watchdog
    #: verdicts.
    profiler: Optional[Any] = None
    # -- preemption -------------------------------------------------------
    handle_sigterm: bool = True
    record_history: bool = True


@dataclass
class WatchdogVerdict:
    reason: str          # "consecutive_skips" | "loss_spike" | "grad_spike"
    step: int            # step at which the verdict fired
    first_bad_step: int  # first step of the bad window (rollback bound)
    detail: str = ""


class Watchdog:
    """Host-side divergence detector over polled per-step metrics.

    ``observe(step, loss, grad_norm, skipped)`` returns a
    :class:`WatchdogVerdict` when training is deemed diverged, else None.
    Skipped or non-finite steps never enter the rolling history, so the
    spike baselines only reflect healthy steps; a healthy step resets the
    consecutive-skip and anomaly counters (the scaler's own hysteresis
    handles isolated overflows — the watchdog only fires on runs of them).
    """

    def __init__(self, config: Optional[ResilienceConfig] = None):
        self.config = config or ResilienceConfig()
        self._loss_hist: deque = deque(maxlen=self.config.history_window)
        self._gnorm_hist: deque = deque(maxlen=self.config.history_window)
        self.reset()

    def reset(self) -> None:
        self._loss_hist.clear()
        self._gnorm_hist.clear()
        self._skips = 0
        self._anomalies = 0
        self._first_bad: Optional[int] = None

    def _bad(self, step: int) -> int:
        if self._first_bad is None:
            self._first_bad = step
        return self._first_bad

    def observe(self, step: int, loss: float,
                grad_norm: Optional[float] = None,
                skipped: bool = False) -> Optional[WatchdogVerdict]:
        cfg = self.config
        nonfinite = not math.isfinite(loss) or (
            grad_norm is not None and not math.isfinite(grad_norm))
        if skipped or nonfinite:
            self._skips += 1
            first = self._bad(step)
            if self._skips >= cfg.max_consecutive_skips:
                return WatchdogVerdict(
                    "consecutive_skips", step, first,
                    detail=f"{self._skips} consecutive skipped/non-finite "
                           f"steps")
            return None

        spike = None
        if len(self._loss_hist) >= cfg.min_history:
            med = statistics.median(self._loss_hist)
            if loss - med > cfg.loss_spike_factor * max(abs(med),
                                                        cfg.spike_floor):
                spike = ("loss_spike",
                         f"loss {loss:.4g} vs median {med:.4g}")
        if (spike is None and grad_norm is not None
                and len(self._gnorm_hist) >= cfg.min_history):
            med = statistics.median(self._gnorm_hist)
            if grad_norm > cfg.grad_spike_factor * max(med, cfg.spike_floor):
                spike = ("grad_spike",
                         f"grad_norm {grad_norm:.4g} vs median {med:.4g}")

        if spike is not None:
            self._anomalies += 1
            first = self._bad(step)
            if self._anomalies >= cfg.anomaly_patience:
                return WatchdogVerdict(spike[0], step, first,
                                       detail=spike[1])
            return None

        self._skips = 0
        self._anomalies = 0
        self._first_bad = None
        self._loss_hist.append(loss)
        if grad_norm is not None:
            self._gnorm_hist.append(grad_norm)
        return None


@dataclass
class TrainingResult:
    state: Any
    status: str               # "completed" | "preempted"
    steps_completed: int
    rollbacks: int
    telemetry: Dict[str, int]
    history: List[dict] = field(default_factory=list)


def make_train_state(params: Any, opt_state: Any,
                     scaler_state: Optional[LossScalerState] = None,
                     step: int = 0) -> dict:
    """The train-state pytree :func:`run_training` drives: one dict holding
    everything a resume needs (the whole thing round-trips through one
    checkpoint call pair — scaler state and fp32 masters are ordinary
    leaves, per ``apex_tpu.checkpoint``'s design)."""
    state = {
        "params": params,
        "opt_state": opt_state,
        "step": jnp.asarray(step, jnp.int32),
    }
    if scaler_state is not None:
        state["scaler"] = scaler_state
    return state


def make_resilient_train_step(
    loss_fn: Callable,
    optimizer,
    scaler: Optional[LossScaler] = None,
    *,
    mesh=None,
    param_spec=None,
    batch_spec=None,
    opt_state_spec=None,
    params_template=None,
    data_axes: Sequence[str] = (DATA_AXIS,),
    donate: bool = True,
) -> Callable:
    """Build ``step(state, batch, rng) -> (state, metrics)`` — the
    amp-aware sibling of :func:`apex_tpu.training.make_train_step` with the
    driver's contract: ``state`` is a :func:`make_train_state` dict and
    ``metrics`` carries on-device ``loss`` / ``grad_norm`` / ``skipped``
    (and ``loss_scale`` when a scaler is wired) for the watchdog to poll.

    With ``scaler`` the loss is scaled before autodiff, grads are unscaled
    with non-finites zeroed, the optimizer skips on overflow via its
    ``found_inf`` select, and the scaler state updates — the reference
    recommended-flow loop (``README.md:63-103``) as one jitted program.
    Without a scaler, ``skipped`` still reports a fused finiteness check of
    the raw grads so the watchdog sees NaN blow-ups either way.

    Mesh semantics (``mesh``/``param_spec``/``batch_spec``/``data_axes``)
    match ``make_train_step``: per-rank autodiff under shard_map, grad
    pmean over the data axes, single-device fast path on a size-1 mesh.
    """
    if mesh is not None and opt_state_spec is None:
        if params_template is None:
            raise ValueError(
                "need opt_state_spec or params_template to derive it")
        opt_state_spec = optimizer.state_spec(params_template, param_spec)

    if getattr(optimizer, "handles_grad_sync", False):
        opt_axis = getattr(optimizer, "axis_name", None)
        grad_sync_axes = tuple(a for a in data_axes if a != opt_axis)
    else:
        grad_sync_axes = tuple(data_axes)

    def per_rank(state, batch, rng):
        params, opt_state = state["params"], state["opt_state"]
        sstate = state.get("scaler")
        if rng is not None:
            # per-data-shard dropout streams, exactly as make_train_step
            for a in data_axes:
                try:
                    idx = lax.axis_index(a)
                except NameError:
                    idx = 0
                rng = jax.random.fold_in(rng, idx)

        def fwd(p):
            loss = loss_fn(p, batch, rng)
            scaled = loss if sstate is None else scaler.scale(loss, sstate)
            return scaled, loss

        grads, loss = jax.grad(fwd, has_aux=True)(params)
        if mesh is not None:
            grads = sync_data_parallel_grads(grads, grad_sync_axes,
                                             param_spec)
            loss = sync_data_parallel_grads(loss, data_axes)
        if sstate is not None:
            grads, found_inf = scaler.unscale(grads, sstate)
        else:
            found_inf = jnp.logical_not(all_finite(grads))
        gnorm = global_norm(grads)
        new_params, new_opt = optimizer.step(grads, params, opt_state,
                                             found_inf=found_inf)
        new_state = {"params": new_params, "opt_state": new_opt,
                     "step": state["step"] + 1}
        metrics = {"loss": loss, "grad_norm": gnorm, "skipped": found_inf}
        if sstate is not None:
            new_sstate = scaler.update(sstate, found_inf)
            new_state["scaler"] = new_sstate
            metrics["loss_scale"] = new_sstate.loss_scale
        return new_state, metrics

    donate_argnums = (0,) if donate else ()
    if mesh is None or mesh.size == 1:
        return jax.jit(per_rank, donate_argnums=donate_argnums)

    state_spec = {"params": param_spec, "opt_state": opt_state_spec,
                  "step": PartitionSpec()}
    metrics_spec = {"loss": PartitionSpec(), "grad_norm": PartitionSpec(),
                    "skipped": PartitionSpec()}
    if scaler is not None:
        state_spec["scaler"] = jax.tree.map(lambda _: PartitionSpec(),
                                            scaler.init())
        metrics_spec["loss_scale"] = PartitionSpec()
    from apex_tpu.utils.sharding import shard_map

    sharded = shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(state_spec, batch_spec, PartitionSpec()),
        out_specs=(state_spec, metrics_spec),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donate_argnums)


class _SigtermGuard:
    """Scoped SIGTERM hook: sets ``triggered`` instead of killing the
    process, restores the previous handler on exit. Installation is a
    no-op off the main thread (signal API restriction) or when handling
    is disabled — ``triggered`` then only reflects injected preemptions."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.triggered = False
        self._prev = None
        self._installed = False

    def __enter__(self):
        if (self.enabled
                and threading.current_thread() is threading.main_thread()):
            self._prev = signal.signal(signal.SIGTERM, self._on_signal)
            self._installed = True
        return self

    def _on_signal(self, signum, frame):
        self.triggered = True

    def __exit__(self, *exc):
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev)
        return False


def _batch_caller(batch_fn: Callable) -> Callable[[int, int], Any]:
    """Normalize ``batch_fn`` to ``(step, retry_epoch) -> batch``.
    A single-parameter callable ignores the retry epoch (its data cannot
    be re-seeded past a poisoned window — fine when faults are transient).
    """
    try:
        sig = inspect.signature(batch_fn)
        takes_epoch = len([
            p for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]) >= 2 or any(p.kind == p.VAR_POSITIONAL
                       for p in sig.parameters.values())
    except (TypeError, ValueError):
        takes_epoch = False
    if takes_epoch:
        return batch_fn
    return lambda step, epoch: batch_fn(step)


def run_training(
    step_fn: Callable,
    state: dict,
    batch_fn: Callable,
    num_steps: int,
    *,
    rng: Optional[jax.Array] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_manager=None,
    config: Optional[ResilienceConfig] = None,
    fault_injector=None,
) -> TrainingResult:
    """Drive ``step_fn`` for ``num_steps`` with watchdog, rollback,
    retrying checkpoints and preemption handling.

    Args:
      step_fn: ``(state, batch, rng) -> (state, metrics)`` — what
        :func:`make_resilient_train_step` builds. ``metrics`` may carry
        ``loss`` (scalar), ``grad_norm`` and ``skipped``; missing keys
        simply disable the corresponding watchdog checks.
      state: a :func:`make_train_state` dict (must hold a scalar ``step``
        leaf — it is the resume/rollback anchor).
      batch_fn: ``(step) -> batch`` or ``(step, retry_epoch) -> batch``.
        Must be a pure function of its arguments: rollback re-reads past
        steps, and the epoch increments per rollback to re-seed the
        poisoned window.
      rng: optional base PRNG key; the per-step key is
        ``fold_in(rng, step)`` so a rolled-back or resumed run replays
        identical streams.
      checkpoint_dir / checkpoint_manager: where to save. Pass a directory
        (a :class:`RetryingCheckpointManager` is built from the config
        knobs, wired to the fault injector's save hook) or a ready-made
        manager. With neither, the run still watches for divergence but
        cannot roll back — a verdict raises :class:`TrainingDiverged`.
      fault_injector: a :class:`apex_tpu.testing_faults.FaultInjector`.

    Returns a :class:`TrainingResult`; raises :class:`TrainingDiverged`
    when recovery is impossible within the budget.
    """
    cfg = config or ResilienceConfig()
    log = get_logger(__name__)
    if not (isinstance(state, dict) and "step" in state):
        raise ValueError("state must be a make_train_state-style dict with "
                         "a scalar 'step' leaf")

    def _wrap(base) -> RetryingCheckpointManager:
        return RetryingCheckpointManager(
            base, max_retries=cfg.save_retries,
            backoff_base=cfg.save_backoff_base,
            backoff_max=cfg.save_backoff_max,
            delete_corrupt=cfg.delete_corrupt,
            async_writes=cfg.checkpoint_async,
            drain_on_force=cfg.preemption_drain,
            metrics=cfg.metrics,
            before_save=getattr(fault_injector, "before_checkpoint_save",
                                None))

    mgr = None
    own_mgr = False
    if checkpoint_manager is not None:
        mgr = checkpoint_manager
        if isinstance(mgr, (CheckpointManager, ShardedCheckpointManager)):
            mgr = _wrap(mgr)
        elif (isinstance(mgr, RetryingCheckpointManager)
                and cfg.metrics is not None and mgr.metrics is None):
            # a pre-wrapped manager still reports into the attached
            # registry, else the monitor's ckpt_* counters cannot
            # reconcile with the merged telemetry
            mgr.metrics = cfg.metrics
    elif checkpoint_dir is not None:
        # manager-level interval gating stays at 1: the driver decides
        # when to save, and rollback/emergency saves must never be
        # swallowed
        if cfg.checkpoint_format == "sharded":
            base = ShardedCheckpointManager(
                checkpoint_dir, max_to_keep=cfg.max_to_keep,
                save_interval_steps=1)
        elif cfg.checkpoint_format == "orbax":
            base = CheckpointManager(checkpoint_dir,
                                     max_to_keep=cfg.max_to_keep,
                                     save_interval_steps=1)
        else:
            raise ValueError(
                f"unknown checkpoint_format {cfg.checkpoint_format!r} "
                f"(expected 'sharded' or 'orbax')")
        mgr = _wrap(base)
        own_mgr = True

    # a recompilation storm (ragged batch shapes, pytree-structure churn
    # after a restore) must surface as a watchdog event, not as a silent
    # 10× slowdown — wrap the step in the retrace watchdog
    if cfg.retrace_budget is not None and not isinstance(step_fn,
                                                         RetraceWatchdog):
        step_fn = RetraceWatchdog(step_fn, budget=cfg.retrace_budget,
                                  name="train_step", logger=log,
                                  metrics=cfg.metrics)
    elif (isinstance(step_fn, RetraceWatchdog) and cfg.metrics is not None
            and step_fn.metrics is None):
        # a pre-wrapped watchdog still reports into the attached registry,
        # else the monitor's retrace counter cannot reconcile
        step_fn.metrics = cfg.metrics

    watchdog = Watchdog(cfg)
    get_batch = _batch_caller(batch_fn)
    telemetry = {"steps": 0, "skips": 0, "rollbacks": 0, "preemptions": 0,
                 "emergency_saves": 0, "resumes": 0, "verdicts": 0,
                 "retraces": 0}
    reg = cfg.metrics
    prof = cfg.profiler
    step_metrics = None
    if reg is not None:
        # every telemetry key exists in the registry from step 0, so the
        # final counters snapshot reconciles key-for-key even for
        # incident types that never fired
        reg.declare_counters(*telemetry)
        ckpt_telemetry = getattr(mgr, "telemetry", None) or {}
        reg.declare_counters(*("ckpt_" + k for k in ckpt_telemetry))
        for k, v in ckpt_telemetry.items():
            if v:
                # a pre-used manager arrives with history: seed the
                # registry so the final snapshot still equals the merged
                # telemetry key-for-key
                reg.inc("ckpt_" + k, v)
        step_metrics = StepMetrics(
            reg, tokens_per_step=cfg.tokens_per_step,
            model_flops_per_step=cfg.model_flops_per_step,
            peak_flops=cfg.peak_flops,
            memory_interval_steps=cfg.memory_stats_interval_steps)

    def _tick(key: str, n: int = 1) -> None:
        """One incident, two ledgers: the TrainingResult telemetry dict
        and (when attached) the registry counter of the same name."""
        telemetry[key] += n
        if reg is not None:
            reg.inc(key, n)

    history: List[dict] = []
    pending: List[Tuple[int, Any]] = []

    host_step = int(jax.device_get(state["step"]))
    rollbacks = 0
    data_epoch = 0

    if mgr is not None and cfg.resume:
        restored = mgr.restore_latest(state)
        if restored is not None:
            ckpt_step, state = restored
            host_step = int(jax.device_get(state["step"]))
            _tick("resumes")
            log_event(log, "training_resumed", step=host_step,
                      checkpoint=ckpt_step, level="info")
            if reg is not None:
                reg.event("training_resumed", step=host_step,
                          checkpoint=ckpt_step)

    def _flush() -> Optional[WatchdogVerdict]:
        """Sync pending device metrics to host and feed the watchdog —
        the ONLY place the driver blocks on the device, so the step loop
        runs ``poll_interval_steps`` ahead of the anomaly checks."""
        nonlocal pending
        if not pending:
            return None
        values = jax.device_get([m for _, m in pending])
        verdict = None
        for (step_i, _), vals in zip(pending, values):
            loss = float(vals["loss"]) if "loss" in vals else float("nan")
            gnorm = vals.get("grad_norm")
            gnorm = None if gnorm is None else float(gnorm)
            skipped = bool(vals.get("skipped", False))
            _tick("skips", int(skipped))
            if cfg.record_history:
                history.append({"step": step_i, "loss": loss,
                                "grad_norm": gnorm, "skipped": skipped})
            if step_metrics is not None:
                scale = vals.get("loss_scale")
                step_metrics.record_polled(
                    step_i, loss=loss, grad_norm=gnorm, skipped=skipped,
                    loss_scale=None if scale is None else float(scale))
                if skipped:
                    reg.event("skip", step=step_i)
            if verdict is None:
                verdict = watchdog.observe(step_i, loss, gnorm, skipped)
        pending = []
        return verdict

    def _rollback(verdict: WatchdogVerdict) -> None:
        nonlocal state, host_step, data_epoch, rollbacks
        _tick("verdicts")
        log_event(log, "watchdog_verdict", reason=verdict.reason,
                  step=verdict.step, first_bad_step=verdict.first_bad_step,
                  detail=verdict.detail, level="error")
        if reg is not None:
            reg.event("watchdog_verdict", reason=verdict.reason,
                      step=verdict.step,
                      first_bad_step=verdict.first_bad_step,
                      detail=verdict.detail)
        if prof is not None:
            prof.on_incident(verdict.reason, verdict.step)
        if mgr is None:
            raise TrainingDiverged(
                f"watchdog verdict '{verdict.reason}' at step "
                f"{verdict.step} and no checkpoint manager to roll back "
                f"with: {verdict.detail}", telemetry)
        rollbacks += 1
        _tick("rollbacks")
        if rollbacks > cfg.max_rollbacks:
            raise TrainingDiverged(
                f"rollback budget exhausted ({cfg.max_rollbacks}) after "
                f"verdict '{verdict.reason}' at step {verdict.step}",
                telemetry)
        restored = mgr.restore_before(verdict.first_bad_step, state)
        if restored is None:
            raise TrainingDiverged(
                f"no healthy checkpoint older than step "
                f"{verdict.first_bad_step} to roll back to", telemetry)
        ckpt_step, state = restored
        # checkpoints newer than the restore point were written inside the
        # undetected window — delete them so neither a later rollback nor
        # a crash-resume can land on suspect state
        for s in mgr.manager.all_steps():
            if s > ckpt_step:
                try:
                    mgr.manager.delete(s)
                except Exception:  # noqa: BLE001
                    pass
        if "scaler" in state:
            sc = state["scaler"]
            state = dict(state)
            state["scaler"] = sc.replace(
                loss_scale=jnp.maximum(
                    sc.loss_scale / cfg.rollback_scale_decay,
                    1.0).astype(jnp.float32),
                growth_tracker=jnp.zeros_like(sc.growth_tracker),
                unskipped=jnp.zeros_like(sc.unskipped),
            )
        host_step = int(jax.device_get(state["step"]))
        if cfg.reseed_data_on_rollback:
            data_epoch += 1
        watchdog.reset()
        log_event(log, "rollback", to_step=ckpt_step, attempt=rollbacks,
                  budget=cfg.max_rollbacks, data_epoch=data_epoch,
                  level="warning")
        if reg is not None:
            reg.event("rollback", to_step=ckpt_step, attempt=rollbacks,
                      budget=cfg.max_rollbacks, data_epoch=data_epoch)

    status = "completed"
    try:
        with _SigtermGuard(cfg.handle_sigterm) as guard:
            while True:
                while host_step < num_steps:
                    faults = (fault_injector.begin_step()
                              if fault_injector is not None else None)
                    if guard.triggered or (faults is not None
                                           and faults.preempt):
                        source = ("sigterm" if guard.triggered
                                  else "injected")
                        _flush()
                        _tick("preemptions")
                        status = "preempted"
                        if mgr is not None:
                            saved = mgr.save(host_step, state, force=True)
                            _tick("emergency_saves", int(saved))
                            log_event(log, "preemption_save",
                                      step=host_step, saved=saved,
                                      source=source, level="warning")
                            if reg is not None:
                                reg.event("preemption_save",
                                          step=host_step, saved=saved,
                                          source=source)
                        break
                    batch = get_batch(host_step, data_epoch)
                    if faults is not None and faults.nan_grads:
                        from apex_tpu.testing_faults import poison_batch
                        batch = poison_batch(batch)
                    step_rng = (None if rng is None
                                else jax.random.fold_in(rng, host_step))
                    if step_metrics is not None:
                        step_metrics.begin_step()
                    state, metrics = step_fn(state, batch, step_rng)
                    host_step += 1
                    _tick("steps")
                    if step_metrics is not None:
                        step_metrics.end_step(host_step)
                    if prof is not None:
                        prof.on_step(host_step)
                    pending.append((host_step, metrics))

                    at_save = (mgr is not None
                               and host_step % cfg.save_interval_steps == 0)
                    if len(pending) >= cfg.poll_interval_steps or at_save:
                        # vet before saving: a checkpoint is only written
                        # once every step it contains passed the watchdog
                        verdict = _flush()
                        if verdict is not None:
                            _rollback(verdict)
                            continue
                    if at_save:
                        mgr.save(host_step, state)

                if status == "preempted":
                    break
                # the tail of the run may not land on a poll boundary —
                # flush, and if the LAST window diverged, roll back and
                # take another pass over the remaining steps
                verdict = _flush()
                if verdict is not None:
                    _rollback(verdict)
                    continue
                if mgr is not None and cfg.save_final:
                    # settle in-flight async writes before deciding
                    # whether the final step still needs a (sync) save
                    mgr.wait_until_finished()
                    if mgr.manager.latest_step() != host_step:
                        mgr.save(host_step, state, force=True)
                break
    finally:
        if isinstance(step_fn, RetraceWatchdog):
            telemetry["retraces"] = step_fn.retraces
        if prof is not None and prof.active:
            prof.stop(host_step)
        if mgr is not None:
            try:
                mgr.wait_until_finished()
            finally:
                if own_mgr:
                    mgr.close()
            # merge the (now-quiesced) checkpoint ledger into the run
            # telemetry under a ckpt_ prefix — the same names the
            # registry counters carry, so the monitor reconciles both
            for k, v in (getattr(mgr, "telemetry", None) or {}).items():
                telemetry["ckpt_" + k] = v
        if reg is not None:
            # the final snapshot is the monitor CLI's reconciliation
            # anchor — flush even on the TrainingDiverged exit paths
            reg.flush()

    return TrainingResult(state, status, host_step, rollbacks, telemetry,
                          history)
