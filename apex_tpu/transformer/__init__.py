"""Megatron-style model parallelism on a TPU mesh (capability of
``apex/transformer``): tensor, sequence, pipeline, and context parallelism
plus the mesh registry (``parallel_state``)."""

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel

__all__ = ["parallel_state", "tensor_parallel"]
