"""Megatron-style model parallelism on a TPU mesh (capability of
``apex/transformer``): tensor, sequence, pipeline, context, and expert
parallelism plus the mesh registry (``parallel_state``)."""

from apex_tpu.transformer import enums
from apex_tpu.transformer import functional
from apex_tpu.transformer import moe
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType
from apex_tpu.transformer.moe import MoEConfig, SwitchMLP

__all__ = [
    "enums",
    "functional",
    "moe",
    "parallel_state",
    "tensor_parallel",
    "AttnMaskType",
    "AttnType",
    "LayerType",
    "ModelType",
    "MoEConfig",
    "SwitchMLP",
]
