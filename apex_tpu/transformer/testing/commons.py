"""Shared test helpers (counterpart of
``apex/transformer/testing/commons.py:44-296``): seeded init, a trainable
identity fixture, distributed/mesh bring-up, and toy forward-step functions
for pipeline-schedule tests."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.transformer import parallel_state

__all__ = ["set_random_seed", "IdentityLayer", "initialize_distributed",
           "print_separator", "model_provider_func", "fwd_step_func"]


def set_random_seed(seed: int) -> jax.Array:
    """Seed numpy + return a JAX key (the reference seeds torch/cuda RNGs;
    JAX's explicit keys make most of that moot, numpy covers host-side
    shuffles)."""
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


class IdentityLayer:
    """A single trainable tensor returned as-is (reference ``IdentityLayer``):
    the minimal "model" for exercising grad flows through collectives."""

    def __init__(self, shape: Sequence[int], scale: float = 1.0, seed: int = 0):
        self.shape = tuple(shape)
        self.scale = scale
        self.seed = seed

    def init(self, key: Optional[jax.Array] = None):
        key = key if key is not None else jax.random.PRNGKey(self.seed)
        return {"weight": self.scale * jax.random.normal(key, self.shape)}

    def apply(self, params):
        return params["weight"]


def initialize_distributed(tensor_model_parallel_size: int = 1,
                           pipeline_model_parallel_size: int = 1,
                           context_parallel_size: int = 1,
                           **kw):
    """Mesh bring-up for tests (the reference's NCCL process-group init +
    ``parallel_state.initialize_model_parallel``)."""
    parallel_state.destroy_model_parallel()
    return parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tensor_model_parallel_size,
        pipeline_model_parallel_size=pipeline_model_parallel_size,
        context_parallel_size=context_parallel_size, **kw)


def print_separator(message: str) -> None:
    print("\n" + "-" * 31 + f" {message} " + "-" * 31, flush=True)


def model_provider_func(hidden_size: int, seed: int = 0) -> Tuple[Any, Any]:
    """A toy two-matmul model ``(module, params)`` for schedule tests
    (reference ``commons.py`` ``MyModel``)."""
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)

    class _Toy:
        def init(self, key=None):
            a, b = (k1, k2) if key is None else jax.random.split(key)
            return {"w1": 0.02 * jax.random.normal(a, (hidden_size,
                                                       hidden_size)),
                    "w2": 0.02 * jax.random.normal(b, (hidden_size,
                                                       hidden_size))}

        def apply(self, params, x):
            return jnp.tanh(x @ params["w1"]) @ params["w2"]

    m = _Toy()
    return m, m.init()


def fwd_step_func(model) -> Callable:
    """Forward-step closure in this framework's no-pipelining-schedule shape
    ``(params, microbatch) -> scalar loss`` (role of the reference's
    ``commons.py`` ``fwd_step_func``; the pipelined schedules instead take a
    ``(preprocess, stage, postprocess)`` triple, see
    ``schedules/fwd_bwd_pipelining_without_interleaving.py``)."""

    def _step(params, microbatch):
        out = model.apply(params, microbatch)
        return jnp.mean(jnp.square(out))

    return _step
