"""Distributed test base.

Counterpart of ``apex/transformer/testing/distributed_test_base.py:22-126``:
the reference subclasses ``MultiProcessTestCase`` to spawn one process per
GPU with NCCL/UCC file-store init. On TPU the honest single-host analog
(SURVEY.md §4) is a virtual device mesh: N CPU devices from
``--xla_force_host_platform_device_count`` (or the real chips), with
``parallel_state`` meshes built/torn down per test. ``world_size`` mirrors
the reference's "min(4, gpus)" policy but over available JAX devices.
"""

from __future__ import annotations

import unittest
from typing import Optional

import jax

from apex_tpu.transformer import parallel_state

__all__ = ["DistributedTestBase"]


class DistributedTestBase(unittest.TestCase):
    """unittest base managing mesh lifecycle around each test.

    Usage mirrors the reference: subclasses read ``self.world_size``, call
    ``self.initialize_model_parallel(tp, pp, cp)`` and get automatic
    teardown. Works under pytest as plain classes too.
    """

    #: cap matching the reference's 4-GPU default (``world_size`` property,
    #: distributed_test_base.py:36-38); override in subclasses as needed
    MAX_WORLD_SIZE: Optional[int] = None

    @property
    def world_size(self) -> int:
        n = len(jax.devices())
        if self.MAX_WORLD_SIZE is not None:
            n = min(n, self.MAX_WORLD_SIZE)
        return n

    def setUp(self):
        super().setUp()
        parallel_state.destroy_model_parallel()

    def tearDown(self):
        parallel_state.destroy_model_parallel()
        super().tearDown()

    def initialize_model_parallel(self, tensor_model_parallel_size: int = 1,
                                  pipeline_model_parallel_size: int = 1,
                                  context_parallel_size: int = 1, **kw):
        devs = jax.devices()[:self.world_size]
        return parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tensor_model_parallel_size,
            pipeline_model_parallel_size=pipeline_model_parallel_size,
            context_parallel_size=context_parallel_size,
            devices=devs, **kw)
