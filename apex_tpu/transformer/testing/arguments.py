"""Megatron-style global arguments.

Counterpart of ``apex/transformer/testing/arguments.py`` (977 LoC of
Megatron argparse): the subset of flags that shape models, parallel layout,
precision, and training schedule in this framework. ``parse_args`` accepts
``extra_args_provider`` and ``defaults`` overrides and performs the same
derived-value checks (world size divisibility, global/micro batch
consistency) the reference does.
"""

from __future__ import annotations

import argparse
from typing import Callable, Dict, Optional

__all__ = ["parse_args", "core_transformer_config_from_args"]


def parse_args(extra_args_provider: Optional[Callable] = None,
               defaults: Optional[Dict] = None,
               ignore_unknown_args: bool = False,
               args=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="apex_tpu Megatron-style arguments",
        allow_abbrev=False)

    g = parser.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=2)
    g.add_argument("--hidden-size", type=int, default=128)
    g.add_argument("--num-attention-heads", type=int, default=8)
    g.add_argument("--num-query-groups", type=int, default=None,
                   help="GQA/MQA K/V head groups (None = MHA)")
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--seq-length", type=int, default=128)
    g.add_argument("--max-position-embeddings", type=int, default=128)
    g.add_argument("--vocab-size", type=int, default=4096)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--position-embedding-type", type=str, default="learned",
                   choices=["learned", "rope", "none"])
    g.add_argument("--rotary-percent", type=float, default=1.0)
    g.add_argument("--rotary-base", type=float, default=10000.0,
                   help="rope theta")
    g.add_argument("--normalization", type=str, default="layernorm",
                   choices=["layernorm", "rmsnorm"])
    g.add_argument("--swiglu", action="store_true",
                   help="gated SiLU MLP (sets activation=swiglu)")
    g.add_argument("--activation", type=str, default=None,
                   help="explicit MLP activation (overrides --swiglu)")
    g.add_argument("--sliding-window", type=int, default=None,
                   help="causal local-attention span (Mistral-style)")

    g = parser.add_argument_group("moe")
    g.add_argument("--num-experts", type=int, default=None,
                   help="SwitchMLP experts per layer (None = dense)")
    g.add_argument("--moe-router-topk", type=int, default=1)
    g.add_argument("--moe-capacity-factor", type=float, default=1.25)
    g.add_argument("--moe-aux-loss-coeff", type=float, default=1e-2)
    g.add_argument("--moe-expert-axis", type=str, default=None,
                   help="mesh axis for expert parallelism (e.g. 'data')")

    g = parser.add_argument_group("parallel")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--context-parallel-method", type=str, default=None,
                   choices=[None, "ring", "ulysses"])
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--num-slices", type=int, default=1,
                   help="multi-slice (DCN) topology: data axis DCN-major")
    g.add_argument("--world-size", type=int, default=None,
                   help="defaults to jax.device_count()")

    g = parser.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", type=int, nargs=3, default=None,
                   metavar=("START", "INCR", "SAMPLES"))
    g.add_argument("--train-iters", type=int, default=10)
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "lamb", "sgd"])
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--use-distributed-optimizer", action="store_true",
                   help="ZeRO-sharded optimizer state over the data axis")
    g.add_argument("--seed", type=int, default=1234)

    g = parser.add_argument_group("precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None,
                   help="static loss scale (None = dynamic when fp16)")
    g.add_argument("--initial-loss-scale", type=float, default=2.0 ** 32)
    g.add_argument("--loss-scale-window", type=int, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--fp8", action="store_true",
                   help="fp8 delayed-scaling qdq hooks (amp.fp8)")
    g.add_argument("--fp8-margin", type=int, default=0)
    g.add_argument("--fp8-amax-history-len", type=int, default=16)

    g = parser.add_argument_group("checkpoint/misc")
    g.add_argument("--recompute", action="store_true",
                   help="full-layer activation recompute")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--adlr-autoresume", action="store_true")
    g.add_argument("--log-interval", type=int, default=10)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        ns, _ = parser.parse_known_args(args)
    else:
        ns = parser.parse_args(args)

    for k, v in (defaults or {}).items():
        key = k.replace("-", "_")
        cur = getattr(ns, key, None)
        # identity checks: unset options (None) and un-passed store_true
        # flags (False) take the default; explicit numeric zeros do not
        if cur is None or cur is False:
            setattr(ns, key, v)

    # derived values + validation (reference parse_args post-processing)
    if ns.world_size is None:
        import jax
        ns.world_size = jax.device_count()
    mp = (ns.tensor_model_parallel_size * ns.pipeline_model_parallel_size
          * ns.context_parallel_size)
    if ns.world_size % mp:
        raise ValueError(
            f"world size {ns.world_size} not divisible by model-parallel "
            f"size {mp}")
    ns.data_parallel_size = ns.world_size // mp
    if ns.global_batch_size is None:
        ns.global_batch_size = ns.micro_batch_size * ns.data_parallel_size
    if ns.global_batch_size % (ns.micro_batch_size * ns.data_parallel_size):
        raise ValueError(
            f"global batch {ns.global_batch_size} not divisible by "
            f"micro-batch {ns.micro_batch_size} x dp {ns.data_parallel_size}")
    if ns.ffn_hidden_size is None:
        ns.ffn_hidden_size = 4 * ns.hidden_size
    if ns.fp16 and ns.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    if ns.activation is None:
        ns.activation = "swiglu" if ns.swiglu else "gelu"
    if (ns.num_query_groups is not None
            and ns.num_attention_heads % ns.num_query_groups):
        raise ValueError(
            f"num_attention_heads ({ns.num_attention_heads}) must be "
            f"divisible by num_query_groups ({ns.num_query_groups})")
    if ns.num_experts is not None and ns.moe_expert_axis == "data":
        ep = ns.data_parallel_size
        if ep > 1 and ns.num_experts % ep:
            raise ValueError(
                f"num_experts ({ns.num_experts}) must divide evenly over "
                f"the expert axis (data, size {ep})")
    if ns.context_parallel_size > 1 and ns.context_parallel_method is None:
        ns.context_parallel_method = "ring"
    ns.params_dtype = "float32"
    if ns.bf16:
        ns.params_dtype = "bfloat16"
    return ns


def core_transformer_config_from_args(args):
    """Build a :class:`apex_tpu.models.TransformerConfig` from parsed args."""
    import jax.numpy as jnp

    from apex_tpu.models import TransformerConfig

    compute = jnp.float32
    if args.bf16:
        compute = jnp.bfloat16
    elif args.fp16:
        compute = jnp.float16
    return TransformerConfig(
        num_layers=args.num_layers,
        hidden_size=args.hidden_size,
        num_attention_heads=args.num_attention_heads,
        num_query_groups=args.num_query_groups,
        ffn_hidden_size=args.ffn_hidden_size,
        vocab_size=args.vocab_size,
        max_position_embeddings=args.max_position_embeddings,
        hidden_dropout=args.hidden_dropout,
        attention_dropout=args.attention_dropout,
        layernorm_epsilon=args.layernorm_epsilon,
        init_method_std=args.init_method_std,
        position_embedding_type=args.position_embedding_type,
        rotary_percent=args.rotary_percent,
        rope_theta=args.rotary_base,
        normalization=args.normalization,
        activation=args.activation,
        sliding_window=args.sliding_window,
        sequence_parallel=args.sequence_parallel,
        context_parallel_method=(
            args.context_parallel_method
            if args.context_parallel_size > 1 else None),
        num_moe_experts=args.num_experts,
        moe_top_k=args.moe_router_topk,
        moe_capacity_factor=args.moe_capacity_factor,
        moe_aux_loss_weight=args.moe_aux_loss_coeff,
        moe_expert_axis=args.moe_expert_axis,
        recompute=args.recompute,
        compute_dtype=compute,
    )
