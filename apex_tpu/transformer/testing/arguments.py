"""Megatron-style global arguments — the COMPLETE reference surface.

Counterpart of ``apex/transformer/testing/arguments.py`` (977 LoC of
Megatron argparse). Every one of the reference's 171 flags is accepted
here and carries an explicit disposition in :data:`REFERENCE_DISPOSITIONS`
— ``wired`` (drives framework behavior or a validated derivation) or
``inert`` (accepted for script compatibility with the platform reason
recorded; using one emits a single warning naming it). ``parse_args``
performs the reference's derived-value post-processing (required-arg and
divisibility checks, batch consistency, deprecated-alias mapping,
recompute-granularity mapping, padded vocab, virtual-pipeline derivation).

The reference file itself is a configuration CONTRACT (its consumers live
in Megatron's trainer, not in apex); parity here means: same flags, same
derivations and validations, explicit per-flag status — no silent
omissions (VERDICT r2 item 5).
"""

from __future__ import annotations

import argparse
import warnings
from typing import Callable, Dict, Optional, Tuple

__all__ = ["parse_args", "core_transformer_config_from_args",
           "REFERENCE_DISPOSITIONS"]

# --------------------------------------------------------------------------
# Disposition registry: EVERY flag of the reference arguments.py, mapped to
# ("wired" | "inert", note). "wired" = consumed by this framework (model /
# mesh / precision / schedule / data pipeline / checkpoint / derivation);
# "inert" = parsed and recorded for script compatibility, with the reason
# it has no TPU-side effect. This table IS the parity checklist.
# --------------------------------------------------------------------------

_W = "wired"
_I = "inert"

REFERENCE_DISPOSITIONS: Dict[str, Tuple[str, str]] = {
    # ---- model shape ----
    "--num-layers": (_W, "TransformerConfig.num_layers"),
    "--hidden-size": (_W, "TransformerConfig.hidden_size"),
    "--num-attention-heads": (_W, "TransformerConfig.num_attention_heads"),
    "--kv-channels": (_W, "validated: head_dim is hidden/heads; a "
                          "conflicting override is rejected"),
    "--ffn-hidden-size": (_W, "TransformerConfig.ffn_hidden_size"),
    "--seq-length": (_W, "training sequence length (validated against "
                         "max-position-embeddings)"),
    "--encoder-seq-length": (_W, "encoder length (enc-dec models); "
                                 "defaults from --seq-length"),
    "--decoder-seq-length": (_W, "decoder length (enc-dec models)"),
    "--max-position-embeddings": (_W, "TransformerConfig"
                                      ".max_position_embeddings"),
    "--make-vocab-size-divisible-by": (_W, "derives args.padded_vocab_size"
                                           " (TP-friendly padding)"),
    "--layernorm-epsilon": (_W, "TransformerConfig.layernorm_epsilon"),
    "--hidden-dropout": (_W, "TransformerConfig.hidden_dropout"),
    "--attention-dropout": (_W, "TransformerConfig.attention_dropout"),
    "--init-method-std": (_W, "TransformerConfig.init_method_std"),
    "--init-method-xavier-uniform": (_I, "normal init only; xavier was a "
                                         "Megatron-vision option"),
    "--apply-residual-connection-post-layernorm": (
        _I, "pre-LN architecture only (the reference's standalone LM also "
            "defaults pre-LN)"),
    "--openai-gelu": (_I, "tanh-approx gelu is the default; exact-erf gelu "
                          "available via --activation"),
    "--onnx-safe": (_I, "no ONNX export path on TPU (XLA is the compiler)"),
    "--fp32-residual-connection": (_W, "residual adds accumulate fp32 when "
                                       "set (amp policy)"),
    "--attention-softmax-in-fp32": (_I, "flash-attention softmax always "
                                        "accumulates fp32 (kernel "
                                        "invariant, not a flag)"),
    "--no-query-key-layer-scaling": (_I, "1/sqrt(d) scaling only; QK "
                                         "layer-scaling was an fp16-"
                                         "overflow workaround the bf16 "
                                         "default makes moot"),
    "--num-experts": (_W, "TransformerConfig.num_moe_experts (SwitchMLP)"),
    # ---- parallel layout ----
    "--tensor-model-parallel-size": (_W, "mesh tensor axis"),
    "--pipeline-model-parallel-size": (_W, "mesh pipeline axis"),
    "--model-parallel-size": (_W, "deprecated alias of "
                                  "--tensor-model-parallel-size (reference "
                                  "semantics)"),
    "--pipeline-model-parallel-split-rank": (
        _W, "initialize_model_parallel(pipeline_model_parallel_split_rank=) "
            "-> models.PipelinedEncoderDecoder two-section 1F1B pipeline"),
    "--num-layers-per-virtual-pipeline-stage": (
        _W, "derives virtual_pipeline_model_parallel_size"),
    "--sequence-parallel": (_W, "TransformerConfig.sequence_parallel"),
    "--standalone-embedding-stage": (_I, "embedding is replicated across "
                                         "stages with psum'd grads (no "
                                         "dedicated stage-0 needed)"),
    "--distributed-backend": (_I, "XLA collectives over ICI/DCN; there is "
                                  "no nccl/gloo choice"),
    "--no-async-tensor-model-parallel-allreduce": (
        _I, "XLA schedules collective/compute overlap; no manual toggle"),
    "--no-scatter-gather-tensors-in-pipeline": (
        _I, "pipeline comm is ppermute on SP-sized shards already"),
    "--use-cpu-initialization": (_I, "init runs wherever jax.jit places it;"
                                     " params materialize sharded"),
    "--lazy-mpu-init": (_I, "mesh construction is explicit "
                            "(initialize_model_parallel); nothing to defer"),
    "--cpu-offload": (_I, "no host-offload path; HBM-resident training"),
    "--empty-unused-memory-level": (_I, "XLA owns device memory; no manual "
                                        "cache emptying"),
    # ---- training schedule ----
    "--micro-batch-size": (_W, "microbatch calculator"),
    "--batch-size": (_W, "deprecated alias of --micro-batch-size"),
    "--global-batch-size": (_W, "microbatch calculator"),
    "--rampup-batch-size": (_W, "RampupBatchsizeNumMicroBatches"),
    "--train-iters": (_W, "host training loop length"),
    "--train-samples": (_W, "sample-based loop length (exclusive with "
                            "--train-iters)"),
    "--log-interval": (_W, "host loop logging cadence"),
    "--exit-interval": (_W, "host loop early-exit iteration"),
    "--exit-duration-in-mins": (_W, "host loop wall-clock exit"),
    "--eval-interval": (_W, "host loop eval cadence"),
    "--eval-iters": (_W, "host loop eval length"),
    "--optimizer": (_W, "adam|lamb|sgd -> Fused* optimizers"),
    "--lr": (_W, "optimizer lr"),
    "--min-lr": (_W, "lr schedule floor"),
    "--lr-decay-style": (_W, "lr schedule shape"),
    "--lr-decay-iters": (_W, "lr schedule span (iterations)"),
    "--lr-decay-samples": (_W, "lr schedule span (samples)"),
    "--lr-warmup-fraction": (_W, "warmup as fraction of decay span"),
    "--lr-warmup-iters": (_W, "warmup iterations"),
    "--lr-warmup-samples": (_W, "warmup samples"),
    "--warmup": (_W, "deprecated alias: old percentage form of "
                     "--lr-warmup-fraction"),
    "--override-lr-scheduler": (_W, "checkpoint-resume scheduler policy"),
    "--use-checkpoint-lr-scheduler": (_W, "checkpoint-resume scheduler "
                                          "policy"),
    "--adam-beta1": (_W, "FusedAdam/LAMB beta1"),
    "--adam-beta2": (_W, "FusedAdam/LAMB beta2"),
    "--adam-eps": (_W, "FusedAdam/LAMB eps"),
    "--sgd-momentum": (_W, "FusedSGD momentum"),
    "--weight-decay": (_W, "optimizer weight decay"),
    "--start-weight-decay": (_W, "weight-decay schedule start"),
    "--end-weight-decay": (_W, "weight-decay schedule end"),
    "--weight-decay-incr-style": (_W, "weight-decay schedule shape"),
    "--clip-grad": (_W, "fused global-norm clip (contrib.clip_grad)"),
    "--seed": (_W, "jax.random.PRNGKey seed"),
    "--head-lr-mult": (_I, "vision-head lr multiplier (Megatron vision "
                           "trainer concern)"),
    # ---- precision ----
    "--fp16": (_W, "compute dtype fp16 + dynamic loss scaling"),
    "--bf16": (_W, "compute dtype bf16 (TPU-native default)"),
    "--loss-scale": (_W, "static loss scale (None = dynamic under fp16)"),
    "--initial-loss-scale": (_W, "dynamic scaler init"),
    "--min-loss-scale": (_W, "dynamic scaler floor"),
    "--loss-scale-window": (_W, "dynamic scaler growth window"),
    "--hysteresis": (_W, "dynamic scaler hysteresis"),
    "--fp16-lm-cross-entropy": (_I, "vocab-parallel CE always upcasts to "
                                    "fp32 (Megatron kernel semantics); "
                                    "fp16 CE saved no memory here"),
    "--accumulate-allreduce-grads-in-fp32": (
        _W, "DDP/ZeRO fp32 grad accumulation flag"),
    # ---- recompute / checkpointing-of-activations ----
    "--checkpoint-activations": (_W, "deprecated alias: recompute-"
                                     "granularity=full"),
    "--recompute-activations": (_W, "alias: recompute-granularity="
                                    "selective"),
    "--recompute-granularity": (_W, "full -> TransformerConfig.recompute="
                                    "True; selective -> 'selective' "
                                    "(checkpoint policy)"),
    "--recompute-method": (_I, "uniform/block chunking: the per-layer scan "
                               "remat is uniform by construction"),
    "--recompute-num-layers": (_I, "per-layer remat granularity is the "
                                   "scan body"),
    "--distribute-saved-activations": (_I, "saved activations are already "
                                           "SP/TP-sharded by GSPMD"),
    # ---- kernel-fusion toggles (XLA or Pallas-dispatch concerns) ----
    "--no-masked-softmax-fusion": (_I, "Pallas kernel dispatch is "
                                       "APEX_TPU_FORCE_PALLAS, not argv"),
    "--no-bias-gelu-fusion": (_I, "XLA fuses bias+gelu unconditionally"),
    "--no-bias-dropout-fusion": (_I, "XLA fuses bias+dropout "
                                     "unconditionally"),
    "--no-persist-layer-norm": (_I, "Pallas LN has no persistent-kernel "
                                    "variant distinction"),
    "--no-gradient-accumulation-fusion": (_I, "wgrad accumulation fusion "
                                              "is XLA buffer donation"),
    # ---- DDP / memory ----
    "--no-contiguous-buffers-in-local-ddp": (_I, "XLA owns buffer layout; "
                                                 "no local-DDP buffer "
                                                 "mode"),
    # ---- model/optimizer checkpointing ----
    "--save": (_W, "orbax checkpoint dir (apex_tpu.checkpoint)"),
    "--save-interval": (_W, "host loop save cadence"),
    "--no-save-optim": (_W, "checkpoint content policy"),
    "--no-save-rng": (_W, "checkpoint content policy"),
    "--load": (_W, "orbax restore dir"),
    "--no-load-optim": (_W, "restore content policy"),
    "--no-load-rng": (_W, "restore content policy"),
    "--finetune": (_W, "restore policy: reset iteration/optimizer"),
    "--adlr-autoresume": (_W, "autoresume hook (pipeline_parallel.utils)"),
    "--adlr-autoresume-interval": (_W, "autoresume poll cadence"),
    # ---- data pipeline ----
    "--data-path": (_W, "data.pipeline dataset path(s)"),
    "--split": (_W, "train/val/test split string"),
    "--vocab-file": (_W, "tokenizer vocab (data pipeline)"),
    "--merge-file": (_W, "BPE merges (data pipeline)"),
    "--vocab-extra-ids": (_W, "extra sentinel tokens (T5-style)"),
    "--tokenizer-type": (_W, "data pipeline tokenizer selection"),
    "--data-impl": (_I, "no mmap/lazy indexed-dataset variants; the data "
                        "pipeline streams host arrays"),
    "--mmap-warmup": (_I, "no mmap datasets"),
    "--num-workers": (_W, "host data-loader worker threads"),
    "--dataloader-type": (_W, "single|cyclic sampler selection "
                              "(_batchsampler)"),
    "--no-data-sharding": (_W, "DP-sharded vs replicated sampling"),
    "--reset-position-ids": (_W, "get_ltor_masks_and_position_ids"),
    "--reset-attention-mask": (_W, "get_ltor_masks_and_position_ids"),
    "--eod-mask-loss": (_W, "get_ltor_masks_and_position_ids"),
    "--short-seq-prob": (_W, "BERT-style data sampling"),
    "--mask-prob": (_W, "BERT-style masking rate"),
    "--sample-rate": (_I, "vision dataset subsampling (Megatron vision "
                          "data tooling)"),
    "--mask-factor": (_I, "vision inpainting data tooling"),
    "--mask-type": (_I, "vision inpainting data tooling"),
    "--classes-fraction": (_I, "vision dataset subsetting tooling"),
    "--data-per-class-fraction": (_I, "vision dataset subsetting tooling"),
    # ---- logging / tensorboard ----
    "--tensorboard-dir": (_I, "no tensorboard writer; metrics go through "
                              "utils.logging / host loop"),
    "--tensorboard-log-interval": (_I, "no tensorboard writer"),
    "--tensorboard-queue-size": (_I, "no tensorboard writer"),
    "--log-batch-size-to-tensorboard": (_I, "no tensorboard writer"),
    "--log-memory-to-tensorboard": (_I, "no tensorboard writer"),
    "--log-timers-to-tensorboard": (_I, "no tensorboard writer"),
    "--log-validation-ppl-to-tensorboard": (_I, "no tensorboard writer"),
    "--log-world-size-to-tensorboard": (_I, "no tensorboard writer"),
    "--no-log-learnig-rate-to-tensorboard": (_I, "no tensorboard writer"),
    "--no-log-loss-scale-to-tensorboard": (_I, "no tensorboard writer"),
    "--log-params-norm": (_W, "calc_params_l2_norm debug dump"),
    "--log-num-zeros-in-grad": (_W, "grad-zeros debug metric"),
    # ---- inference ----
    "--inference-batch-times-seqlen-threshold": (
        _I, "pipeline inference micro-batching heuristic; generation here "
            "is the KV-cache decode path"),
    # ---- downstream-task tooling (BERT/ICT/retriever/vision/dino) ----
    "--bert-load": (_W, "BERT checkpoint for downstream init"),
    "--bert-no-binary-head": (_W, "BertModel(add_binary_head=False)"),
    "--ict-head-size": (_I, "ICT/REALM retrieval tooling out of scope"),
    "--ict-load": (_I, "ICT/REALM retrieval tooling out of scope"),
    "--biencoder-projection-dim": (_I, "REALM biencoder tooling"),
    "--biencoder-shared-query-context-model": (_I, "REALM biencoder "
                                                   "tooling"),
    "--block-data-path": (_I, "REALM block index tooling"),
    "--embedding-path": (_I, "REALM embedding index tooling"),
    "--indexer-batch-size": (_I, "REALM indexer tooling"),
    "--indexer-log-interval": (_I, "REALM indexer tooling"),
    "--titles-data-path": (_I, "REALM data tooling"),
    "--evidence-data-path": (_I, "REALM data tooling"),
    "--query-in-block-prob": (_I, "ICT data sampling"),
    "--use-one-sent-docs": (_I, "ICT data sampling"),
    "--retriever-report-topk-accuracies": (_I, "retriever eval tooling"),
    "--retriever-score-scaling": (_I, "retriever eval tooling"),
    "--retriever-seq-length": (_I, "retriever eval tooling"),
    "--img-h": (_W, "ViTConfig image size (h)"),
    "--img-w": (_W, "ViTConfig image size (w)"),
    "--num-channels": (_W, "ViTConfig.channels"),
    "--num-classes": (_W, "ViTConfig.num_classes"),
    "--patch-dim": (_W, "ViTConfig.patch_size"),
    "--vision-backbone-type": (_I, "ViT only; no swin/mit backbones"),
    "--vision-pretraining": (_I, "vision pretraining trainer out of scope"),
    "--vision-pretraining-type": (_I, "vision pretraining trainer"),
    "--swin-backbone-type": (_I, "no swin backbone"),
    "--iter-per-epoch": (_I, "vision trainer epoch accounting"),
    "--dino-bottleneck-size": (_I, "DINO self-supervision tooling"),
    "--dino-freeze-last-layer": (_I, "DINO self-supervision tooling"),
    "--dino-head-hidden-size": (_I, "DINO self-supervision tooling"),
    "--dino-local-crops-number": (_I, "DINO self-supervision tooling"),
    "--dino-local-img-size": (_I, "DINO self-supervision tooling"),
    "--dino-norm-last-layer": (_I, "DINO self-supervision tooling"),
    "--dino-teacher-temp": (_I, "DINO self-supervision tooling"),
    "--dino-warmup-teacher-temp": (_I, "DINO self-supervision tooling"),
    "--dino-warmup-teacher-temp-epochs": (_I, "DINO self-supervision "
                                              "tooling"),
}

# flags this framework adds beyond the reference surface (not in the
# disposition table, which tracks reference parity only)
_EXTENSION_FLAGS = """--num-query-groups --vocab-size
--position-embedding-type --rotary-percent --rotary-base --normalization
--swiglu --activation --sliding-window --moe-router-topk
--moe-capacity-factor --moe-aux-loss-coeff --moe-expert-axis
--context-parallel-size --context-parallel-method
--virtual-pipeline-model-parallel-size --num-slices --world-size
--use-distributed-optimizer --fp8 --fp8-margin --fp8-amax-history-len
--scan-unroll""".split()


def _str2bool(v: str) -> bool:
    """argparse ``type=`` converter for tri-state bool flags: the reference
    declares these ``type=bool``, under which an explicit ``--onnx-safe
    False`` parses as True (``bool('False')``); both flags are inert here,
    so fix the quirk rather than mirroring it (ADVICE r3)."""
    if v.lower() in ("true", "1", "yes", "y"):
        return True
    if v.lower() in ("false", "0", "no", "n"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {v!r}")


def parse_args(extra_args_provider: Optional[Callable] = None,
               defaults: Optional[Dict] = None,
               ignore_unknown_args: bool = False,
               args=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="apex_tpu Megatron-style arguments",
        allow_abbrev=False)

    g = parser.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=2)
    g.add_argument("--hidden-size", type=int, default=128)
    g.add_argument("--num-attention-heads", type=int, default=8)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--num-query-groups", type=int, default=None,
                   help="GQA/MQA K/V head groups (None = MHA)")
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--seq-length", type=int, default=128)
    g.add_argument("--encoder-seq-length", type=int, default=None)
    g.add_argument("--decoder-seq-length", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=128)
    g.add_argument("--vocab-size", type=int, default=4096)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--init-method-xavier-uniform", action="store_true")
    g.add_argument("--apply-residual-connection-post-layernorm",
                   action="store_true")
    g.add_argument("--openai-gelu", action="store_true")
    g.add_argument("--onnx-safe", type=_str2bool, default=None)
    g.add_argument("--fp32-residual-connection", action="store_true")
    g.add_argument("--attention-softmax-in-fp32", action="store_true")
    g.add_argument("--no-query-key-layer-scaling", action="store_false",
                   dest="apply_query_key_layer_scaling")
    g.add_argument("--position-embedding-type", type=str, default="learned",
                   choices=["learned", "rope", "none"])
    g.add_argument("--rotary-percent", type=float, default=1.0)
    g.add_argument("--rotary-base", type=float, default=10000.0,
                   help="rope theta")
    g.add_argument("--normalization", type=str, default="layernorm",
                   choices=["layernorm", "rmsnorm"])
    g.add_argument("--swiglu", action="store_true",
                   help="gated SiLU MLP (sets activation=swiglu)")
    g.add_argument("--activation", type=str, default=None,
                   help="explicit MLP activation (overrides --swiglu)")
    g.add_argument("--sliding-window", type=int, default=None,
                   help="causal local-attention span (Mistral-style)")

    g = parser.add_argument_group("moe")
    g.add_argument("--num-experts", type=int, default=None,
                   help="SwitchMLP experts per layer (None = dense)")
    g.add_argument("--moe-router-topk", type=int, default=1)
    g.add_argument("--moe-capacity-factor", type=float, default=1.25)
    g.add_argument("--moe-aux-loss-coeff", type=float, default=1e-2)
    g.add_argument("--moe-expert-axis", type=str, default=None,
                   help="mesh axis for expert parallelism (e.g. 'data')")

    g = parser.add_argument_group("parallel")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--model-parallel-size", type=int, default=None,
                   help="deprecated alias of --tensor-model-parallel-size")
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-split-rank", type=int,
                   default=None)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--context-parallel-method", type=str, default=None,
                   choices=[None, "ring", "ulysses"])
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                   default=None)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--standalone-embedding-stage", action="store_true")
    g.add_argument("--distributed-backend", type=str, default="xla")
    g.add_argument("--no-async-tensor-model-parallel-allreduce",
                   action="store_true")
    g.add_argument("--no-scatter-gather-tensors-in-pipeline",
                   action="store_false",
                   dest="scatter_gather_tensors_in_pipeline")
    g.add_argument("--use-cpu-initialization", action="store_true")
    g.add_argument("--lazy-mpu-init", type=_str2bool, default=None)
    g.add_argument("--cpu-offload", action="store_true")
    g.add_argument("--empty-unused-memory-level", type=int, default=0)
    g.add_argument("--num-slices", type=int, default=1,
                   help="multi-slice (DCN) topology: data axis DCN-major")
    g.add_argument("--world-size", type=int, default=None,
                   help="defaults to jax.device_count()")

    g = parser.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--batch-size", type=int, default=None,
                   help="deprecated alias of --micro-batch-size")
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", type=int, nargs=3, default=None,
                   metavar=("START", "INCR", "SAMPLES"))
    g.add_argument("--train-iters", type=int, default=10)
    g.add_argument("--train-samples", type=int, default=None)
    g.add_argument("--log-interval", type=int, default=10)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--exit-duration-in-mins", type=int, default=None)
    g.add_argument("--eval-interval", type=int, default=1000)
    g.add_argument("--eval-iters", type=int, default=100)
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "lamb", "sgd"])
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--min-lr", type=float, default=0.0)
    g.add_argument("--lr-decay-style", type=str, default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-decay-samples", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--lr-warmup-iters", type=int, default=0)
    g.add_argument("--lr-warmup-samples", type=int, default=0)
    g.add_argument("--warmup", type=int, default=None,
                   help="deprecated: old percentage form of "
                        "--lr-warmup-fraction")
    g.add_argument("--override-lr-scheduler", action="store_true")
    g.add_argument("--use-checkpoint-lr-scheduler", action="store_true")
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--start-weight-decay", type=float, default=None)
    g.add_argument("--end-weight-decay", type=float, default=None)
    g.add_argument("--weight-decay-incr-style", type=str,
                   default="constant", choices=["constant", "linear",
                                                "cosine"])
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--use-distributed-optimizer", action="store_true",
                   help="ZeRO-sharded optimizer state over the data axis")
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--head-lr-mult", type=float, default=1.0)

    g = parser.add_argument_group("precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None,
                   help="static loss scale (None = dynamic when fp16)")
    g.add_argument("--initial-loss-scale", type=float, default=2.0 ** 32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--fp16-lm-cross-entropy", action="store_true")
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    g.add_argument("--fp8", action="store_true",
                   help="fp8 delayed-scaling hooks (amp.fp8)")
    g.add_argument("--fp8-margin", type=int, default=0)
    g.add_argument("--fp8-amax-history-len", type=int, default=16)

    g = parser.add_argument_group("recompute")
    g.add_argument("--checkpoint-activations", action="store_true",
                   help="deprecated: recompute-granularity=full")
    g.add_argument("--recompute-activations", action="store_true",
                   help="alias: recompute-granularity=selective")
    g.add_argument("--recompute-granularity", type=str, default=None,
                   choices=[None, "full", "selective"])
    g.add_argument("--recompute-method", type=str, default=None,
                   choices=[None, "uniform", "block"])
    g.add_argument("--recompute-num-layers", type=int, default=1)
    g.add_argument("--distribute-saved-activations", action="store_true")
    g.add_argument("--scan-unroll", type=int, default=1,
                   help="layer-scan unroll factor (TPU scheduling knob)")

    g = parser.add_argument_group("fusion (inert: XLA/Pallas dispatch)")
    g.add_argument("--no-masked-softmax-fusion", action="store_false",
                   dest="masked_softmax_fusion")
    g.add_argument("--no-bias-gelu-fusion", action="store_false",
                   dest="bias_gelu_fusion")
    g.add_argument("--no-bias-dropout-fusion", action="store_false",
                   dest="bias_dropout_fusion")
    g.add_argument("--no-persist-layer-norm", action="store_true")
    g.add_argument("--no-gradient-accumulation-fusion",
                   action="store_false", dest="gradient_accumulation_fusion")
    g.add_argument("--no-contiguous-buffers-in-local-ddp",
                   action="store_false",
                   dest="use_contiguous_buffers_in_local_ddp")

    g = parser.add_argument_group("checkpointing")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--no-save-optim", action="store_true", default=None)
    g.add_argument("--no-save-rng", action="store_true", default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--no-load-optim", action="store_true", default=None)
    g.add_argument("--no-load-rng", action="store_true", default=None)
    g.add_argument("--finetune", action="store_true")
    g.add_argument("--adlr-autoresume", action="store_true")
    g.add_argument("--adlr-autoresume-interval", type=int, default=1000)

    g = parser.add_argument_group("data")
    g.add_argument("--data-path", nargs="*", default=None)
    g.add_argument("--split", type=str, default="969, 30, 1")
    g.add_argument("--vocab-file", type=str, default=None)
    g.add_argument("--merge-file", type=str, default=None)
    g.add_argument("--vocab-extra-ids", type=int, default=0)
    g.add_argument("--tokenizer-type", type=str, default=None)
    g.add_argument("--data-impl", type=str, default="infer")
    g.add_argument("--mmap-warmup", action="store_true")
    g.add_argument("--num-workers", type=int, default=2)
    g.add_argument("--dataloader-type", type=str, default=None,
                   choices=[None, "single", "cyclic"])
    g.add_argument("--no-data-sharding", action="store_false",
                   dest="data_sharding")
    g.add_argument("--reset-position-ids", action="store_true")
    g.add_argument("--reset-attention-mask", action="store_true")
    g.add_argument("--eod-mask-loss", action="store_true")
    g.add_argument("--short-seq-prob", type=float, default=0.1)
    g.add_argument("--mask-prob", type=float, default=0.15)
    g.add_argument("--sample-rate", type=float, default=1.0)
    g.add_argument("--mask-factor", type=float, default=1.0)
    g.add_argument("--mask-type", type=str, default="random")
    g.add_argument("--classes-fraction", type=float, default=1.0)
    g.add_argument("--data-per-class-fraction", type=float, default=1.0)

    g = parser.add_argument_group("logging (tensorboard flags inert)")
    g.add_argument("--tensorboard-dir", type=str, default=None)
    g.add_argument("--tensorboard-log-interval", type=int, default=1)
    g.add_argument("--tensorboard-queue-size", type=int, default=1000)
    g.add_argument("--log-batch-size-to-tensorboard", action="store_true")
    g.add_argument("--log-memory-to-tensorboard", action="store_true")
    g.add_argument("--log-timers-to-tensorboard", action="store_true")
    g.add_argument("--log-validation-ppl-to-tensorboard",
                   action="store_true")
    g.add_argument("--log-world-size-to-tensorboard", action="store_true")
    g.add_argument("--no-log-learnig-rate-to-tensorboard",
                   action="store_false", dest="log_learning_rate_to_tb")
    g.add_argument("--no-log-loss-scale-to-tensorboard",
                   action="store_false", dest="log_loss_scale_to_tb")
    g.add_argument("--log-params-norm", action="store_true")
    g.add_argument("--log-num-zeros-in-grad", action="store_true")

    g = parser.add_argument_group("inference")
    g.add_argument("--inference-batch-times-seqlen-threshold", type=int,
                   default=512)

    g = parser.add_argument_group("downstream-task tooling (inert)")
    g.add_argument("--bert-load", type=str, default=None)
    g.add_argument("--bert-no-binary-head", action="store_false",
                   dest="bert_binary_head")
    g.add_argument("--ict-head-size", type=int, default=None)
    g.add_argument("--ict-load", type=str, default=None)
    g.add_argument("--biencoder-projection-dim", type=int, default=0)
    g.add_argument("--biencoder-shared-query-context-model",
                   action="store_true")
    g.add_argument("--block-data-path", type=str, default=None)
    g.add_argument("--embedding-path", type=str, default=None)
    g.add_argument("--indexer-batch-size", type=int, default=128)
    g.add_argument("--indexer-log-interval", type=int, default=1000)
    g.add_argument("--titles-data-path", type=str, default=None)
    g.add_argument("--evidence-data-path", type=str, default=None)
    g.add_argument("--query-in-block-prob", type=float, default=0.1)
    g.add_argument("--use-one-sent-docs", action="store_true")
    g.add_argument("--retriever-report-topk-accuracies", nargs="+",
                   type=int, default=[])
    g.add_argument("--retriever-score-scaling", action="store_true")
    g.add_argument("--retriever-seq-length", type=int, default=256)
    g.add_argument("--img-h", type=int, default=224)
    g.add_argument("--img-w", type=int, default=224)
    g.add_argument("--num-channels", type=int, default=3)
    g.add_argument("--num-classes", type=int, default=1000)
    g.add_argument("--patch-dim", type=int, default=16)
    g.add_argument("--vision-backbone-type", type=str, default="vit")
    g.add_argument("--vision-pretraining", action="store_true")
    g.add_argument("--vision-pretraining-type", type=str,
                   default="classify")
    g.add_argument("--swin-backbone-type", type=str, default="tiny")
    g.add_argument("--iter-per-epoch", type=int, default=1250)
    g.add_argument("--dino-bottleneck-size", type=int, default=256)
    g.add_argument("--dino-freeze-last-layer", type=float, default=1)
    g.add_argument("--dino-head-hidden-size", type=int, default=2048)
    g.add_argument("--dino-local-crops-number", type=int, default=10)
    g.add_argument("--dino-local-img-size", type=int, default=96)
    g.add_argument("--dino-norm-last-layer", action="store_true")
    g.add_argument("--dino-teacher-temp", type=float, default=0.07)
    g.add_argument("--dino-warmup-teacher-temp", type=float, default=0.04)
    g.add_argument("--dino-warmup-teacher-temp-epochs", type=int,
                   default=30)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        ns, _ = parser.parse_known_args(args)
    else:
        ns = parser.parse_args(args)

    # one warning naming any INERT reference flags the caller actually set
    import sys
    argv = list(args) if args is not None else sys.argv[1:]
    used_inert = sorted(
        f for f, (status, _) in REFERENCE_DISPOSITIONS.items()
        if status == _I and any(a == f or a.startswith(f + "=")
                                for a in argv))
    ns.inert_flags_set = used_inert
    if used_inert:
        warnings.warn(
            "flags accepted for Megatron-script compatibility but inert on "
            f"this platform: {', '.join(used_inert)} (reasons: "
            "apex_tpu.transformer.testing.arguments.REFERENCE_DISPOSITIONS"
            " / PARITY.md)", stacklevel=2)

    for k, v in (defaults or {}).items():
        key = k.replace("-", "_")
        cur = getattr(ns, key, None)
        # identity checks: unset options (None) and un-passed store_true
        # flags (False) take the default; explicit numeric zeros do not
        if cur is None or cur is False:
            setattr(ns, key, v)

    # ---- deprecated aliases (reference semantics) ----
    if ns.model_parallel_size is not None:
        ns.tensor_model_parallel_size = ns.model_parallel_size
    if ns.batch_size is not None:
        ns.micro_batch_size = ns.batch_size
    if ns.warmup is not None:
        if ns.lr_warmup_fraction is not None:
            raise ValueError("--warmup (deprecated) and "
                             "--lr-warmup-fraction are exclusive")
        ns.lr_warmup_fraction = ns.warmup / 100.0
    if ns.checkpoint_activations:
        ns.recompute_granularity = "full"
    elif ns.recompute_activations and ns.recompute_granularity is None:
        ns.recompute_granularity = "selective"

    # ---- derived values + validation (reference post-processing) ----
    if ns.world_size is None:
        import jax
        ns.world_size = jax.device_count()
    mp = (ns.tensor_model_parallel_size * ns.pipeline_model_parallel_size
          * ns.context_parallel_size)
    if ns.world_size % mp:
        raise ValueError(
            f"world size {ns.world_size} not divisible by model-parallel "
            f"size {mp}")
    ns.data_parallel_size = ns.world_size // mp
    if ns.global_batch_size is None:
        ns.global_batch_size = ns.micro_batch_size * ns.data_parallel_size
    if ns.global_batch_size % (ns.micro_batch_size * ns.data_parallel_size):
        raise ValueError(
            f"global batch {ns.global_batch_size} not divisible by "
            f"micro-batch {ns.micro_batch_size} x dp {ns.data_parallel_size}")
    if ns.ffn_hidden_size is None:
        ns.ffn_hidden_size = 4 * ns.hidden_size
    if ns.hidden_size % ns.num_attention_heads:
        raise ValueError(
            f"hidden size {ns.hidden_size} not divisible by "
            f"num_attention_heads {ns.num_attention_heads}")
    if (ns.kv_channels is not None
            and ns.kv_channels != ns.hidden_size // ns.num_attention_heads):
        raise ValueError(
            f"kv-channels ({ns.kv_channels}) must equal hidden/heads "
            f"({ns.hidden_size // ns.num_attention_heads}): decoupled head "
            "width is not supported")
    if ns.seq_length > ns.max_position_embeddings:
        raise ValueError(
            f"seq-length {ns.seq_length} exceeds max-position-embeddings "
            f"{ns.max_position_embeddings}")
    if ns.encoder_seq_length is None:
        ns.encoder_seq_length = ns.seq_length
    if ns.train_samples is not None and ns.train_iters is not None:
        # reference: iteration-based and sample-based training exclusive;
        # our default train_iters=10 yields -> samples win when given
        ns.train_iters = None
    if ns.lr_warmup_fraction is not None and ns.lr_warmup_iters:
        raise ValueError("--lr-warmup-fraction and --lr-warmup-iters are "
                         "exclusive")
    if ns.fp16 and ns.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    if ns.start_weight_decay is not None or ns.end_weight_decay is not None:
        if ns.start_weight_decay is None or ns.end_weight_decay is None:
            raise ValueError("--start-weight-decay and --end-weight-decay "
                             "must be given together")
        if ns.start_weight_decay < 0:
            raise ValueError("start-weight-decay must be >= 0")
    else:
        ns.start_weight_decay = ns.weight_decay
        ns.end_weight_decay = ns.weight_decay
    if ns.activation is None:
        ns.activation = "swiglu" if ns.swiglu else "gelu"
    if (ns.num_query_groups is not None
            and ns.num_attention_heads % ns.num_query_groups):
        raise ValueError(
            f"num_attention_heads ({ns.num_attention_heads}) must be "
            f"divisible by num_query_groups ({ns.num_query_groups})")
    if ns.num_experts is not None and ns.moe_expert_axis == "data":
        ep = ns.data_parallel_size
        if ep > 1 and ns.num_experts % ep:
            raise ValueError(
                f"num_experts ({ns.num_experts}) must divide evenly over "
                f"the expert axis (data, size {ep})")
    if ns.context_parallel_size > 1 and ns.context_parallel_method is None:
        ns.context_parallel_method = "ring"
    # virtual pipeline: explicit size wins; else derive from per-stage layers
    if (ns.num_layers_per_virtual_pipeline_stage is not None
            and ns.virtual_pipeline_model_parallel_size is None):
        per = (ns.pipeline_model_parallel_size
               * ns.num_layers_per_virtual_pipeline_stage)
        if ns.num_layers % per:
            raise ValueError(
                f"num_layers ({ns.num_layers}) must divide into pp "
                f"({ns.pipeline_model_parallel_size}) x layers-per-virtual-"
                f"stage ({ns.num_layers_per_virtual_pipeline_stage})")
        ns.virtual_pipeline_model_parallel_size = ns.num_layers // per
    # padded vocab (reference _vocab_size_with_padding, TP-friendly)
    div = ns.make_vocab_size_divisible_by * ns.tensor_model_parallel_size
    ns.padded_vocab_size = ((ns.vocab_size + div - 1) // div) * div
    # recompute mapping into the model config
    ns.recompute = {None: False, "full": True,
                    "selective": "selective"}[ns.recompute_granularity]
    ns.params_dtype = "float32"
    if ns.bf16:
        ns.params_dtype = "bfloat16"
    return ns


def core_transformer_config_from_args(args):
    """Build a :class:`apex_tpu.models.TransformerConfig` from parsed args."""
    import jax.numpy as jnp

    from apex_tpu.models import TransformerConfig

    compute = jnp.float32
    if args.bf16:
        compute = jnp.bfloat16
    elif args.fp16:
        compute = jnp.float16
    return TransformerConfig(
        num_layers=args.num_layers,
        hidden_size=args.hidden_size,
        num_attention_heads=args.num_attention_heads,
        num_query_groups=args.num_query_groups,
        ffn_hidden_size=args.ffn_hidden_size,
        vocab_size=args.padded_vocab_size,
        max_position_embeddings=args.max_position_embeddings,
        hidden_dropout=args.hidden_dropout,
        attention_dropout=args.attention_dropout,
        layernorm_epsilon=args.layernorm_epsilon,
        init_method_std=args.init_method_std,
        position_embedding_type=args.position_embedding_type,
        rotary_percent=args.rotary_percent,
        rope_theta=args.rotary_base,
        normalization=args.normalization,
        activation=args.activation,
        sliding_window=args.sliding_window,
        sequence_parallel=args.sequence_parallel,
        context_parallel_method=(
            args.context_parallel_method
            if args.context_parallel_size > 1 else None),
        num_moe_experts=args.num_experts,
        moe_top_k=args.moe_router_topk,
        moe_capacity_factor=args.moe_capacity_factor,
        moe_aux_loss_weight=args.moe_aux_loss_coeff,
        moe_expert_axis=args.moe_expert_axis,
        recompute=args.recompute,
        scan_unroll=args.scan_unroll,
        compute_dtype=compute,
    )
