"""Global args singleton (counterpart of
``apex/transformer/testing/global_vars.py``): ``set_global_variables`` parses
(or accepts) args once; ``get_args`` asserts initialization like the
reference's ``_ensure_var_is_initialized``."""

from __future__ import annotations

from typing import Optional

from apex_tpu.transformer.testing.arguments import parse_args

_GLOBAL_ARGS = None


def set_global_variables(args=None, *, extra_args_provider=None,
                         defaults=None, ignore_unknown_args=False,
                         build_microbatch_calculator: bool = True):
    """Parse and install the global args (idempotent only via
    :func:`destroy_global_vars`), and build the microbatch-calculator
    singleton from them (reference
    ``global_vars.py:_build_num_microbatches_calculator``)."""
    global _GLOBAL_ARGS
    if _GLOBAL_ARGS is not None:
        raise RuntimeError("global args are already initialized")
    if args is None:
        args = parse_args(extra_args_provider=extra_args_provider,
                          defaults=defaults,
                          ignore_unknown_args=ignore_unknown_args)
    if build_microbatch_calculator:
        from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

        # setup raises if a calculator already exists (reference
        # _ensure-not-initialized semantics) — clobbering a directly
        # installed calculator here would silently change the schedule.
        # It runs BEFORE _GLOBAL_ARGS is installed so a failure leaves the
        # module fully uninitialized rather than half-set.
        pp_utils.setup_microbatch_calculator(
            rank=0,
            rampup_batch_size=args.rampup_batch_size,
            global_batch_size=args.global_batch_size,
            micro_batch_size=args.micro_batch_size,
            data_parallel_size=args.data_parallel_size)
    _GLOBAL_ARGS = args
    return args


def get_args():
    if _GLOBAL_ARGS is None:
        raise RuntimeError("global args are not initialized; call "
                           "set_global_variables() first")
    return _GLOBAL_ARGS


def get_num_microbatches() -> int:
    """Reference ``global_vars.py:40`` — delegates to the calculator
    singleton."""
    from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

    return pp_utils.get_num_microbatches()


def get_current_global_batch_size() -> Optional[int]:
    from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

    try:
        return pp_utils.get_current_global_batch_size()
    except AttributeError:
        args = get_args()
        return getattr(args, "global_batch_size", None)


def get_timers():
    """Reference ``global_vars.py:81`` — the named-timer singleton."""
    from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

    return pp_utils.get_timers()


def get_adlr_autoresume():
    """Reference ``global_vars.py:75``; None unless an autoresume hook was
    installed (SURVEY.md §5: the only failure-recovery integration point)."""
    from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

    return pp_utils.get_autoresume()


def get_tensorboard_writer():
    """Reference ``global_vars.py:69``; observability rides the library
    logger here — always None, kept for call-site parity."""
    return None


def destroy_global_vars() -> None:
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = None
    from apex_tpu.transformer.pipeline_parallel import utils as pp_utils

    pp_utils._destroy_microbatch_calculator()
