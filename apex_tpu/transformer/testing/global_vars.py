"""Global args singleton (counterpart of
``apex/transformer/testing/global_vars.py``): ``set_global_variables`` parses
(or accepts) args once; ``get_args`` asserts initialization like the
reference's ``_ensure_var_is_initialized``."""

from __future__ import annotations

from typing import Optional

from apex_tpu.transformer.testing.arguments import parse_args

_GLOBAL_ARGS = None


def set_global_variables(args=None, *, extra_args_provider=None,
                         defaults=None, ignore_unknown_args=False):
    """Parse and install the global args (idempotent only via
    :func:`destroy_global_vars`)."""
    global _GLOBAL_ARGS
    if _GLOBAL_ARGS is not None:
        raise RuntimeError("global args are already initialized")
    if args is None:
        args = parse_args(extra_args_provider=extra_args_provider,
                          defaults=defaults,
                          ignore_unknown_args=ignore_unknown_args)
    _GLOBAL_ARGS = args
    return args


def get_args():
    if _GLOBAL_ARGS is None:
        raise RuntimeError("global args are not initialized; call "
                           "set_global_variables() first")
    return _GLOBAL_ARGS


def get_current_global_batch_size() -> Optional[int]:
    args = get_args()
    return getattr(args, "global_batch_size", None)


def destroy_global_vars() -> None:
    global _GLOBAL_ARGS
    _GLOBAL_ARGS = None
