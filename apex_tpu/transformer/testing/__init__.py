"""Test-harness toolkit (counterpart of ``apex/transformer/testing``).

The reference ships a Megatron-style global-args system (``arguments.py``,
``global_vars.py``), toy-model helpers (``commons.py``), a multi-process
distributed test base (``distributed_test_base.py``), and standalone GPT/BERT
fixtures. Here the standalone models are first-class
(:mod:`apex_tpu.models`); this package provides the args system, the
commons helpers, and the virtual-mesh test base that stands in for
``MultiProcessTestCase`` on a single host (SURVEY.md §4 implication).
"""

from apex_tpu.models import BertModel as StandaloneBertModel
from apex_tpu.models import GPTModel as StandaloneGPTModel
from apex_tpu.transformer.testing.arguments import parse_args
from apex_tpu.transformer.testing.commons import (
    IdentityLayer,
    initialize_distributed,
    print_separator,
    set_random_seed,
)
from apex_tpu.transformer.testing.distributed_test_base import (
    DistributedTestBase,
)
from apex_tpu.transformer.testing.global_vars import (
    get_args,
    set_global_variables,
)

__all__ = [
    "parse_args",
    "get_args",
    "set_global_variables",
    "IdentityLayer",
    "set_random_seed",
    "initialize_distributed",
    "print_separator",
    "DistributedTestBase",
    "StandaloneGPTModel",
    "StandaloneBertModel",
]
