"""Rotary position embedding wrappers.

Counterpart of ``apex/transformer/functional/fused_rope.py:19-303`` — thin
re-exports of the fused kernels under the reference's public names.
"""

from apex_tpu.ops.rope import (
    fused_rope,
    fused_rope_2d,
    fused_rope_cached,
    fused_rope_thd,
)

__all__ = [
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
    "fused_apply_rotary_pos_emb_thd",
    "fused_apply_rotary_pos_emb_2d",
]

fused_apply_rotary_pos_emb = fused_rope
fused_apply_rotary_pos_emb_cached = fused_rope_cached
fused_apply_rotary_pos_emb_thd = fused_rope_thd
fused_apply_rotary_pos_emb_2d = fused_rope_2d
