"""Functional wrappers over the fused kernels (reference ``apex/transformer/functional/``)."""

from apex_tpu.transformer.functional.fused_softmax import (
    FusedScaleMaskSoftmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
    generic_scaled_masked_softmax,
)
from apex_tpu.transformer.functional.fused_rope import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
    fused_apply_rotary_pos_emb_2d,
)

__all__ = [
    "FusedScaleMaskSoftmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
    "fused_apply_rotary_pos_emb_thd",
    "fused_apply_rotary_pos_emb_2d",
]
