"""Fused scale+mask+softmax dispatcher.

TPU-native counterpart of ``apex/transformer/functional/fused_softmax.py``:
the reference's :class:`FusedScaleMaskSoftmax` picks between four CUDA
kernels and a plain torch softmax via ``is_kernel_available``
(``fused_softmax.py:222-248``). Here the Pallas kernels (``apex_tpu.ops``)
have none of the CUDA constraints (dtype, 16 < sk <= 16384, power-of-two
batch-per-block), so the predicate is kept for API/diagnostic parity and the
fused path is the default whenever fusion is requested.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import (
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType

__all__ = [
    "FusedScaleMaskSoftmax",
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
]


class FusedScaleMaskSoftmax:
    """Fused operation: scaling + mask + softmax.

    Mirrors the reference constructor (``fused_softmax.py:181-220``):

    Args:
      input_in_fp16 / input_in_bf16: declared input dtype (diagnostic parity;
        the kernels accept any float dtype).
      attn_mask_type: :class:`AttnMaskType` (padding or causal).
      scaled_masked_softmax_fusion: use the fused kernels (else pure XLA).
      mask_func: mask application fn for the unfused path, called as
        ``mask_func(scores, mask)``.
      softmax_in_fp32: compute softmax in fp32 (the fused kernels always do).
      scale: optional logit scale factor; requires ``softmax_in_fp32``
        (reference assertion, ``fused_softmax.py:218-219``).
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = False,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active at the same time.")
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale

    def is_kernel_available(self, mask: Optional[jax.Array], b: int, np_: int,
                            sq: int, sk: int) -> bool:
        """Reference predicate (``fused_softmax.py:222-248``) — the CUDA
        limits (sk <= 16384, fp16/bf16 only, sq % 4 == 0) don't apply to the
        Pallas kernels; the fused causal kernel still requires square scores
        (same gate as the reference's ``sq == sk`` check)."""
        if not self.scaled_masked_softmax_fusion:
            return False
        if self.attn_mask_type == AttnMaskType.causal and (
                sq != sk or mask is not None):
            # the fused causal kernel takes no mask argument — an explicit
            # mask (sliding window, varlen, KV-cache slots) must ride the
            # unfused path, which applies causal AND the mask
            return False
        return True

    def __call__(self, x: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
        assert x.ndim == 4  # (b, np, sq, sk), reference `forward` assertion
        b, np_, sq, sk = x.shape
        if self.is_kernel_available(mask, b, np_, sq, sk):
            return self.forward_fused_softmax(x, mask)
        return self.forward_torch_softmax(x, mask)

    def forward_fused_softmax(self, x, mask):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            b, np_, sq, sk = x.shape
            out = scaled_upper_triang_masked_softmax(
                x.reshape(-1, sq, sk), scale)
            return out.reshape(x.shape)
        return scaled_masked_softmax(x, mask, scale)

    def forward_torch_softmax(self, x, mask):
        """Unfused path (reference ``fused_softmax.py:253-270``)."""
        orig_dtype = x.dtype
        if self.input_in_float16 and self.softmax_in_fp32:
            x = x.astype(jnp.float32)
        if self.scale is not None:
            x = x * self.scale
        if self.attn_mask_type == AttnMaskType.causal:
            sq, sk = x.shape[-2], x.shape[-1]
            causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
            x = jnp.where(causal, x, -10000.0)
        if mask is not None:
            x = self.mask_func(x, mask) if self.mask_func is not None else (
                jnp.where(mask, -10000.0, x))
        probs = jax.nn.softmax(x, axis=-1)
        if self.input_in_float16 and self.softmax_in_fp32:
            probs = probs.astype(orig_dtype)
        return probs


# Module-level aliases matching the reference's public autograd wrappers
# (``fused_softmax.py:20-178`` exposes ScaledUpperTriangMaskedSoftmax etc.).
ScaledSoftmax = scaled_softmax
ScaledMaskedSoftmax = scaled_masked_softmax
ScaledUpperTriangMaskedSoftmax = scaled_upper_triang_masked_softmax
GenericScaledMaskedSoftmax = generic_scaled_masked_softmax
