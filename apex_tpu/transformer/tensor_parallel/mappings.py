"""Forward/backward collective region functions.

TPU-native counterpart of the reference's autograd communication Functions
(``apex/transformer/tensor_parallel/mappings.py:141-268``): each torch
``autograd.Function`` whose forward is one NCCL collective and whose backward
is the conjugate collective becomes a ``jax.custom_vjp`` over the matching XLA
collective (``psum`` / ``all_gather`` / ``psum_scatter``), executed over a
named mesh axis inside ``shard_map``.

All functions degrade to the identity when the axis is unbound (world size 1
semantics, mirroring the reference's early-outs when
``get_tensor_model_parallel_world_size() == 1``, e.g. ``mappings.py:36-40``),
so layer code runs unchanged in unsharded unit tests.

Tensor-model-parallel regions shard the **last** dim (hidden); sequence-
parallel regions shard dim **0** (sequence), exactly as the reference
(``mappings.py:63-138``).

Canonical AD usage: compute gradients **inside** ``shard_map`` (per-rank
autodiff, mirroring torch's one-rank-per-process model, e.g.
``jax.value_and_grad`` of the per-rank loss with param grads exiting through
the params' own sharded specs). Differentiating *through* the shard_map
boundary composes shard_map's own boundary transposes (replicated out-specs
scale cotangents by 1/axis_size; replicated in-specs psum them) with these
explicit backward collectives and double-counts reductions.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import TENSOR_AXIS

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "mark_sequence_parallel_parameter",
]


def axis_bound(axis_name: str) -> bool:
    """True when ``axis_name`` is a bound collective axis (inside shard_map)."""
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def axis_size(axis_name: str) -> int:
    from apex_tpu.utils.sharding import axis_size as _axis_size
    return _axis_size(axis_name)


def _local_chunk(x: jax.Array, axis_name: str, dim: int) -> jax.Array:
    """This rank's chunk of ``x`` along ``dim`` (reference ``mappings.py:45-60``)."""
    n = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    local = x.shape[dim] // n
    return lax.dynamic_slice_in_dim(x, rank * local, local, axis=dim)


# ---------------------------------------------------------------------------
# tensor-model-parallel regions (hidden dim = last dim)
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """Identity forward, all-reduce backward (``_CopyToModelParallelRegion``,
    reference ``mappings.py:141-156``)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    if axis_bound(axis_name):
        g = lax.psum(g, axis_name)
    return (g,)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """All-reduce forward, identity backward (``_ReduceFromModelParallelRegion``,
    reference ``mappings.py:159-172``)."""
    if axis_bound(axis_name):
        return lax.psum(x, axis_name)
    return x


def _reduce_fwd(x, axis_name):
    return reduce_from_tensor_model_parallel_region(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """Keep own last-dim chunk forward, all-gather backward
    (``_ScatterToModelParallelRegion``, reference ``mappings.py:175-190``)."""
    if axis_bound(axis_name):
        return _local_chunk(x, axis_name, x.ndim - 1)
    return x


def _scatter_fwd(x, axis_name):
    return scatter_to_tensor_model_parallel_region(x, axis_name), None


def _scatter_bwd(axis_name, _, g):
    if axis_bound(axis_name):
        g = lax.all_gather(g, axis_name, axis=g.ndim - 1, tiled=True)
    return (g,)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """All-gather last dim forward, keep-own-chunk backward
    (``_GatherFromModelParallelRegion``, reference ``mappings.py:193-210``)."""
    if axis_bound(axis_name):
        return lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)
    return x


def _gather_fwd(x, axis_name):
    return gather_from_tensor_model_parallel_region(x, axis_name), None


def _gather_bwd(axis_name, _, g):
    if axis_bound(axis_name):
        g = _local_chunk(g, axis_name, g.ndim - 1)
    return (g,)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# ---------------------------------------------------------------------------
# sequence-parallel regions (sequence dim = dim 0)
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis_name=TENSOR_AXIS):
    """Keep own dim-0 chunk forward, all-gather backward
    (``_ScatterToSequenceParallelRegion``, reference ``mappings.py:213-228``)."""
    if axis_bound(axis_name):
        return _local_chunk(x, axis_name, 0)
    return x


def _sp_scatter_fwd(x, axis_name):
    return scatter_to_sequence_parallel_region(x, axis_name), None


def _sp_scatter_bwd(axis_name, _, g):
    if axis_bound(axis_name):
        g = lax.all_gather(g, axis_name, axis=0, tiled=True)
    return (g,)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(
    x, tensor_parallel_output_grad: bool = True, axis_name: str = TENSOR_AXIS
):
    """All-gather dim 0 forward; backward is reduce-scatter when the gathered
    activation enters a tensor-parallel matmul (each rank contributes a
    partial grad), or plain chunk-split otherwise
    (``_GatherFromSequenceParallelRegion``, reference ``mappings.py:231-251``).
    """
    if axis_bound(axis_name):
        return lax.all_gather(x, axis_name, axis=0, tiled=True)
    return x


def _sp_gather_fwd(x, tensor_parallel_output_grad, axis_name):
    return gather_from_sequence_parallel_region(
        x, tensor_parallel_output_grad, axis_name), None


def _sp_gather_bwd(tensor_parallel_output_grad, axis_name, _, g):
    if axis_bound(axis_name):
        if tensor_parallel_output_grad:
            g = lax.psum_scatter(g, axis_name, scatter_dimension=0, tiled=True)
        else:
            g = _local_chunk(g, axis_name, 0)
    return (g,)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis_name=TENSOR_AXIS):
    """Reduce-scatter dim 0 forward, all-gather backward
    (``_ReduceScatterToSequenceParallelRegion``, reference ``mappings.py:254-268``)."""
    if axis_bound(axis_name):
        return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    return x


def _sp_rs_fwd(x, axis_name):
    return reduce_scatter_to_sequence_parallel_region(x, axis_name), None


def _sp_rs_bwd(axis_name, _, g):
    if axis_bound(axis_name):
        g = lax.all_gather(g, axis_name, axis=0, tiled=True)
    return (g,)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def mark_sequence_parallel_parameter(p, axis_name=TENSOR_AXIS):
    """Identity forward; backward psums the parameter cotangent over the
    tensor axis.

    Counterpart of the reference's ``sequence_parallel_enabled`` attribute on
    layer-norm / row-linear-bias params (``transformer/layers/layer_norm.py:
    26-99``, ``tensor_parallel/layers.py:758-775``) plus the trainer-side
    grad all-reduce: under sequence parallelism those params consume
    *sequence-sharded* activations, so per-rank grads are partial sums. Here
    the sync is part of the parameter's use site instead of trainer
    bookkeeping — wrap the param where it meets the sharded activation and
    autodiff produces fully-reduced grads on every rank.
    """
    return p


def _mark_sp_fwd(p, axis_name):
    return p, None


def _mark_sp_bwd(axis_name, _, g):
    if axis_bound(axis_name):
        g = lax.psum(g, axis_name)
    return (g,)


mark_sequence_parallel_parameter.defvjp(_mark_sp_fwd, _mark_sp_bwd)
