"""Tensor-parallel layers and collectives.

TPU-native counterpart of ``apex/transformer/tensor_parallel/__init__.py``:
the reference's autograd communication Functions become ``jax.custom_vjp``
wrappers over XLA collectives, the layers become functional init/apply
modules whose parameters carry :class:`jax.sharding.PartitionSpec` metadata,
and the CUDA RNG tracker becomes a functional PRNG-key tracker built on
``jax.random.fold_in``.
"""

from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    scatter_to_sequence_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    linear_with_grad_accumulation_and_async_allreduce,
)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.random import (
    get_rng_tracker,
    get_cuda_rng_tracker,
    model_parallel_rng_key,
    checkpoint,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data
from apex_tpu.transformer.tensor_parallel.utils import (
    divide,
    split_tensor_into_1d_equal_chunks,
    gather_split_1d_tensor,
)

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "linear_with_grad_accumulation_and_async_allreduce",
    "vocab_parallel_cross_entropy",
    "get_rng_tracker",
    "get_cuda_rng_tracker",
    "model_parallel_rng_key",
    "checkpoint",
    "broadcast_data",
    "divide",
    "split_tensor_into_1d_equal_chunks",
    "gather_split_1d_tensor",
]
