"""Vocab-parallel cross entropy.

TPU-native counterpart of ``_VocabParallelCrossEntropy``
(``apex/transformer/tensor_parallel/cross_entropy.py:23-134``): logits stay
sharded along the vocab dim across the tensor axis and only three scalars per
token cross the interconnect — the max logit (``:29``), the predicted logit
(``:58``), and the softmax denominator (``:66``) — instead of gathering the
full [tokens, vocab] logits. Backward is computed from saved softmax
residuals without recomputation, as the reference does (``:100-134``).

Label smoothing follows the reference's formulation (``:75-90``):
``loss = (1 - s') * nll - s' * mean(log_probs)`` with
``s' = label_smoothing * V / (V - 1)``.

Runs inside ``shard_map`` with the tensor axis bound (sharded path) or
standalone (degenerate world-size-1 path) — same code, collectives become
identities.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = ["vocab_parallel_cross_entropy"]


def _tp(axis_name):
    if axis_bound(axis_name):
        return lax.axis_index(axis_name), axis_size(axis_name), True
    return 0, 1, False


def _forward(vocab_parallel_logits, target, label_smoothing, axis_name,
             z_loss=0.0):
    rank, size, bound = _tp(axis_name)
    in_dtype = vocab_parallel_logits.dtype
    # fp32 internal math regardless of logits dtype (the reference CUDA
    # kernel upcasts half logits, xentropy_kernel.cu) — callers can feed
    # bf16 logits straight from a bf16 LM-head matmul
    vocab_parallel_logits = vocab_parallel_logits.astype(jnp.float32)
    local_vocab = vocab_parallel_logits.shape[-1]
    global_vocab = local_vocab * size
    start = rank * local_vocab

    # 1st all-reduce: max logit for numerical stability (reference :27-33).
    logits_max = jnp.max(vocab_parallel_logits, axis=-1)
    if bound:
        logits_max = lax.pmax(logits_max, axis_name)
    logits = vocab_parallel_logits - logits_max[..., None]

    # Masked local lookup of the target logit (reference :36-56).
    masked_target = target - start
    in_range = (masked_target >= 0) & (masked_target < local_vocab)
    masked_target = jnp.where(in_range, masked_target, 0)
    predicted = jnp.take_along_axis(logits, masked_target[..., None], axis=-1)[..., 0]
    predicted = jnp.where(in_range, predicted, 0.0)
    # 2nd all-reduce: predicted logit (reference :58).
    if bound:
        predicted = lax.psum(predicted, axis_name)

    # 3rd all-reduce: softmax denominator (reference :61-66).
    exp_logits = jnp.exp(logits)
    sum_exp = jnp.sum(exp_logits, axis=-1)
    if bound:
        sum_exp = lax.psum(sum_exp, axis_name)

    loss = jnp.log(sum_exp) - predicted

    softmax = exp_logits / sum_exp[..., None]

    smoothing = 0.0
    if label_smoothing > 0:
        # Reference :75-90.
        smoothing = label_smoothing * global_vocab / (global_vocab - 1)
        log_probs = logits - jnp.log(sum_exp)[..., None]
        sum_log_probs = jnp.sum(log_probs, axis=-1)
        if bound:
            sum_log_probs = lax.psum(sum_log_probs, axis_name)
        mean_log_probs = sum_log_probs / global_vocab
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs

    # z-loss (exceeds reference; PaLM/Megatron-LM logit regularization):
    # coef * log(Z)^2 with the TRUE partition function (max re-added).
    # Added AFTER the smoothing rescale so forward and the custom backward
    # agree on exactly z * logZ^2 per token; computed only when active so
    # the default path saves no extra residual.
    log_z = None
    if z_loss > 0.0:
        log_z = jnp.log(sum_exp) + logits_max
        loss = loss + z_loss * log_z * log_z

    # residual kept in the caller's dtype: halves backward HBM traffic for
    # bf16 logits (the grad is bf16 anyway — it feeds a bf16 matmul)
    residuals = (softmax.astype(in_dtype), in_range, masked_target,
                 smoothing, global_vocab, log_z)
    return loss, residuals


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def vocab_parallel_cross_entropy(
    vocab_parallel_logits: jax.Array,
    target: jax.Array,
    label_smoothing: float = 0.0,
    axis_name: str = TENSOR_AXIS,
    z_loss: float = 0.0,
) -> jax.Array:
    """Per-token CE loss from vocab-sharded logits [..., V/tp] and global
    ids. ``z_loss`` adds PaLM-style logit regularization
    ``z_loss * log(Z)^2`` per token (exceeds the reference)."""
    loss, _ = _forward(vocab_parallel_logits, target, label_smoothing,
                       axis_name, z_loss)
    return loss


def _vjp_fwd(vocab_parallel_logits, target, label_smoothing, axis_name,
             z_loss):
    loss, residuals = _forward(
        vocab_parallel_logits, target, label_smoothing, axis_name, z_loss)
    return loss, residuals


def _vjp_bwd(label_smoothing, axis_name, z_loss, residuals, g):
    # Reference backward (:100-134): grad = softmax - onehot(target) on the
    # local shard, with the smoothing correction spread over the vocab.
    softmax, in_range, masked_target, smoothing, global_vocab, log_z = \
        residuals
    grad = softmax.astype(jnp.float32)     # fp32 math, output in input dtype
    onehot = jax.nn.one_hot(
        masked_target, softmax.shape[-1], dtype=jnp.float32)
    onehot = onehot * in_range[..., None].astype(jnp.float32)
    if smoothing > 0:
        grad = grad - (1.0 - smoothing) * onehot - smoothing / global_vocab
    else:
        grad = grad - onehot
    if z_loss > 0.0:
        # d/dlogits [z * logZ^2] = 2 z logZ * softmax
        grad = grad + (2.0 * z_loss) * log_z[..., None] * \
            softmax.astype(jnp.float32)
    grad = grad * g[..., None].astype(jnp.float32)
    return (grad.astype(softmax.dtype), None)


vocab_parallel_cross_entropy.defvjp(_vjp_fwd, _vjp_bwd)
