"""Activation-memory arenas.

Counterpart of ``apex/transformer/tensor_parallel/memory.py`` (``MemoryBuffer``
/ ``RingMemBuffer``): the reference preallocates one large device tensor and
hands out zero-copy views so activation-checkpoint regions never hit the CUDA
allocator. On TPU, XLA owns device memory — buffers are program-allocated,
donation recycles them, and there is no runtime allocator to bypass — so the
arena here is a *functional* scratch: one flat array, trace-time slicing into
requested shapes, explicit reset. It exists for API parity and for host-side
staging composition with :mod:`apex_tpu.native`'s pooled buffers, and it
enforces the same invariants the reference does (no over-allocation, dtype
match).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["MemoryBuffer", "RingMemBuffer", "allocate_mem_buff"]


class MemoryBuffer:
    """Flat arena of ``numel`` elements handing out shaped slices
    (reference ``MemoryBuffer.get``)."""

    def __init__(self, name: str, numel: int, dtype=jnp.bfloat16,
                 track_usage: bool = False):
        self.name = name
        self.numel = int(numel)
        self.dtype = dtype
        self.track_usage = track_usage
        self.data = jnp.zeros((self.numel,), dtype)
        self._start = 0
        self.in_use_value = 0
        self.total_value = 0

    def reset(self) -> None:
        # usage accounting per fill cycle: elements handed out vs capacity
        if self.track_usage:
            self.in_use_value += self._start
            self.total_value += self.numel
        self._start = 0

    def is_in_use(self) -> bool:
        return self._start > 0

    def numel_in_use(self) -> int:
        return self._start

    def get(self, shape: Sequence[int], dtype=None) -> jax.Array:
        """Carve the next ``prod(shape)`` elements as a view of the arena."""
        dtype = dtype or self.dtype
        if dtype != self.dtype:
            raise ValueError(
                f"arena {self.name} holds {self.dtype}, asked for {dtype}")
        n = int(np.prod(shape, dtype=np.int64))
        end = self._start + n
        if end > self.numel:
            raise MemoryError(
                f"arena {self.name}: requested {n} elements at offset "
                f"{self._start}, capacity {self.numel}")
        out = jax.lax.dynamic_slice(self.data, (self._start,), (n,))
        self._start = end
        return out.reshape(tuple(shape))

    def print_average_usage(self) -> None:
        if self.track_usage and self.total_value:
            print(f"arena {self.name}: average usage "
                  f"{100.0 * self.in_use_value / max(self.total_value, 1):.1f}%")


class RingMemBuffer:
    """Round-robin ring of ``num_buffers`` arenas (reference
    ``RingMemBuffer``): consecutive ``get_next`` calls rotate arenas so a
    double-buffered pipeline stage never overwrites live activations."""

    def __init__(self, name: str, num_buffers: int, numel: int,
                 dtype=jnp.bfloat16, track_usage: bool = False):
        self.num_buffers = num_buffers
        self.buffers = [
            MemoryBuffer(f"{name}-{i}", numel, dtype, track_usage)
            for i in range(num_buffers)
        ]
        self._index = -1

    def get_next_buffer(self) -> MemoryBuffer:
        self._index = (self._index + 1) % self.num_buffers
        buf = self.buffers[self._index]
        buf.reset()
        return buf


def allocate_mem_buff(name: str, numel: int, dtype=jnp.bfloat16,
                      track_usage: bool = False) -> MemoryBuffer:
    """Factory matching the reference's module-level allocator."""
    return MemoryBuffer(name, numel, dtype, track_usage)
