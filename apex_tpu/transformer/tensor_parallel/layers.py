"""Tensor-parallel layers.

TPU-native counterpart of ``apex/transformer/tensor_parallel/layers.py``:

- ``ColumnParallelLinear`` (reference class at ``layers.py:460``, forward at
  ``:609-643``): weight split along the output dim, optional output
  all-gather, optional Megatron sequence parallelism.
- ``RowParallelLinear`` (reference ``layers.py:645``, forward ``:777-813``):
  weight split along the input dim, output all-reduce (or reduce-scatter to
  sequence shards under SP).
- ``VocabParallelEmbedding`` (reference ``layers.py:174-276``): vocab-sharded
  embedding with masked lookup + all-reduce.

Design: layers are functional modules — ``init(key)`` builds **global**-shape
parameters (so replicated init is rank-consistent by construction, the
property the reference engineers via master-weight scatter in
``_initialize_affine_weight_cpu``, ``layers.py:110-152``) and ``spec()``
returns the matching :class:`PartitionSpec` pytree; ``apply(params, x)`` is
written against the **local shard** view and is meant to run inside
``shard_map`` over the ``tensor`` mesh axis, where the specs at the shard_map
boundary slice the global params into per-rank shards. Outside ``shard_map``
every collective degrades to the identity, so the same code path is the
world-size-1 reference implementation.

The reference's async-grad-allreduce / fused-wgrad-accumulation machinery
(``LinearWithGradAccumulationAndAsyncCommunication``, ``layers.py:278-440``,
calling ``fused_weight_gradient_mlp_cuda``) exists to overlap the input-grad
all-reduce with the weight-grad GEMM and to accumulate dW in place. Under
XLA both are compiler duties: the collective and the wgrad einsum have no
data dependence, so the latency-hiding scheduler overlaps them, and donated
gradient buffers give in-place accumulation (SURVEY.md §7 hard part (f)).
``linear_with_grad_accumulation_and_async_allreduce`` is therefore a thin
functional wrapper kept for API parity.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_bound,
    axis_size,
    copy_to_tensor_model_parallel_region,
    mark_sequence_parallel_parameter,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide

__all__ = [
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "linear_with_grad_accumulation_and_async_allreduce",
]


def _default_init() -> Callable:
    # Reference default: ``init.xavier_normal_`` (layers.py:471,654).
    return jax.nn.initializers.xavier_normal()


def _tp_info(axis_name: str) -> Tuple[Any, int]:
    """(rank, size) of the tensor axis; (0, 1) outside shard_map."""
    if axis_bound(axis_name):
        return lax.axis_index(axis_name), axis_size(axis_name)
    return 0, 1


def linear_with_grad_accumulation_and_async_allreduce(
    x: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array],
    *,
    sequence_parallel_enabled: bool = False,
    axis_name: str = TENSOR_AXIS,
) -> jax.Array:
    """Forward of the reference's fused linear Function (``layers.py:279-330``).

    Under SP the sequence-sharded input is all-gathered into the matmul and
    the backward reduce-scatters dX (the custom_vjp in
    :func:`gather_from_sequence_parallel_region` encodes exactly the
    reference's backward at ``layers.py:383-390,429-433``); otherwise the
    input passes through the copy region whose backward all-reduces dX
    (``layers.py:368-371``).
    """
    if sequence_parallel_enabled:
        total_input = gather_from_sequence_parallel_region(
            x, True, axis_name)
    else:
        total_input = copy_to_tensor_model_parallel_region(x, axis_name)
    # compute in the activation dtype (amp O2 semantics: bf16 compute against
    # fp32 master params; the cast's transpose keeps param grads fp32)
    out = jnp.matmul(total_input, weight.T.astype(x.dtype))
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


@dataclass
class ColumnParallelLinear:
    """Linear with weight W [out, in] split along out: Y_i = X A_i^T.

    Reference: ``apex/transformer/tensor_parallel/layers.py:460-643``.
    """

    input_size: int
    output_size: int
    bias: bool = True
    gather_output: bool = True
    init_method: Optional[Callable] = None
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_AXIS

    def __post_init__(self):
        if self.sequence_parallel_enabled and self.gather_output:
            # Reference raises the same incompatibility (layers.py:553-558).
            raise ValueError(
                "`sequence_parallel_enabled` is incompatible with `gather_output`"
            )

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        init_fn = self.init_method or _default_init()
        w = init_fn(key, (self.output_size, self.input_size), self.params_dtype)
        params = {"weight": w}
        if self.bias:
            # Reference zero-initializes the bias (layers.py:601-607).
            params["bias"] = jnp.zeros((self.output_size,), self.params_dtype)
        return params

    def spec(self) -> Dict[str, PartitionSpec]:
        s = {"weight": PartitionSpec(self.axis_name, None)}
        if self.bias:
            s["bias"] = PartitionSpec(self.axis_name)
        return s

    def apply(self, params: Dict[str, jax.Array], x: jax.Array):
        """Forward (reference ``layers.py:609-643``). Returns ``(out, bias)``
        when ``skip_bias_add`` else ``out`` (bias folded in)."""
        bias = params.get("bias")
        fused_bias = bias if not self.skip_bias_add else None
        out = linear_with_grad_accumulation_and_async_allreduce(
            x, params["weight"], fused_bias,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            axis_name=self.axis_name,
        )
        if self.gather_output:
            out = gather_from_tensor_model_parallel_region(out, self.axis_name)
        if self.skip_bias_add:
            return out, bias
        return out


@dataclass
class RowParallelLinear:
    """Linear with weight W [out, in] split along in: Y = sum_i X_i A_i^T.

    Reference: ``apex/transformer/tensor_parallel/layers.py:645-813``.
    """

    input_size: int
    output_size: int
    bias: bool = True
    input_is_parallel: bool = False
    init_method: Optional[Callable] = None
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_AXIS

    def __post_init__(self):
        if self.sequence_parallel_enabled and not self.input_is_parallel:
            # Reference raises the same (layers.py:737-741).
            raise ValueError(
                "To enable `sequence_parallel_enabled`, `input_is_parallel` must be `True`"
            )

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        init_fn = self.init_method or _default_init()
        w = init_fn(key, (self.output_size, self.input_size), self.params_dtype)
        params = {"weight": w}
        if self.bias:
            params["bias"] = jnp.zeros((self.output_size,), self.params_dtype)
        return params

    def spec(self) -> Dict[str, PartitionSpec]:
        s = {"weight": PartitionSpec(None, self.axis_name)}
        if self.bias:
            s["bias"] = PartitionSpec()  # replicated, added post-reduce
        return s

    def apply(self, params: Dict[str, jax.Array], x: jax.Array):
        """Forward (reference ``layers.py:777-813``)."""
        if not self.input_is_parallel:
            x = scatter_to_tensor_model_parallel_region(x, self.axis_name)
        partial_out = jnp.matmul(x, params["weight"].T.astype(x.dtype))
        if self.sequence_parallel_enabled:
            out = reduce_scatter_to_sequence_parallel_region(
                partial_out, self.axis_name)
        else:
            out = reduce_from_tensor_model_parallel_region(
                partial_out, self.axis_name)
        bias = params.get("bias")
        if bias is not None and self.sequence_parallel_enabled:
            # bias meets sequence-sharded output: per-rank bias grads are
            # partial sums (reference marks the bias
            # ``sequence_parallel_enabled``, layers.py:758-775)
            bias = mark_sequence_parallel_parameter(bias, self.axis_name)
        if self.skip_bias_add:
            return out, bias
        if bias is not None:
            out = out + bias.astype(out.dtype)
        return out


@dataclass
class VocabParallelEmbedding:
    """Embedding sharded along the vocab dim.

    Each rank owns rows ``[rank*V/tp, (rank+1)*V/tp)``; out-of-range token ids
    are masked to 0, looked up, zeroed, and the partial embeddings all-reduced
    (reference ``layers.py:174-276``, masked lookup at ``:245-264``).
    """

    num_embeddings: int
    embedding_dim: int
    init_method: Optional[Callable] = None
    params_dtype: Any = jnp.float32
    axis_name: str = TENSOR_AXIS

    def init(self, key: jax.Array) -> Dict[str, jax.Array]:
        init_fn = self.init_method or jax.nn.initializers.normal(stddev=1.0)
        w = init_fn(key, (self.num_embeddings, self.embedding_dim), self.params_dtype)
        return {"weight": w}

    def spec(self) -> Dict[str, PartitionSpec]:
        return {"weight": PartitionSpec(self.axis_name, None)}

    def apply(self, params: Dict[str, jax.Array], token_ids: jax.Array) -> jax.Array:
        weight = params["weight"]  # local shard [V/tp, H] inside shard_map
        rank, size = _tp_info(self.axis_name)
        local_vocab = self.num_embeddings // size if size > 1 else weight.shape[0]
        start = rank * local_vocab
        if size > 1 or axis_bound(self.axis_name):
            # Masked local lookup (reference layers.py:245-255).
            masked = token_ids - start
            in_range = (masked >= 0) & (masked < local_vocab)
            masked = jnp.where(in_range, masked, 0)
            out = jnp.take(weight, masked, axis=0)
            out = jnp.where(in_range[..., None], out, 0.0)
            out = reduce_from_tensor_model_parallel_region(out, self.axis_name)
        else:
            out = jnp.take(weight, token_ids, axis=0)
        return out
