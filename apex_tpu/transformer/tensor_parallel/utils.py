"""Small tensor-parallel helpers (reference ``apex/transformer/utils.py:1-54``)."""

from __future__ import annotations

import jax
from jax import lax

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.utils.sharding import axis_size

__all__ = [
    "ensure_divisibility",
    "divide",
    "split_tensor_into_1d_equal_chunks",
    "gather_split_1d_tensor",
]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Reference ``utils.py:26-30``."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_into_1d_equal_chunks(x: jax.Array, axis_name: str = TENSOR_AXIS) -> jax.Array:
    """This rank's 1D chunk of the flattened tensor (reference ``utils.py:33-43``).

    Must run inside ``shard_map`` with ``axis_name`` bound.
    """
    flat = x.reshape(-1)
    n = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    chunk = flat.shape[0] // n
    return lax.dynamic_slice_in_dim(flat, rank * chunk, chunk, axis=0)


def gather_split_1d_tensor(x: jax.Array, axis_name: str = TENSOR_AXIS) -> jax.Array:
    """All-gather 1D chunks back into the full flat tensor (reference ``utils.py:46-54``)."""
    return lax.all_gather(x.reshape(-1), axis_name, axis=0, tiled=True)
