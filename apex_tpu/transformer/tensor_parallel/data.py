"""Input-batch broadcast across the model-parallel group.

The reference broadcasts the tokenized batch from TP-rank-0 to the other
tensor-parallel ranks so every rank sees identical data
(``apex/transformer/tensor_parallel/data.py:~30-122``: dtype/size checks,
flatten, ``torch.distributed.broadcast``, unflatten). Under JAX's
single-controller model, replication across a mesh axis is a *sharding*, not
a communication call: the host hands the global batch to ``jit`` with a
PartitionSpec that omits the tensor axis and XLA materializes the replicas.

``broadcast_data`` keeps the reference's signature (keys + datatype check)
and returns the batch with a replicated-over-tensor-axis sharding constraint
applied, so it can be dropped into ported training loops.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from apex_tpu.transformer import parallel_state

__all__ = ["broadcast_data"]


def _check_data_types(keys: List[str], data: Dict[str, jax.Array], target_dtype) -> None:
    """Reference ``data.py:~35-45``: every broadcast member must share a dtype."""
    for key in keys:
        if data[key].dtype != target_dtype:
            raise ValueError(
                f"{key} has data type {data[key].dtype} while {target_dtype} is expected"
            )


def broadcast_data(keys: List[str], data: Dict[str, jax.Array], datatype) -> Dict[str, jax.Array]:
    """Replicate ``data[keys]`` across the tensor-parallel axis.

    Inside ``jit`` this is a sharding constraint (data-sharded over ``data``,
    replicated over ``tensor``); outside it is the identity — either way every
    TP rank observes the same values, matching the reference broadcast.
    """
    _check_data_types(keys, data, datatype)
    out = {}
    for key in keys:
        x = data[key]
        if parallel_state.model_parallel_is_initialized():
            try:
                x = jax.lax.with_sharding_constraint(x, PartitionSpec())
            except Exception:  # outside jit/mesh context: already replicated
                pass
        out[key] = x
    return out
