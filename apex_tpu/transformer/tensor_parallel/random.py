"""Model-parallel RNG state management, the JAX way.

The reference maintains stateful per-region CUDA RNG states so TP ranks draw
*distinct* dropout masks inside model-parallel regions but *identical* numbers
for replicated init (``apex/transformer/tensor_parallel/random.py:90-240``,
``get_cuda_rng_tracker().fork()``). JAX PRNG is functional, so the tracker
here derives region keys with ``jax.random.fold_in``: forking into the
model-parallel region folds the tensor-parallel axis index into the key
(distinct streams per rank); the default region leaves the key untouched
(identical streams). SURVEY.md §7 hard part (d).

Also provides :func:`checkpoint` — activation recomputation with RNG restore
(reference ``random.py:~240-311``) — which in JAX is exactly
``jax.checkpoint``: recomputation replays the same fold_in-derived keys, so
dropout masks match between forward and rematerialized backward by
construction (no state save/restore needed).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import jax
from jax import lax

from apex_tpu.transformer.parallel_state import TENSOR_AXIS

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"

__all__ = [
    "get_rng_tracker",
    "get_cuda_rng_tracker",
    "model_parallel_rng_key",
    "checkpoint",
    "RngTracker",
]


def model_parallel_rng_key(key: jax.Array, axis_name: str = TENSOR_AXIS) -> jax.Array:
    """Decorrelate ``key`` across the tensor-parallel axis.

    Counterpart of seeding the model-parallel RNG with
    ``seed + 2718 + tp_rank`` (reference ``random.py:194-205``): inside
    ``shard_map`` the tensor-axis index is folded into the key, outside the
    key is returned unchanged.
    """
    try:
        rank = lax.axis_index(axis_name)
    except NameError:
        return key
    return jax.random.fold_in(key, rank)


class RngTracker:
    """Functional analog of ``CudaRNGStatesTracker`` (reference ``random.py:90-188``).

    Holds a base key; :meth:`fork` yields the key for a named region —
    ``model-parallel-rng`` regions additionally fold in the TP rank. Each
    ``fork`` of the same region advances a per-region counter so successive
    forks (e.g. dropout layers) get fresh keys, mirroring how the reference's
    stateful generator advances.
    """

    def __init__(self, key: Optional[jax.Array] = None):
        self._key = key if key is not None else jax.random.PRNGKey(0)
        self._counters: dict = {}

    def reset(self) -> None:
        self._counters.clear()

    def add(self, name: str, seed: int) -> None:
        """API parity with the reference tracker; regions are derived, not stored."""
        self._counters.setdefault(name, 0)

    @contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        count = self._counters.get(name, 0)
        self._counters[name] = count + 1
        key = jax.random.fold_in(self._key, hash(name) % (2**31))
        key = jax.random.fold_in(key, count)
        if name == _MODEL_PARALLEL_RNG_TRACKER_NAME:
            key = model_parallel_rng_key(key)
        yield key


_TRACKER = RngTracker()


def get_rng_tracker() -> RngTracker:
    return _TRACKER


# Name-compat alias (reference: ``get_cuda_rng_tracker``, ``random.py:229-231``).
get_cuda_rng_tracker = get_rng_tracker


def checkpoint(fn, *args, **kwargs):
    """Activation checkpointing (reference ``random.py:~240-311``).

    ``jax.checkpoint`` rematerializes the forward during backward; because all
    randomness flows through explicit keys, the reference's fork/restore of
    RNG state is unnecessary — replay is deterministic by construction.
    """
    return jax.checkpoint(fn)(*args, **kwargs)
