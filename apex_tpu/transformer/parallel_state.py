"""Model-parallel mesh registry.

TPU-native counterpart of the reference's process-group registry
(``apex/transformer/parallel_state.py:155-419``). Where the reference creates
NCCL process groups for tensor/pipeline/data/embedding parallelism, here a
single :class:`jax.sharding.Mesh` carries named axes and every "group" is a
mesh axis; XLA collectives (``psum``/``all_gather``/``psum_scatter``/
``ppermute``) over an axis name replace group handles.

Axis layout (outermost → innermost): ``(data, pipeline, context, tensor)``.
The tensor axis is innermost so TP collectives — the most latency/bandwidth
sensitive — map onto the shortest ICI hops; pipeline ``ppermute`` rides the
next ring out; data-parallel gradient reductions tolerate the longest paths
(DCN when multi-slice). This mirrors the reference's topology awareness
(hybrid IB/socket groups keyed on ``NUM_GPUS_PER_IB_BLOCK``,
``parallel_state.py:108-153``) in XLA terms.

Rank getters follow the reference API (``get_tensor_model_parallel_rank`` etc.,
``parallel_state.py:421-430``): inside ``shard_map`` they return the traced
``lax.axis_index``; outside they return 0 (the "controller" view — JAX is
single-controller per process, unlike torch's one-rank-per-process model).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

# Canonical axis names.
DATA_AXIS = "data"
PIPELINE_AXIS = "pipeline"
CONTEXT_AXIS = "context"
TENSOR_AXIS = "tensor"

MESH_AXIS_NAMES = (DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)

_MESH: Optional[Mesh] = None

# Interleaved-schedule virtual pipeline state
# (reference: parallel_state.py:675-696).
_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None

# Test-only world-size overrides (reference exposes the same "fake" setters).
_FAKE_SIZES: dict = {}


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    context_parallel_size: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build and install the global mesh.

    Data-parallel size is inferred as ``n_devices // (tp * pp * cp)``, exactly
    as the reference infers ``data_parallel_size`` from the world size
    (``apex/transformer/parallel_state.py:213-222``).
    """
    global _MESH, _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    tp, pp, cp = tensor_model_parallel_size, pipeline_model_parallel_size, context_parallel_size
    denom = tp * pp * cp
    if n % denom != 0:
        raise RuntimeError(
            f"device count ({n}) is not divisible by tensor_model_parallel_size "
            f"({tp}) x pipeline_model_parallel_size ({pp}) x context_parallel_size ({cp})"
        )
    dp = n // denom
    dev_array = np.array(devs).reshape(dp, pp, cp, tp)
    _MESH = Mesh(dev_array, MESH_AXIS_NAMES)
    if virtual_pipeline_model_parallel_size is not None:
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = virtual_pipeline_model_parallel_size
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError(
            "model parallel mesh is not initialized; call "
            "apex_tpu.transformer.parallel_state.initialize_model_parallel() first"
        )
    return _MESH


def destroy_model_parallel() -> None:
    """Reference: ``parallel_state.py:761-792``."""
    global _MESH, _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    _MESH = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _FAKE_SIZES.clear()


# ---------------------------------------------------------------------------
# world sizes
# ---------------------------------------------------------------------------

def _axis_size(axis: str) -> int:
    if axis in _FAKE_SIZES:
        return _FAKE_SIZES[axis]
    return get_mesh().shape[axis]


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(PIPELINE_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(CONTEXT_AXIS)


def get_data_parallel_world_size() -> int:
    return _axis_size(DATA_AXIS)


def get_model_parallel_world_size() -> int:
    return get_tensor_model_parallel_world_size() * get_pipeline_model_parallel_world_size()


# test-only overrides, mirroring the reference's set_*_world_size
def set_tensor_model_parallel_world_size(size: Optional[int]) -> None:
    _set_fake(TENSOR_AXIS, size)


def set_pipeline_model_parallel_world_size(size: Optional[int]) -> None:
    _set_fake(PIPELINE_AXIS, size)


def _set_fake(axis: str, size: Optional[int]) -> None:
    if size is None:
        _FAKE_SIZES.pop(axis, None)
    else:
        _FAKE_SIZES[axis] = size


# ---------------------------------------------------------------------------
# ranks — traced inside shard_map, 0 on the controller
# ---------------------------------------------------------------------------

def _axis_rank(axis: str):
    try:
        return jax.lax.axis_index(axis)
    except NameError:
        return 0


def get_tensor_model_parallel_rank():
    return _axis_rank(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_AXIS)


def get_context_parallel_rank():
    return _axis_rank(CONTEXT_AXIS)


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Reference: ``parallel_state.py:589-600``."""
    if not ignore_virtual:
        vpp = get_virtual_pipeline_model_parallel_world_size()
        if vpp is not None and get_virtual_pipeline_model_parallel_rank() != 0:
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vpp = get_virtual_pipeline_model_parallel_world_size()
        if vpp is not None and get_virtual_pipeline_model_parallel_rank() != vpp - 1:
            return False
    return get_pipeline_model_parallel_rank() == get_pipeline_model_parallel_world_size() - 1


def get_pipeline_model_parallel_next_rank():
    rank = get_pipeline_model_parallel_rank()
    return (rank + 1) % get_pipeline_model_parallel_world_size()


def get_pipeline_model_parallel_prev_rank():
    rank = get_pipeline_model_parallel_rank()
    return (rank - 1) % get_pipeline_model_parallel_world_size()


# ---------------------------------------------------------------------------
# virtual pipeline (interleaved schedule) state
# ---------------------------------------------------------------------------

def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = rank


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def set_virtual_pipeline_model_parallel_world_size(size: Optional[int]) -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = size


# ---------------------------------------------------------------------------
# sharding helpers
# ---------------------------------------------------------------------------

def data_parallel_spec(*trailing: Optional[str]) -> PartitionSpec:
    """PartitionSpec sharding dim 0 over the data axis."""
    return PartitionSpec(DATA_AXIS, *trailing)


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def get_rank_info() -> str:
    """Compact rank/topology string (reference: ``parallel_state.py:421-430``)."""
    if not model_parallel_is_initialized():
        return "mesh=uninitialized"
    m = get_mesh()
    return (
        f"dp={m.shape[DATA_AXIS]} pp={m.shape[PIPELINE_AXIS]} "
        f"cp={m.shape[CONTEXT_AXIS]} tp={m.shape[TENSOR_AXIS]}"
    )
