"""Model-parallel mesh registry.

TPU-native counterpart of the reference's process-group registry
(``apex/transformer/parallel_state.py:155-419``). Where the reference creates
NCCL process groups for tensor/pipeline/data/embedding parallelism, here a
single :class:`jax.sharding.Mesh` carries named axes and every "group" is a
mesh axis; XLA collectives (``psum``/``all_gather``/``psum_scatter``/
``ppermute``) over an axis name replace group handles.

Axis layout (outermost → innermost): ``(data, pipeline, context, tensor)``.
The tensor axis is innermost so TP collectives — the most latency/bandwidth
sensitive — map onto the shortest ICI hops; pipeline ``ppermute`` rides the
next ring out; data-parallel gradient reductions tolerate the longest paths
(DCN when multi-slice). This mirrors the reference's topology awareness
(hybrid IB/socket groups keyed on ``NUM_GPUS_PER_IB_BLOCK``,
``parallel_state.py:108-153``) in XLA terms.

Rank getters follow the reference API (``get_tensor_model_parallel_rank`` etc.,
``parallel_state.py:421-430``): inside ``shard_map`` they return the traced
``lax.axis_index``; outside they return 0 (the "controller" view — JAX is
single-controller per process, unlike torch's one-rank-per-process model).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec

# Canonical axis names.
DATA_AXIS = "data"
PIPELINE_AXIS = "pipeline"
CONTEXT_AXIS = "context"
TENSOR_AXIS = "tensor"

MESH_AXIS_NAMES = (DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)

_MESH: Optional[Mesh] = None
_NUM_SLICES: int = 1

# Interleaved-schedule virtual pipeline state
# (reference: parallel_state.py:675-696).
_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None

# Encoder/decoder two-section pipeline split: pipeline ranks < split run
# encoder stages, ranks >= split run decoder stages
# (reference: parallel_state.py:155-247 stores the split rank at group
# construction; rank predicates :589-668).
_PIPELINE_MODEL_PARALLEL_SPLIT_RANK: Optional[int] = None

# Test-only world-size overrides (reference exposes the same "fake" setters).
_FAKE_SIZES: dict = {}


class UndersizedMeshError(RuntimeError):
    """The available device set cannot satisfy the requested mesh shape.

    Raised (instead of a bare RuntimeError) so the test harness can skip
    multi-device tests on undersized backends by TYPE — anchoring skips on
    message substrings would also mask genuine mesh-construction
    regressions (ADVICE r2)."""


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    context_parallel_size: int = 1,
    *,
    num_slices: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build and install the global mesh.

    Data-parallel size is inferred as ``n_devices // (tp * pp * cp)``, exactly
    as the reference infers ``data_parallel_size`` from the world size
    (``apex/transformer/parallel_state.py:213-222``).

    ``num_slices > 1`` declares a multi-slice (DCN-connected) topology — the
    TPU analog of the reference's hybrid IB/socket NCCL group construction
    keyed on ``NUM_GPUS_PER_IB_BLOCK`` (``parallel_state.py:108-153``).
    Invariants enforced:

    - the model axes (pipeline/context/tensor) must fit inside ONE slice, so
      their latency-sensitive collectives ride ICI only;
    - the data axis is laid out DCN-major: data coordinate ``d`` lives on
      slice ``d // (dp_per_slice)``, so the gradient all-reduce decomposes
      into fast intra-slice ICI segments plus the unavoidable cross-slice
      DCN hop (XLA performs this decomposition when the layout permits it).

    On real multi-slice hardware the per-slice device sets come from each
    device's ``slice_index``; elsewhere (virtual CPU meshes, single slice)
    the enumeration order of ``jax.devices()`` — process/slice-major — is
    used as the slice layout.
    """
    global _MESH, _NUM_SLICES, _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    tp, pp, cp = tensor_model_parallel_size, pipeline_model_parallel_size, context_parallel_size
    denom = tp * pp * cp
    if n % denom != 0:
        raise UndersizedMeshError(
            f"device count ({n}) is not divisible by tensor_model_parallel_size "
            f"({tp}) x pipeline_model_parallel_size ({pp}) x context_parallel_size ({cp})"
        )
    dp = n // denom
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if num_slices > 1:
        if n % num_slices:
            raise UndersizedMeshError(
                f"device count ({n}) is not divisible by num_slices "
                f"({num_slices})")
        per_slice = n // num_slices
        if per_slice % denom:
            raise RuntimeError(
                f"model-parallel block (tp {tp} x pp {pp} x cp {cp} = "
                f"{denom}) does not fit evenly in one slice ({per_slice} "
                f"devices): model axes must never cross the DCN boundary")
        # group devices by slice (DCN-major): physical slice_index when the
        # platform exposes it, enumeration order otherwise
        if all(getattr(d, "slice_index", None) is not None for d in devs):
            from collections import Counter
            counts = Counter(d.slice_index for d in devs)
            if len(counts) != num_slices or set(counts.values()) != {per_slice}:
                raise UndersizedMeshError(
                    f"num_slices={num_slices} needs {per_slice} devices on "
                    f"each physical slice, but the device set spans "
                    f"{dict(sorted(counts.items()))} (slice_index -> count); "
                    "an uneven layout would let model axes cross the DCN "
                    "boundary")
            order = sorted(range(n), key=lambda i: (devs[i].slice_index, i))
            devs = [devs[i] for i in order]
        dev_array = np.array(devs).reshape(dp, pp, cp, tp)
    else:
        dev_array = np.array(devs).reshape(dp, pp, cp, tp)
    if pipeline_model_parallel_split_rank is not None:
        if not 0 < pipeline_model_parallel_split_rank < pp:
            raise ValueError(
                f"pipeline_model_parallel_split_rank "
                f"({pipeline_model_parallel_split_rank}) must leave at least "
                f"one encoder and one decoder stage: need 0 < split < "
                f"pipeline size ({pp})")
        if virtual_pipeline_model_parallel_size is not None:
            # reference parity: the interleaved schedule rejects
            # encoder_and_decoder (fwd_bwd_pipelining_with_interleaving.py)
            raise ValueError(
                "interleaved (virtual) pipelining is not supported with an "
                "encoder/decoder split — the reference's interleaved "
                "schedule rejects ModelType.encoder_and_decoder too")
    _MESH = Mesh(dev_array, MESH_AXIS_NAMES)
    _NUM_SLICES = num_slices
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = pipeline_model_parallel_split_rank
    if virtual_pipeline_model_parallel_size is not None:
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = virtual_pipeline_model_parallel_size
    return _MESH


def get_num_slices() -> int:
    """Declared DCN slice count of the current mesh (1 = single slice)."""
    return _NUM_SLICES


def get_data_parallel_dcn_size() -> int:
    """Cross-slice (DCN) factor of the data axis."""
    return _NUM_SLICES


def get_data_parallel_ici_size() -> int:
    """Intra-slice (ICI) factor of the data axis."""
    return get_data_parallel_world_size() // _NUM_SLICES


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError(
            "model parallel mesh is not initialized; call "
            "apex_tpu.transformer.parallel_state.initialize_model_parallel() first"
        )
    return _MESH


def destroy_model_parallel() -> None:
    """Reference: ``parallel_state.py:761-792``."""
    global _MESH, _NUM_SLICES, _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _MESH = None
    _NUM_SLICES = 1
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = None
    _FAKE_SIZES.clear()


# ---------------------------------------------------------------------------
# world sizes
# ---------------------------------------------------------------------------

def _axis_size(axis: str) -> int:
    if axis in _FAKE_SIZES:
        return _FAKE_SIZES[axis]
    return get_mesh().shape[axis]


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(PIPELINE_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(CONTEXT_AXIS)


def get_data_parallel_world_size() -> int:
    return _axis_size(DATA_AXIS)


def get_model_parallel_world_size() -> int:
    return get_tensor_model_parallel_world_size() * get_pipeline_model_parallel_world_size()


# test-only overrides, mirroring the reference's set_*_world_size
def set_tensor_model_parallel_world_size(size: Optional[int]) -> None:
    _set_fake(TENSOR_AXIS, size)


def set_pipeline_model_parallel_world_size(size: Optional[int]) -> None:
    _set_fake(PIPELINE_AXIS, size)


def _set_fake(axis: str, size: Optional[int]) -> None:
    if size is None:
        _FAKE_SIZES.pop(axis, None)
    else:
        _FAKE_SIZES[axis] = size


# ---------------------------------------------------------------------------
# ranks — traced inside shard_map, 0 on the controller
# ---------------------------------------------------------------------------

def _axis_rank(axis: str):
    try:
        return jax.lax.axis_index(axis)
    except NameError:
        return 0


def get_tensor_model_parallel_rank():
    return _axis_rank(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_AXIS)


def get_context_parallel_rank():
    return _axis_rank(CONTEXT_AXIS)


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Reference: ``parallel_state.py:589-600``."""
    if not ignore_virtual:
        vpp = get_virtual_pipeline_model_parallel_world_size()
        if vpp is not None and get_virtual_pipeline_model_parallel_rank() != 0:
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vpp = get_virtual_pipeline_model_parallel_world_size()
        if vpp is not None and get_virtual_pipeline_model_parallel_rank() != vpp - 1:
            return False
    return get_pipeline_model_parallel_rank() == get_pipeline_model_parallel_world_size() - 1


def get_pipeline_model_parallel_next_rank():
    rank = get_pipeline_model_parallel_rank()
    return (rank + 1) % get_pipeline_model_parallel_world_size()


def get_pipeline_model_parallel_prev_rank():
    rank = get_pipeline_model_parallel_rank()
    return (rank - 1) % get_pipeline_model_parallel_world_size()


# ---------------------------------------------------------------------------
# encoder/decoder split (two-section pipeline) state
# (reference: parallel_state.py:155-247 split-rank bookkeeping; rank
# predicates :601-668 is_pipeline_stage_{before,after,at}_split)
# ---------------------------------------------------------------------------

def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    """First pipeline rank of the decoder section, or None (decoder-only)."""
    return _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank: Optional[int]) -> None:
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = rank


def is_pipeline_stage_before_split(rank=None):
    """True when the given (default: this) pipeline rank runs encoder
    stages. With no split configured every stage counts as "before" —
    reference semantics (``parallel_state.py:601-616``). Inside
    ``shard_map`` the default rank is traced, so the result may be a traced
    bool (compose with ``lax.cond``/``jnp.where``)."""
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    if _PIPELINE_MODEL_PARALLEL_SPLIT_RANK is None:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    return rank < _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def is_pipeline_stage_after_split(rank=None):
    """True when the given (default: this) pipeline rank runs decoder
    stages (reference ``parallel_state.py:619-634``)."""
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    if _PIPELINE_MODEL_PARALLEL_SPLIT_RANK is None:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    return rank >= _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def is_pipeline_stage_at_split():
    """True on the last encoder stage (its successor starts the decoder) —
    reference ``parallel_state.py:637-645``."""
    rank = get_pipeline_model_parallel_rank()
    before = is_pipeline_stage_before_split(rank)
    after = is_pipeline_stage_after_split(rank + 1)
    if isinstance(before, bool) and isinstance(after, bool):
        return before and after
    return jnp.logical_and(before, after)


# ---------------------------------------------------------------------------
# virtual pipeline (interleaved schedule) state
# ---------------------------------------------------------------------------

def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = rank


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def set_virtual_pipeline_model_parallel_world_size(size: Optional[int]) -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = size


# ---------------------------------------------------------------------------
# fp8 amax reduction (reference parallel_state.py:280-292 builds one
# TP x DP process group per pipeline stage when use_fp8_ is set)
# ---------------------------------------------------------------------------

def amax_reduction_axes(include_pipeline: bool = False) -> tuple:
    """Mesh axes an fp8 amax reduction spans.

    The reference's ``_AMAX_REDUCTION_GROUP`` covers ``tensor x data`` ranks
    within one pipeline stage (``parallel_state.py:284-292``): every rank
    holding replicas or shards of the *same* layer's tensors must agree on
    the delayed-scaling factors. The mesh translation is all axes except
    ``pipeline`` (different stages hold different layers; pass
    ``include_pipeline=True`` to force globally uniform scales anyway).
    Returns the axis names; reduce with ``lax.pmax(amax, axes)`` inside
    ``shard_map`` (see :mod:`apex_tpu.amp.fp8`).
    """
    axes = [DATA_AXIS, CONTEXT_AXIS, TENSOR_AXIS]
    if include_pipeline:
        axes.insert(1, PIPELINE_AXIS)
    return tuple(axes)


# ---------------------------------------------------------------------------
# sharding helpers
# ---------------------------------------------------------------------------

def data_parallel_spec(*trailing: Optional[str]) -> PartitionSpec:
    """PartitionSpec sharding dim 0 over the data axis."""
    return PartitionSpec(DATA_AXIS, *trailing)


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def get_rank_info() -> str:
    """Compact rank/topology string (reference: ``parallel_state.py:421-430``)."""
    if not model_parallel_is_initialized():
        return "mesh=uninitialized"
    m = get_mesh()
    return (
        f"dp={m.shape[DATA_AXIS]} pp={m.shape[PIPELINE_AXIS]} "
        f"cp={m.shape[CONTEXT_AXIS]} tp={m.shape[TENSOR_AXIS]}"
    )
