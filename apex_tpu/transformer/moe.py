"""Mixture-of-Experts with expert parallelism (EP).

**Exceeds the reference**: apex has no MoE/expert code anywhere in the tree
(SURVEY.md §2.2 "EP — absent"). This module completes the parallelism matrix
(DP/TP/SP/PP/CP/EP) with the TPU-native shape of switch routing:

- router: top-1 or top-2 gating with optional jitter and the standard
  load-balancing auxiliary loss (Shazeer/Fedus switch-transformer recipe —
  public algorithm, implemented fresh);
- capacity-based dispatch: per-shard token buffers ``[E, C, h]`` built with
  one-hot matmuls (MXU-friendly, no scatters), tokens over capacity dropped
  to the residual path;
- expert parallelism over a mesh axis (default: the ``data`` axis, the
  standard "EP rides DP" layout): one ``lax.all_to_all`` ships each
  expert's buffer to its owning rank, the expert FFNs run as one batched
  einsum over the local experts, and a second ``all_to_all`` ships results
  back. Unsharded (axis unbound) it degrades to a dense dispatch over all
  experts locally.

Layout follows the transformer stack: ``[s, b, h]`` activations, functional
``init/apply``, works inside ``shard_map`` next to
:class:`~apex_tpu.models.transformer.ParallelTransformerLayer`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from apex_tpu.transformer.parallel_state import DATA_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size
from apex_tpu.transformer.tensor_parallel.utils import divide
from apex_tpu.utils.activations import (
    apply_activation,
    is_gated,
    validate_activation,
)

__all__ = ["MoEConfig", "SwitchMLP"]


@dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    top_k: int = 1                      # 1 = switch, 2 = GShard-style
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    router_jitter: float = 0.0          # multiplicative input jitter at train
    expert_axis: Optional[str] = DATA_AXIS
    # expert FFN activation; gated pairs ("swiglu"/"geglu") widen w_in to
    # 2*ffn with gate/up unit-interleaved (same layout as ParallelMLP)
    activation: str = "gelu"
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    init_method_std: float = 0.02

    def __post_init__(self):
        validate_activation(self.activation)

    @property
    def gated(self) -> bool:
        return is_gated(self.activation)


class SwitchMLP:
    """Top-k routed expert FFN bank.

    ``apply(params, x[s, b, h], rng, deterministic) ->
    (y[s, b, h], aux_loss)``; ``aux_loss`` is already scaled by
    ``config.aux_loss_weight`` — callers add it to the training objective
    as-is.
    """

    def __init__(self, config: MoEConfig):
        self.config = config

    # -- params --------------------------------------------------------------

    def init(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        kr, k1, k2 = jax.random.split(key, 3)
        std = c.init_method_std
        dt = c.params_dtype
        fin = (2 if c.gated else 1) * c.ffn_hidden_size
        p = {
            "router": jax.random.normal(
                kr, (c.hidden_size, c.num_experts), dt) * std,
            "w_in": jax.random.normal(
                k1, (c.num_experts, c.hidden_size, fin),
                dt) * std,
            "w_out": jax.random.normal(
                k2, (c.num_experts, c.ffn_hidden_size, c.hidden_size),
                dt) * std,
            "b_out": jnp.zeros((c.num_experts, c.hidden_size), dt),
        }
        if not c.gated:
            # gated projections are bias-free (shared convention with
            # ParallelMLP, utils/activations.py)
            p["b_in"] = jnp.zeros((c.num_experts, fin), dt)
        return p

    def spec(self) -> Dict[str, PartitionSpec]:
        """Experts sharded dim-0 over the expert axis; router replicated."""
        e = self.config.expert_axis
        s = {
            "router": PartitionSpec(),
            "w_in": PartitionSpec(e, None, None),
            "w_out": PartitionSpec(e, None, None),
            "b_out": PartitionSpec(e, None),
        }
        if not self.config.gated:
            s["b_in"] = PartitionSpec(e, None)
        return s

    # -- routing -------------------------------------------------------------

    def _route(self, params, x2d, rng, deterministic):
        """x2d: [T, h] -> (weights [T, k], experts [T, k], aux_loss)."""
        c = self.config
        inp = x2d
        if not deterministic and c.router_jitter > 0.0 and rng is not None:
            eps = jax.random.uniform(
                rng, x2d.shape, x2d.dtype,
                1.0 - c.router_jitter, 1.0 + c.router_jitter)
            inp = x2d * eps
        logits = inp.astype(jnp.float32) @ params["router"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)            # [T, E]
        weights, experts = lax.top_k(probs, c.top_k)       # [T, k]
        if c.top_k > 1:
            weights = weights / jnp.sum(weights, -1, keepdims=True)

        # load-balancing loss: E * sum_e fraction_e * mean_prob_e
        # (switch-transformer aux objective)
        top1 = experts[:, 0]
        frac = jnp.mean(
            jax.nn.one_hot(top1, c.num_experts, dtype=jnp.float32), axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = (c.aux_loss_weight * c.num_experts
               * jnp.sum(frac * mean_prob))
        return weights, experts, aux

    # -- dispatch/combine ----------------------------------------------------

    def _capacity(self, tokens: int) -> int:
        c = self.config
        cap = int(tokens * c.capacity_factor * c.top_k / c.num_experts)
        return max(cap, 1)

    def apply(self, params, x, *, rng=None, deterministic: bool = True,
              drop_free: bool = False) -> Tuple[jax.Array, jax.Array]:
        """``drop_free=True`` sizes the capacity buffers at ``tokens`` (an
        expert can hold every token), guaranteeing no capacity drops — the
        decode path uses this: per-step token counts are tiny, so the
        factor-based capacity would drop tokens batch-size-dependently and
        decode logits would silently diverge from the batched forward."""
        c = self.config
        s, b, h = x.shape
        tokens = s * b
        x2d = x.reshape(tokens, h)
        weights, experts, aux = self._route(params, x2d, rng, deterministic)
        if drop_free and tokens > 512:
            # DENSE drop-free evaluation for batched token counts (round
            # 5): the capacity machinery with cap = tokens builds
            # [T, E, T] dispatch/combine one-hots — QUADRATIC in tokens
            # (a 32k-token 64-expert prefill would need ~275 GB) — and
            # computes every buffer slot anyway. Scanning local experts
            # over all tokens pays the same E/top_k FLOP blowup with
            # O(T * ffn) memory; under EP each rank runs its local
            # experts and one psum replaces both all_to_alls. Small
            # token counts (single-token decode) keep the one-shot
            # capacity dispatch below.
            y = self._dense_drop_free(params, x2d, weights, experts)
            return y.reshape(s, b, h).astype(x.dtype), aux
        cap = tokens if drop_free else self._capacity(tokens)

        # position of each token within its expert's capacity buffer, one
        # pass per k (cumsum over the one-hot assignment matrix)
        dispatch = jnp.zeros((tokens, c.num_experts, cap), x.dtype)
        combine = jnp.zeros((tokens, c.num_experts, cap), jnp.float32)
        prior = jnp.zeros((c.num_experts,), jnp.int32)
        for k in range(c.top_k):
            onehot = jax.nn.one_hot(experts[:, k], c.num_experts,
                                    dtype=jnp.int32)       # [T, E]
            pos = jnp.cumsum(onehot, axis=0) - 1 + prior   # [T, E]
            prior = prior + jnp.sum(onehot, axis=0)
            within = jnp.take_along_axis(
                pos, experts[:, k:k + 1], axis=1)[:, 0]    # [T]
            keep = within < cap                            # overflow dropped
            pos_oh = jax.nn.one_hot(jnp.where(keep, within, cap),
                                    cap + 1, dtype=x.dtype)[:, :cap]
            contrib = onehot.astype(x.dtype)[:, :, None] * pos_oh[:, None, :]
            dispatch = dispatch + contrib
            combine = combine + (contrib.astype(jnp.float32)
                                 * weights[:, k, None, None])

        # gather tokens into expert buffers: [E, C, h] (one-hot matmul — a
        # dense MXU op instead of data-dependent scatters)
        buffers = jnp.einsum("tec,th->ech", dispatch, x2d)

        ep = (axis_size(c.expert_axis)
              if c.expert_axis and axis_bound(c.expert_axis) else 1)
        if ep > 1:
            divide(c.num_experts, ep)    # validate E % ep == 0
            # ship expert buffers to their owners: split the expert dim
            # (chunk i -> rank i), concat received chunks along capacity:
            # [E, C, h] -> [E/ep, ep*C, h]; each rank now holds its local
            # experts' tokens from every rank
            buffers = lax.all_to_all(buffers, c.expert_axis, split_axis=0,
                                     concat_axis=1, tiled=True)

        cd = c.compute_dtype
        # params inside shard_map are already the local expert shard
        # ([E/ep, ...]) under spec(); unsharded they are the full bank
        w_in = params["w_in"]
        w_out, b_out = params["w_out"], params["b_out"]
        hmid = jnp.einsum("ech,ehf->ecf", buffers.astype(cd),
                          w_in.astype(cd))
        if not c.gated:
            hmid = hmid + params["b_in"][:, None, :].astype(cd)
        hmid = apply_activation(hmid, c.activation)
        out = jnp.einsum("ecf,efh->ech", hmid,
                         w_out.astype(cd)) + b_out[:, None, :].astype(cd)

        if ep > 1:
            # inverse shuffle: split capacity back per source rank, concat
            # experts back to global order: [E/ep, ep*C, h] -> [E, C, h]
            out = lax.all_to_all(out, c.expert_axis, split_axis=1,
                                 concat_axis=0, tiled=True)

        # combine back to token order with routing weights
        y = jnp.einsum("tec,ech->th", combine.astype(jnp.float32),
                       out.astype(jnp.float32))
        return y.reshape(s, b, h).astype(x.dtype), aux

    def _dense_drop_free(self, params, x2d, weights, experts):
        """Every local expert processes every token; per-token routing
        weights combine the results (exactly the drop-free capacity math,
        without its [T, E, cap] one-hots). Returns fp32 ``[T, h]``."""
        c = self.config
        tokens, h = x2d.shape
        ep = (axis_size(c.expert_axis)
              if c.expert_axis and axis_bound(c.expert_axis) else 1)
        if ep > 1:
            divide(c.num_experts, ep)
            # the token batch is SHARDED along the expert axis (EP rides
            # DP), so shard-local partials must not be psum'd as-is (each
            # rank's rows are DIFFERENT tokens — the capacity path handles
            # this with its all_to_all pair): gather every rank's tokens
            # and routing decisions, let the local experts process the
            # full set, psum the partial outputs, then slice this rank's
            # rows back out. The compact [T, k] weights/experts move over
            # the interconnect (E/(2k)x less than the dense [T, E] wte,
            # which is pure local compute built post-gather).
            e_local = c.num_experts // ep
            idx = lax.axis_index(c.expert_axis)
            x2d = lax.all_gather(x2d, c.expert_axis, axis=0, tiled=True)
            weights = lax.all_gather(weights, c.expert_axis, axis=0,
                                     tiled=True)
            experts = lax.all_gather(experts, c.expert_axis, axis=0,
                                     tiled=True)
        wte = jnp.zeros((x2d.shape[0], c.num_experts), jnp.float32)
        for k in range(c.top_k):
            wte = wte + (jax.nn.one_hot(experts[:, k], c.num_experts,
                                        dtype=jnp.float32)
                         * weights[:, k:k + 1].astype(jnp.float32))
        if ep > 1:
            wte = lax.dynamic_slice(
                wte, (jnp.int32(0), idx * e_local),
                (x2d.shape[0], e_local))
        cd = c.compute_dtype
        xc = x2d.astype(cd)

        def one_expert(y, ew):
            if c.gated:
                w_in, w_out, b_out, w_col = ew
                hm = xc @ w_in.astype(cd)
            else:
                w_in, b_in, w_out, b_out, w_col = ew
                hm = xc @ w_in.astype(cd) + b_in.astype(cd)
            hm = apply_activation(hm, c.activation)
            oe = hm @ w_out.astype(cd) + b_out.astype(cd)
            return y + w_col[:, None] * oe.astype(jnp.float32), None

        if c.gated:
            xs = (params["w_in"], params["w_out"], params["b_out"], wte.T)
        else:
            xs = (params["w_in"], params["b_in"], params["w_out"],
                  params["b_out"], wte.T)
        y, _ = lax.scan(one_expert,
                        jnp.zeros((x2d.shape[0], h), jnp.float32), xs)
        if ep > 1:
            y = lax.psum(y, c.expert_axis)
            y = lax.dynamic_slice_in_dim(y, idx * tokens, tokens, axis=0)
        return y
