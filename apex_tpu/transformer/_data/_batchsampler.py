"""Resumable, data-parallel-sharded batch samplers.

Parity with ``apex/transformer/_data/_batchsampler.py:~1-180``
(``MegatronPretrainingSampler``, ``MegatronPretrainingRandomSampler``): both
yield lists of dataset indices for **this data-parallel rank's** microbatch,
starting from ``consumed_samples`` so a resumed run continues the exact data
order (the checkpoint/resume story of SURVEY.md §5).

Host-side index generation is rank-agnostic JAX-wise — these feed whatever
input pipeline stages batches onto the mesh.
"""

from __future__ import annotations

import numpy as np

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]


class _Base:
    def __init__(self, total_samples: int, consumed_samples: int,
                 micro_batch_size: int, data_parallel_rank: int,
                 data_parallel_size: int):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)

        if total_samples <= 0:
            raise RuntimeError(
                f"no sample to consume: {total_samples}")
        if micro_batch_size <= 0:
            raise RuntimeError(
                f"micro_batch_size size must be greater than 0, but "
                f"{micro_batch_size}")
        if data_parallel_size <= 0:
            raise RuntimeError(
                f"data parallel size must be greater than 0, but "
                f"{data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise RuntimeError(
                f"data_parallel_rank should be smaller than data size: "
                f"{data_parallel_rank}, {data_parallel_size}")


class MegatronPretrainingSampler(_Base):
    """Sequential sampler (reference class of the same name): rank ``r``
    takes the ``r``-th ``micro_batch_size`` slice of each global batch."""

    def __init__(self, total_samples, consumed_samples, micro_batch_size,
                 data_parallel_rank, data_parallel_size,
                 drop_last: bool = True):
        super().__init__(total_samples, consumed_samples, micro_batch_size,
                         data_parallel_rank, data_parallel_size)
        # single-pass sampler: exhausted data is an error here, while the
        # random sampler below wraps into later epochs (reference puts this
        # check only on the sequential variant)
        if consumed_samples >= total_samples:
            raise RuntimeError(
                f"no samples left to consume: {consumed_samples}, "
                f"{total_samples}")
        self.drop_last = drop_last

    def __len__(self):
        return self.total_samples

    def get_start_end_idx(self):
        start_idx = self.data_parallel_rank * self.micro_batch_size
        end_idx = start_idx + self.micro_batch_size
        return start_idx, end_idx

    def __iter__(self):
        batch = []
        # data sharding: [DP rank 0 mbs, DP rank 1 mbs, ..., DP rank n mbs]
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.micro_batch_times_data_parallel_size:
                start_idx, end_idx = self.get_start_end_idx()
                yield batch[start_idx:end_idx]
                batch = []
        if len(batch) > 0 and not self.drop_last:
            start_idx, end_idx = self.get_start_end_idx()
            yield batch[start_idx:end_idx]


class MegatronPretrainingRandomSampler(_Base):
    """Shuffled sampler, resumable mid-epoch: the permutation is seeded by
    the epoch so every rank regenerates the same order, and
    ``consumed_samples`` fast-forwards into it (reference logic: bucket
    offset from ``current_epoch_samples``)."""

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        # the tail that doesn't fill a global batch is dropped each epoch, so
        # epoch accounting runs on the active sample count (reference:
        # active_total_samples = total_samples - last_batch_size)
        last_batch_size = (
            self.total_samples % self.micro_batch_times_data_parallel_size)
        active_total_samples = self.total_samples - last_batch_size
        if active_total_samples <= 0:
            raise RuntimeError(
                "total_samples smaller than one global batch: "
                f"{self.total_samples} < "
                f"{self.micro_batch_times_data_parallel_size}")
        self.epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples
        assert (current_epoch_samples
                % self.micro_batch_times_data_parallel_size == 0)

        # data sharding: interleaved buckets, one per DP rank
        bucket_size = (self.total_samples
                       // self.micro_batch_times_data_parallel_size
                       ) * self.micro_batch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        g = np.random.default_rng(self.epoch)
        random_idx = g.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.micro_batch_size:
                self.consumed_samples += self.micro_batch_times_data_parallel_size
                yield batch
                batch = []
