from apex_tpu.transformer._data._batchsampler import (
    MegatronPretrainingSampler,
    MegatronPretrainingRandomSampler,
)

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]
