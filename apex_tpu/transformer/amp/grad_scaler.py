"""Model-parallel-aware loss scaler.

Counterpart of ``apex/transformer/amp/grad_scaler.py:21-125``: the
reference's ``GradScaler`` subclass all-reduces ``found_inf`` across the
model-parallel group in ``_maybe_opt_step`` and ``update``, because under
TP/PP each rank only sees its shard's gradients — one rank's overflow must
skip the step (and shrink the scale) on *every* rank or parameters
desynchronize.

Here :class:`GradScaler` extends :class:`apex_tpu.amp.LossScaler`: inside
``shard_map`` the ``unscale`` overflow flag is OR-reduced (``psum`` of the
0/1 flag) over whichever of the configured mesh axes are bound; outside any
mesh it degrades to the plain scaler.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.transformer.parallel_state import (
    CONTEXT_AXIS,
    PIPELINE_AXIS,
    TENSOR_AXIS,
)
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

__all__ = ["GradScaler"]


class GradScaler(LossScaler):
    """LossScaler whose overflow flag is agreed across model-parallel ranks.

    Args match :class:`LossScaler` plus ``model_parallel_axes`` (default:
    tensor, pipeline and context — the reference's "model-parallel group").
    """

    def __init__(self, *args,
                 model_parallel_axes: Sequence[str] = (
                     TENSOR_AXIS, PIPELINE_AXIS, CONTEXT_AXIS),
                 **kw):
        super().__init__(*args, **kw)
        self.model_parallel_axes = tuple(model_parallel_axes)

    def _sync_found_inf(self, found_inf: jax.Array) -> jax.Array:
        """OR ``found_inf`` over every bound model-parallel axis (the
        reference's ``torch.distributed.all_reduce(found_inf, MAX, model
        parallel group)``)."""
        flag = found_inf.astype(jnp.float32)
        for axis in self.model_parallel_axes:
            if axis_bound(axis):
                flag = lax.psum(flag, axis)
        return flag > 0

    def unscale(self, grads: Any,
                state: LossScalerState) -> Tuple[Any, jax.Array]:
        grads, found_inf = super().unscale(grads, state)
        return grads, self._sync_found_inf(found_inf)
