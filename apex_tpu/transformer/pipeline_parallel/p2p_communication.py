"""Pipeline p2p communication.

TPU-native counterpart of ``apex/transformer/pipeline_parallel/
p2p_communication.py:48-690``. The reference batches NCCL isend/irecv pairs
between adjacent pipeline stages (``_run_p2pops``, ``:48-160``) and offers the
fused ``send_forward_recv_backward``-style calls the 1F1B schedule needs.

On TPU every adjacent-stage exchange is a single ``lax.ppermute`` over the
``pipeline`` mesh axis: all stages shift their tensor one hop around the ICI
ring simultaneously (exactly the communication pattern 1F1B produces when
every stage sends in lock-step), and XLA lowers it to collective-permute.
Usable only inside ``shard_map``; outside (world size 1) they are identity,
mirroring the reference's no-op at pipeline world size 1.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = [
    "send_forward",
    "send_backward",
    "send_forward_recv_backward",
    "send_backward_recv_forward",
    "ring_shift",
]


def _perm_next(size: int):
    return [(i, (i + 1) % size) for i in range(size)]


def _perm_prev(size: int):
    return [(i, (i - 1) % size) for i in range(size)]


def ring_shift(x: Any, *, reverse: bool = False,
               axis_name: str = PIPELINE_AXIS) -> Any:
    """Shift a pytree one hop along the pipeline ring.

    ``reverse=False``: each stage receives from the previous stage (the
    forward-activation direction, reference ``send_forward``/``recv_forward``
    at ``p2p_communication.py:385-460``); ``reverse=True``: from the next
    stage (the gradient direction, ``send_backward``/``recv_backward``).
    """
    if not axis_bound(axis_name):
        return x
    size = axis_size(axis_name)
    if size == 1:
        return x
    perm = _perm_prev(size) if reverse else _perm_next(size)
    return jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm), x)


def send_forward(output_tensor: Any, *, axis_name: str = PIPELINE_AXIS) -> Any:
    """Send activations to the next stage; returns what this stage receives
    from its previous stage (reference ``p2p_communication.py:~385-420``; the
    ring wraps, so the first stage receives the last stage's tensor — callers
    mask it, as the schedules do)."""
    return ring_shift(output_tensor, reverse=False, axis_name=axis_name)


def send_backward(input_grad: Any, *, axis_name: str = PIPELINE_AXIS) -> Any:
    """Send gradients to the previous stage; returns what this stage receives
    from its next stage (reference ``:~422-460``)."""
    return ring_shift(input_grad, reverse=True, axis_name=axis_name)


def send_forward_recv_backward(output_tensor: Any, input_grad: Any, *,
                               axis_name: str = PIPELINE_AXIS):
    """Fused variant (reference ``:~462-520``): both directions in one step."""
    return (ring_shift(output_tensor, reverse=False, axis_name=axis_name),
            ring_shift(input_grad, reverse=True, axis_name=axis_name))


def send_backward_recv_forward(input_grad: Any, output_tensor: Any, *,
                               axis_name: str = PIPELINE_AXIS):
    """Fused variant (reference ``:~522-580``)."""
    return (ring_shift(input_grad, reverse=True, axis_name=axis_name),
            ring_shift(output_tensor, reverse=False, axis_name=axis_name))
