"""Microbatch calculators.

Parity with ``apex/transformer/microbatches.py:26-195``: a calculator exposes
``get() -> num_micro_batches`` and ``get_current_global_batch_size()``, and
``update(consumed_samples, consistency_check)`` advances ramp-up state.
These are host-side bookkeeping (they size the scan over microbatches), so
pure Python is the right implementation on TPU too.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional

__all__ = [
    "build_num_microbatches_calculator",
    "NumMicroBatchesCalculator",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
]


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """Reference: ``microbatches.py:26-75``."""
    if rampup_batch_size is None:
        calculator = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
        if rank == 0:
            print(
                "setting number of micro-batches to constant "
                f"{calculator.get()}", flush=True)
    else:
        if len(rampup_batch_size) != 3:
            raise ValueError(
                "expected the following format: --rampup-batch-size <start "
                "batch size> <batch size increment> <ramp-up samples>")
        start_batch_size = int(rampup_batch_size[0])
        batch_size_increment = int(rampup_batch_size[1])
        ramup_samples = int(rampup_batch_size[2])
        if rank == 0:
            print(
                "will use batch size rampup starting from global batch size "
                f"{start_batch_size} to global batch size "
                f"{global_batch_size} with batch size increments "
                f"{batch_size_increment} over {ramup_samples} samples.",
                flush=True)
        calculator = RampupBatchsizeNumMicroBatches(
            start_batch_size, batch_size_increment, ramup_samples,
            global_batch_size, micro_batch_size, data_parallel_size)
    return calculator


class NumMicroBatchesCalculator(ABC):
    """Reference ABC at ``microbatches.py:61-75``."""

    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    @abstractmethod
    def update(self, consumed_samples, consistency_check) -> None:
        ...


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Reference: ``microbatches.py:77-97``."""

    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        micro_batch_times_data_parallel = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_data_parallel != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel "
                f"size ({data_parallel_size})")
        self.num_micro_batches = (
            global_batch_size // micro_batch_times_data_parallel)
        if self.num_micro_batches < 1:
            raise ValueError("number of microbatches must be at least 1")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Batch-size rampup, reference ``microbatches.py:100-195``.

    Global batch size grows from ``start_batch_size`` by
    ``batch_size_increment`` per step over ``ramup_samples`` consumed samples,
    then stays at ``global_batch_size``.
    """

    def __init__(self, start_batch_size, batch_size_increment, ramup_samples,
                 global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        if self.micro_batch_times_data_parallel_size <= 0:
            raise ValueError("micro * dp size must be positive")
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size

        diff_batch_size = self.global_batch_size - self.start_batch_size
        if diff_batch_size < 0:
            raise ValueError(
                "expected global batch size to be at least equal to start "
                "batch size")
        if diff_batch_size % batch_size_increment != 0:
            raise ValueError(
                f"expected global batch size interval ({diff_batch_size}) to "
                "be divisible by global batch size increment "
                f"({batch_size_increment})")

        num_increments = diff_batch_size // self.batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments > 0 else 0)

        self.update(0, False)

    def update(self, consumed_samples, consistency_check) -> None:
        if (consumed_samples > self.ramup_samples
                or self.rampup_samples_per_increment == 0):
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size)
        if consistency_check and (
                self.current_global_batch_size
                % self.micro_batch_times_data_parallel_size != 0):
            raise RuntimeError(
                f"current global batch size ({self.current_global_batch_size}) "
                "is not divisible by micro-batch-size "
                f"({self.micro_batch_size}) times data parallel size "
                f"({self.data_parallel_size})")
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size)
