"""Pipeline parallelism.

TPU-native counterpart of ``apex/transformer/pipeline_parallel/``: microbatch
calculators, the three fwd/bwd schedules (no-pipelining, 1F1B non-interleaved,
interleaved/virtual), p2p communication, and training utilities.

Where the reference drives an eager 1F1B state machine with NCCL
``batch_isend_irecv`` (``p2p_communication.py:48-690``) and explicit
``forward_step``/``backward_step`` calls per microbatch
(``schedules/fwd_bwd_pipelining_without_interleaving.py:241-597``), the TPU
design expresses the *forward* pipeline as a ``lax.scan`` over schedule ticks
with a ``ppermute`` ring shift per tick, and obtains the *backward* pipeline
from autodiff: the VJP of ``ppermute`` is the reverse ring permute, so
``jax.grad`` of the scanned forward is itself a reverse-order pipelined
schedule, compiled and overlap-scheduled by XLA.
"""

from apex_tpu.transformer.pipeline_parallel.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    get_forward_backward_func,
)

__all__ = [
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
    "build_num_microbatches_calculator",
    "get_forward_backward_func",
]
