"""Stage construction helpers.

Reference: ``apex/transformer/pipeline_parallel/schedules/common.py:30-150``
(``build_model``): instantiates the rank's model chunk(s) — a list of
``vpp`` chunks under interleaving — and optionally wraps them in DDP.

TPU analog: parameters for all layers are initialized **globally** as one
stacked ``[L, ...]`` pytree (rank-consistent init by construction) and then
*arranged* so that sharding the leading dim over the ``pipeline`` mesh axis
gives each rank exactly the layers its (virtual) stages own. DDP wrapping has
no analog — the data axis pmean in the train step covers it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import PartitionSpec

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    mark_sequence_parallel_parameter as _mark_psum_grad,
)

__all__ = [
    "arrange_layers_for_pipeline",
    "pipeline_stage_spec",
    "mark_pipeline_replicated",
    "build_model",
]


def arrange_layers_for_pipeline(
    stacked_params: Any,
    pipeline_size: int,
    virtual_pipeline_size: Optional[int] = None,
) -> Any:
    """Rearrange a ``[L, ...]``-stacked layer pytree for pipeline sharding.

    Without interleaving: ``[L, ...] -> [S, L/S, ...]`` — rank ``i`` owns
    layers ``[i*L/S, (i+1)*L/S)`` (the reference's contiguous layer split in
    ``build_model``).

    With interleaving: ``[L, ...] -> [S, vpp, L/V, ...]`` where position
    ``[i, c]`` holds virtual stage ``v = c*S + i`` — the reference's
    round-robin chunk assignment (``fwd_bwd_pipelining_with_interleaving.py``
    model-chunk indexing).
    """
    S = pipeline_size

    def one(x):
        L = x.shape[0]
        if virtual_pipeline_size is None:
            if L % S:
                raise ValueError(f"num layers ({L}) not divisible by "
                                 f"pipeline size ({S})")
            return x.reshape(S, L // S, *x.shape[1:])
        vpp = virtual_pipeline_size
        V = S * vpp
        if L % V:
            raise ValueError(f"num layers ({L}) not divisible by pipeline "
                             f"size x virtual size ({V})")
        Lc = L // V
        # [L] -> [V, Lc] -> [vpp, S, Lc] -> [S, vpp, Lc]
        return (x.reshape(vpp, S, Lc, *x.shape[1:])
                 .transpose(1, 0, *range(2, x.ndim + 2)))

    return jax.tree.map(one, stacked_params)


def pipeline_stage_spec(layer_spec: Any,
                        virtual_pipeline_size: Optional[int] = None,
                        axis_name: str = PIPELINE_AXIS) -> Any:
    """PartitionSpec pytree for :func:`arrange_layers_for_pipeline` output:
    pipeline axis on dim 0, then (chunk dim,) layer dim, then the per-layer
    spec."""
    extra = (None,) if virtual_pipeline_size is not None else ()

    def one(s):
        return PartitionSpec(axis_name, *extra, None, *s)

    return jax.tree.map(
        one, layer_spec, is_leaf=lambda x: isinstance(x, PartitionSpec))


def mark_pipeline_replicated(params: Any,
                             axis_name: str = PIPELINE_AXIS) -> Any:
    """Mark parameters replicated across pipeline stages (embedding, final
    norm, tied head) so their per-stage partial grads are psum-reduced — the
    analog of the reference's embedding-grad all-reduce between first and last
    stages (``parallel_state.py:347-407`` embedding groups). Identity forward,
    ``psum`` over the pipeline axis on the backward."""
    return jax.tree.map(lambda p: _mark_psum_grad(p, axis_name), params)


def build_model(model_provider_func, wrap_with_ddp: bool = True,
                virtual_pipeline_model_parallel_size: Optional[int] = None,
                *args, **kwargs):
    """Reference-shaped ``build_model`` (``schedules/common.py:30-150``).

    Calls ``model_provider_func(*args, pre_process=..., post_process=...,
    **kwargs)`` once per virtual chunk and returns the list. On TPU the
    provider should return a functional module (init/spec/apply); DDP
    wrapping is a no-op (``wrap_with_ddp`` accepted for signature parity —
    the data-axis pmean in the train step is DDP).
    """
    vpp = virtual_pipeline_model_parallel_size
    n_chunks = vpp if vpp is not None else 1
    models = []
    for c in range(n_chunks):
        models.append(model_provider_func(
            *args, pre_process=(c == 0), post_process=(c == n_chunks - 1),
            **kwargs))
    return models
