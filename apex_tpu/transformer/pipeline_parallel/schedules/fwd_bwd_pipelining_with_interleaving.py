"""Interleaved (virtual-pipeline) schedule.

Reference: ``apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_with_interleaving.py:27-744`` — each rank hosts
``vpp`` model chunks; microbatches traverse the rank ring ``vpp`` times, so
the pipeline has ``V = S * vpp`` virtual stages and the warmup bubble per
chunk shrinks by ``vpp``.

TPU design (circular pipeline): each rank carries a ``[vpp, ...]`` activation
buffer — slot ``c`` holds the microbatch currently at this rank's chunk ``c``
(virtual stage ``v = c * S + rank``). Per tick every rank computes **all**
its chunks (each on a different in-flight microbatch), then one ``ppermute``
moves the whole buffer to the next rank; the wrap-around at rank 0 shifts the
chunk dimension by one (stage ``c*S + S-1`` feeds stage ``(c+1)*S``), rank 0
slot 0 takes the next injected microbatch, and rank ``S-1`` slot ``vpp-1``
emits finished microbatches. Ticks: ``M + V - 1``. Backward comes from
autodiff, as in the non-interleaved schedule.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.p2p_communication import ring_shift
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    _broadcast_last_stage_loss,
    _index_microbatch,
)
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

__all__ = [
    "make_interleaved_pipelined_loss_fn",
    "forward_backward_pipelining_with_interleaving",
]


def make_interleaved_pipelined_loss_fn(
    preprocess_fn: Callable,
    stage_fn: Callable,
    postprocess_fn: Callable,
    num_microbatches: int,
    virtual_pipeline_size: int,
    *,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
) -> Callable:
    """Build ``loss_fn(params, batch) -> scalar`` for the circular pipeline.

    ``stage_fn(params, hidden, chunk, tick) -> hidden`` applies this rank's
    layer chunk ``chunk`` (``0..vpp-1``); chunk ``c`` of rank ``i`` is virtual
    stage ``c * S + i``, matching the reference's chunk-to-rank assignment
    (``parallel_state.py:675-696`` virtual rank state). Other arguments as in
    :func:`...fwd_bwd_pipelining_without_interleaving.make_pipelined_loss_fn`.
    """
    M = num_microbatches
    vpp = virtual_pipeline_size

    def loss_fn(params, batch):
        staged = jax.checkpoint(stage_fn) if remat else stage_fn

        pipelined = axis_bound(axis_name)
        S = lax.axis_size(axis_name) if pipelined else 1
        i = lax.axis_index(axis_name) if pipelined else 0
        V = S * vpp

        injected = jax.vmap(lambda mb: preprocess_fn(params, mb))(batch)
        hidden0 = jax.tree.map(lambda x: jnp.zeros_like(x[0]), injected)
        # [vpp, ...] in-flight buffer; slot c = this rank's chunk c.
        state0 = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (vpp,) + x.shape), hidden0)
        outbuf0 = jax.tree.map(jnp.zeros_like, injected)
        chunk_ids = jnp.arange(vpp)

        def tick(carry, t):
            state, outbuf = carry
            m_in = jnp.clip(t, 0, M - 1)
            inj = _index_microbatch(injected, m_in)
            # rank 0 slot 0 <- injected microbatch
            state = jax.tree.map(
                lambda s, x: jnp.where(
                    (i == 0)
                    & (jnp.arange(vpp) == 0).reshape(
                        (vpp,) + (1,) * x.ndim),
                    x[None], s),
                state, inj)
            # compute every chunk (each a different in-flight microbatch)
            y = lax.map(
                lambda args: staged(params, args[0], args[1], t),
                (state, chunk_ids))
            # rank S-1 chunk vpp-1 output = finished microbatch t - (V-1)
            m_out = jnp.clip(t - (V - 1), 0, M - 1)
            outbuf = jax.tree.map(
                lambda buf, leaf: lax.dynamic_update_index_in_dim(
                    buf, leaf[vpp - 1], m_out, 0), outbuf, y)
            # one ring hop for the whole buffer; the wrap into rank 0 climbs
            # one chunk (virtual stage c*S + S-1 -> (c+1)*S)
            arrived = ring_shift(y, axis_name=axis_name) if pipelined else y
            shifted = jax.tree.map(lambda a: jnp.roll(a, 1, axis=0), arrived)
            state = jax.tree.map(
                lambda sh, ar: jnp.where(i == 0, sh, ar), shifted, arrived)
            return (state, outbuf), None

        (_, outbuf), _ = lax.scan(
            tick, (state0, outbuf0), jnp.arange(M + V - 1))

        losses = jax.vmap(
            lambda y, mb: postprocess_fn(params, y, mb))(outbuf, batch)
        local = jnp.mean(losses)
        if not pipelined:
            return local
        return _broadcast_last_stage_loss(
            jnp.where(i == S - 1, local, 0.0), axis_name)

    return loss_fn


def forward_backward_pipelining_with_interleaving(
    forward_step_func: Any,
    batch: Any,
    params: Any,
    *,
    num_microbatches: int,
    virtual_pipeline_size: Optional[int] = None,
    forward_only: bool = False,
    grad_scaler: Optional[Callable] = None,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
):
    """Reference-shaped driver; see the non-interleaved counterpart.

    ``virtual_pipeline_size`` defaults to the registered virtual world size
    (``parallel_state.set_virtual_pipeline_model_parallel_world_size`` /
    ``initialize_model_parallel(virtual_pipeline_model_parallel_size=...)``),
    keeping this callable signature-compatible with the other schedules the
    selector can return.
    """
    if virtual_pipeline_size is None:
        from apex_tpu.transformer import parallel_state
        virtual_pipeline_size = (
            parallel_state.get_virtual_pipeline_model_parallel_world_size())
        if virtual_pipeline_size is None:
            raise ValueError(
                "virtual_pipeline_size not given and no virtual pipeline "
                "world size is registered in parallel_state")
    preprocess_fn, stage_fn, postprocess_fn = forward_step_func
    loss_fn = make_interleaved_pipelined_loss_fn(
        preprocess_fn, stage_fn, postprocess_fn, num_microbatches,
        virtual_pipeline_size, axis_name=axis_name, remat=remat)
    if forward_only:
        return loss_fn(params, batch), None
    if grad_scaler is None:
        return jax.value_and_grad(loss_fn)(params, batch)

    def scaled(p, b):
        loss = loss_fn(p, b)
        return grad_scaler(loss), loss  # differentiate scaled, report unscaled

    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params, batch)
    return loss, grads
