"""Interleaved (virtual-pipeline) schedule — 1F1B memory semantics.

Reference: ``apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_with_interleaving.py:27-744`` — each rank hosts
``vpp`` model chunks; microbatches traverse the rank ring ``vpp`` times, so
the pipeline has ``V = S * vpp`` virtual stages and the warmup bubble per
chunk shrinks by ``vpp``.

TPU design — synchronous 1F1B over virtual stages, one ``lax.scan``:

Chunk ``c`` of rank ``i`` is virtual stage ``v = c*S + i`` (the reference's
chunk-to-rank assignment, ``parallel_state.py:675-696``). The wavefront:
forward of microbatch ``m`` at stage ``v`` on tick ``t = m + v``; its
backward at tick ``t = m + 2(V-1) - v`` (the loss cotangent is born at
stage ``V-1`` and rides back one virtual stage per tick). Every tick each
rank runs forward+backward for ALL its chunks, then both ring buffers move:
activations one hop forward (the wrap into rank 0 climbs one chunk),
cotangents one hop backward (the wrap into rank ``S-1`` descends one
chunk). Each (rank, chunk) keeps a circular stash of in-flight *input*
activations — at most ``2(V-1)+1`` each, independent of the microbatch
count — and the backward recomputes the chunk forward from the stash
(``jax.vjp``), exactly the non-interleaved schedule's memory/compute trade.
Ticks: ``M + 2(V-1)``.

As in the non-interleaved schedule, the explicit backward is wrapped in
``jax.custom_vjp`` so ``jax.value_and_grad`` composes; forward-only calls
run a lean streamed-loss pipeline.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.p2p_communication import ring_shift
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    _axis_info,
    _finalize_batch_grads,
    _index_microbatch,
    _init_batch_grads,
    _select,
    _wrap_custom_vjp,
    _zeros_of,
)

__all__ = [
    "make_interleaved_pipelined_loss_fn",
    "forward_backward_pipelining_with_interleaving",
]


def make_interleaved_pipelined_loss_fn(
    preprocess_fn: Callable,
    stage_fn: Callable,
    postprocess_fn: Callable,
    num_microbatches: int,
    virtual_pipeline_size: int,
    *,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
    stage_aux: bool = False,
) -> Callable:
    """Build ``loss_fn(params, batch) -> scalar`` for the circular pipeline.

    ``stage_fn(params, hidden, chunk, tick) -> hidden`` applies this rank's
    layer chunk ``chunk`` (``0..vpp-1``). ``remat`` is accepted for API
    parity; the backward always recomputes from the stashed chunk inputs.
    ``stage_aux``: ``stage_fn`` returns ``(hidden, aux)`` — see the
    non-interleaved schedule; each (rank, chunk)'s aux joins the loss
    directly with a 1/M cotangent seed.
    """
    del remat
    M = num_microbatches
    vpp = virtual_pipeline_size

    def _stage(params, h, c, t):
        out = stage_fn(params, h, c, t)
        return out if stage_aux else (out, jnp.zeros((), jnp.float32))

    # -- forward-only pipeline ----------------------------------------------

    def _forward_only(params, batch):
        pipelined, S, i = _axis_info(axis_name)
        V = S * vpp
        mb0 = _index_microbatch(batch, 0)
        h_shape = jax.eval_shape(preprocess_fn, params, mb0)
        buf0 = jax.tree.map(
            lambda s: jnp.zeros((vpp,) + s.shape, s.dtype), h_shape)

        def tick(carry, t):
            fwd_buf, lacc = carry
            ys = []
            for c in range(vpp):
                v = c * S + i
                m_f = t - v
                mb_f = _index_microbatch(batch, jnp.clip(m_f, 0, M - 1))
                h_c = jax.tree.map(lambda x: x[c], fwd_buf)
                if c == 0:
                    h0 = preprocess_fn(params, mb_f)
                    h_c = _select(i == 0, h0, h_c) if pipelined else h0
                y_c, aux_c = _stage(params, h_c, c, t)
                fwd_valid = (m_f >= 0) & (m_f < M)
                lacc = lacc + jnp.where(fwd_valid,
                                        aux_c.astype(jnp.float32), 0.0)
                ys.append(y_c)
                if c == vpp - 1:
                    m_out = t - (V - 1)
                    mb_out = _index_microbatch(
                        batch, jnp.clip(m_out, 0, M - 1))
                    l = postprocess_fn(params, y_c, mb_out)
                    take = ((i == S - 1) & (m_out >= 0) & (m_out < M))
                    lacc = lacc + jnp.where(take, l.astype(jnp.float32), 0.0)
            y = jax.tree.map(lambda *xs: jnp.stack(xs), *ys)
            arrived = ring_shift(y, axis_name=axis_name) if pipelined else y
            # wrap into rank 0 climbs one chunk (stage c*S+S-1 -> (c+1)*S)
            rolled = jax.tree.map(lambda a: jnp.roll(a, 1, axis=0), arrived)
            fwd_buf = (_select(i == 0, rolled, arrived) if pipelined
                       else rolled)
            return (fwd_buf, lacc), None

        (_, lacc), _ = lax.scan(
            tick, (buf0, jnp.zeros((), jnp.float32)),
            jnp.arange(M + V - 1))
        loss = lacc / M
        return lax.psum(loss, axis_name) if pipelined else loss

    # -- fused forward+backward ---------------------------------------------

    def _fwd_bwd(params, batch):
        pipelined, S, i = _axis_info(axis_name)
        V = S * vpp
        B = 2 * (V - 1) + 1            # per-chunk in-flight input cap
        drain = 2 * (V - 1)
        mb0 = _index_microbatch(batch, 0)
        h_shape = jax.eval_shape(preprocess_fn, params, mb0)
        buf0 = jax.tree.map(
            lambda s: jnp.zeros((vpp,) + s.shape, s.dtype), h_shape)
        stash0 = jax.tree.map(
            lambda s: jnp.zeros((vpp, B) + s.shape, s.dtype), h_shape)
        gacc0 = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        bgacc0, _accum_batch_grads = _init_batch_grads(batch)

        def tick(carry, t):
            fwd_buf, bwd_buf, stash, gacc, bgacc, lacc = carry
            ys, ghs = [], []
            for c in range(vpp):
                v = c * S + i

                # ---- forward: microbatch m_f = t - v ----
                m_f = t - v
                fwd_valid = (m_f >= 0) & (m_f < M)
                mb_f = _index_microbatch(batch, jnp.clip(m_f, 0, M - 1))
                h_c = jax.tree.map(lambda x: x[c], fwd_buf)
                if c == 0:
                    h0 = preprocess_fn(params, mb_f)
                    h_c = _select(i == 0, h0, h_c) if pipelined else h0
                slot_f = jnp.clip(m_f, 0, None) % B
                written = jax.tree.map(
                    lambda s, h: lax.dynamic_update_index_in_dim(
                        s, h, slot_f, 0),
                    jax.tree.map(lambda s: s[c], stash), h_c)
                stash = jax.tree.map(
                    lambda s, w: lax.dynamic_update_index_in_dim(
                        s, jnp.where(fwd_valid, w, s[c]), c, 0),
                    stash, written)
                y_c, aux_c = _stage(params, h_c, c, t)
                lacc = lacc + jnp.where(fwd_valid,
                                        aux_c.astype(jnp.float32), 0.0)
                ys.append(y_c)

                # ---- backward: microbatch m_b = t - 2(V-1) + v ----
                m_b = t - drain + v
                bwd_valid = (m_b >= 0) & (m_b < M)
                m_b_c = jnp.clip(m_b, 0, M - 1)
                mb_b = _index_microbatch(batch, m_b_c)
                slot_b = jnp.clip(m_b, 0, None) % B
                h_in_b = jax.tree.map(
                    lambda s: lax.dynamic_index_in_dim(
                        s[c], slot_b, 0, keepdims=False), stash)
                tick_b = m_b + v
                (y_b, aux_b), vjp_stage = jax.vjp(
                    lambda p, h: _stage(p, h, c, tick_b), params, h_in_b)
                g_p_post = g_mb_post = None
                if c == vpp - 1:
                    l, vjp_post = jax.vjp(
                        lambda h, p, mb: postprocess_fn(p, h, mb),
                        y_b, params, mb_b)
                    seed = jnp.where((i == S - 1) & bwd_valid,
                                     1.0 / M, 0.0).astype(l.dtype)
                    g_y_post, g_p_post, g_mb_post = vjp_post(seed)
                    g_y = (_select(i == S - 1, g_y_post,
                                   jax.tree.map(lambda x: x[c], bwd_buf))
                           if pipelined else g_y_post)
                    lacc = lacc + jnp.where((i == S - 1) & bwd_valid,
                                            l.astype(jnp.float32), 0.0)
                else:
                    g_y = jax.tree.map(lambda x: x[c], bwd_buf)
                g_y = _select(bwd_valid, g_y, _zeros_of(g_y))
                aux_seed = jnp.where(bwd_valid,
                                     1.0 / M, 0.0).astype(aux_b.dtype)
                g_p_stage, g_h = vjp_stage((g_y, aux_seed))
                ghs.append(g_h)
                contribs = [g_p_stage]
                if g_p_post is not None:
                    contribs.append(g_p_post)
                mb_contribs = []
                if g_mb_post is not None:
                    mb_contribs.append(g_mb_post)
                if c == 0:
                    _, vjp_pre = jax.vjp(
                        lambda p, mb: preprocess_fn(p, mb), params, mb_b)
                    g_p_pre, g_mb_pre = vjp_pre(
                        _select(i == 0, g_h, _zeros_of(g_h))
                        if pipelined else g_h)
                    contribs.append(g_p_pre)
                    mb_contribs.append(g_mb_pre)
                gacc = jax.tree.map(
                    lambda a, *gs: a + sum(g.astype(jnp.float32)
                                           for g in gs),
                    gacc, *contribs)
                if bgacc is not None and mb_contribs:
                    bgacc = _accum_batch_grads(bgacc, m_b_c, *mb_contribs)

            # ---- comms: both buffers move, with chunk rolls at the wraps
            y = jax.tree.map(lambda *xs: jnp.stack(xs), *ys)
            gh = jax.tree.map(lambda *xs: jnp.stack(xs), *ghs)
            if pipelined:
                arrived = ring_shift(y, axis_name=axis_name)
                rolled = jax.tree.map(lambda a: jnp.roll(a, 1, axis=0),
                                      arrived)
                fwd_buf = _select(i == 0, rolled, arrived)
                # cotangent of chunk c at rank 0 (stage c*S) feeds chunk
                # c-1 at rank S-1 (stage c*S - 1): reverse hop + roll -1
                arr_b = ring_shift(gh, reverse=True, axis_name=axis_name)
                rolled_b = jax.tree.map(lambda a: jnp.roll(a, -1, axis=0),
                                        arr_b)
                bwd_buf = _select(i == S - 1, rolled_b, arr_b)
            else:
                # single rank: stage c feeds c+1 directly (roll +1), and
                # cotangent of chunk c feeds chunk c-1 (roll -1)
                fwd_buf = jax.tree.map(lambda a: jnp.roll(a, 1, axis=0), y)
                bwd_buf = jax.tree.map(lambda a: jnp.roll(a, -1, axis=0), gh)
            return (fwd_buf, bwd_buf, stash, gacc, bgacc, lacc), None

        carry0 = (buf0, buf0, stash0, gacc0, bgacc0,
                  jnp.zeros((), jnp.float32))
        (_, _, _, gacc, bgacc, lacc), _ = lax.scan(
            tick, carry0, jnp.arange(M + drain))
        loss = lacc / M
        if pipelined:
            loss = lax.psum(loss, axis_name)
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), gacc, params)
        return loss, grads, _finalize_batch_grads(bgacc, batch)

    return _wrap_custom_vjp(_forward_only, _fwd_bwd)


def forward_backward_pipelining_with_interleaving(
    forward_step_func: Any,
    batch: Any,
    params: Any,
    *,
    num_microbatches: int,
    virtual_pipeline_size: Optional[int] = None,
    forward_only: bool = False,
    grad_scaler: Optional[Callable] = None,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
):
    """Reference-shaped driver; see the non-interleaved counterpart.

    ``virtual_pipeline_size`` defaults to the registered virtual world size
    (``parallel_state.set_virtual_pipeline_model_parallel_world_size`` /
    ``initialize_model_parallel(virtual_pipeline_model_parallel_size=...)``),
    keeping this callable signature-compatible with the other schedules the
    selector can return.
    """
    if virtual_pipeline_size is None:
        from apex_tpu.transformer import parallel_state
        virtual_pipeline_size = (
            parallel_state.get_virtual_pipeline_model_parallel_world_size())
        if virtual_pipeline_size is None:
            raise ValueError(
                "virtual_pipeline_size not given and no virtual pipeline "
                "world size is registered in parallel_state")
    preprocess_fn, stage_fn, postprocess_fn = forward_step_func
    loss_fn = make_interleaved_pipelined_loss_fn(
        preprocess_fn, stage_fn, postprocess_fn, num_microbatches,
        virtual_pipeline_size, axis_name=axis_name, remat=remat)
    if forward_only:
        return loss_fn(params, batch), None
    if grad_scaler is None:
        return jax.value_and_grad(loss_fn)(params, batch)

    def scaled(p, b):
        loss = loss_fn(p, b)
        return grad_scaler(loss), loss  # differentiate scaled, report unscaled

    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params, batch)
    return loss, grads
