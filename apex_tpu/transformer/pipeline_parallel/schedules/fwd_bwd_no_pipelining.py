"""No-pipelining schedule: sequential microbatches with grad accumulation.

Reference: ``apex/transformer/pipeline_parallel/schedules/
fwd_bwd_no_pipelining.py:23-124`` — runs every microbatch's forward+backward
under ``no_sync`` (deferring the DP grad allreduce), then the last microbatch
with sync on. On TPU the deferral is structural: grads are accumulated inside
a ``lax.scan`` and the data-parallel ``pmean`` happens once, in the train
step, after this function returns.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["forward_backward_no_pipelining"]


def forward_backward_no_pipelining(
    forward_step_func: Callable,
    batch: Any,
    params: Any,
    *,
    num_microbatches: int,
    forward_only: bool = False,
    grad_scaler: Optional[Callable] = None,
):
    """Run ``num_microbatches`` sequential fwd(+bwd) steps, accumulating.

    Args:
      forward_step_func: ``(params, microbatch) -> scalar loss`` — the analog
        of the reference's ``forward_step_func(batch, model)`` returning
        ``(output, loss_func)`` (``schedules/common.py:253-309``); here the
        loss reduction is folded in.
      batch: pytree whose leaves have leading dim ``num_microbatches``
        (microbatch-major; build with
        :func:`apex_tpu.transformer.pipeline_parallel.utils.split_batch_into_microbatches`).
      params: parameter pytree.
      grad_scaler: optional fn applied to each microbatch loss before
        differentiation (the reference scales on the last stage,
        ``schedules/common.py:378-379``).

    Returns:
      ``(mean_loss, grads)`` with grads averaged over microbatches, or
      ``(mean_loss, None)`` when ``forward_only``.
    """

    def scaled_loss(p, mb):
        loss = forward_step_func(p, mb)
        scaled = grad_scaler(loss) if grad_scaler is not None else loss
        return scaled, loss  # differentiate scaled, report unscaled

    if forward_only:
        def fwd_body(acc, mb):
            return acc + forward_step_func(params, mb), None

        total, _ = lax.scan(fwd_body, jnp.zeros(()), batch)
        return total / num_microbatches, None

    grad_fn = jax.value_and_grad(scaled_loss, has_aux=True)

    def body(carry, mb):
        loss_acc, grad_acc = carry
        (_, loss), grads = grad_fn(params, mb)
        grad_acc = jax.tree.map(jnp.add, grad_acc, grads)
        return (loss_acc + loss, grad_acc), None

    zero_grads = jax.tree.map(jnp.zeros_like, params)
    (loss_sum, grad_sum), _ = lax.scan(
        body, (jnp.zeros(()), zero_grads), batch)
    inv = 1.0 / num_microbatches
    grads = jax.tree.map(lambda g: g * jnp.asarray(inv, g.dtype), grad_sum)
    return loss_sum * inv, grads
