"""Non-interleaved pipelined schedule (the 1F1B capability).

Reference: ``apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py:241-597`` — warmup forwards,
steady-state 1F1B with fused ``send_forward_recv_backward``, cooldown
backwards, all driven eagerly per-rank with NCCL p2p. The defining property
of 1F1B is its memory bound: each stage holds at most O(pipeline-depth)
in-flight activations, *independent of the number of microbatches*.

TPU design — synchronous 1F1B under one ``lax.scan``:

Every tick, every stage does one forward AND one backward (for different
microbatches), then the ring does one ``ppermute`` in each direction
(activations stage i -> i+1, cotangents i+1 -> i) — the lock-step statement
of the reference's fused ``send_forward_recv_backward`` steady state. The
wavefront schedule on stage ``i`` of ``S``:

- forward of microbatch ``m`` at tick ``t = m + i``;
- backward of microbatch ``m`` at tick ``t = m + 2(S-1) - i``
  (the loss cotangent is born on the last stage at ``m + S - 1`` and rides
  ``S-1-i`` reverse hops back).

Total ticks ``T = M + 2(S-1)`` — the same ``2(S-1)``-tick bubble as the
reference's warmup+cooldown. Stage ``i`` keeps a circular stash of its
in-flight *input* activations, at most ``2(S-1)+1`` entries — the in-flight
cap (the reference's ``num_warmup_microbatches`` bound, ``:241-597``);
memory is flat in M. The backward recomputes each stage forward from the
stashed input (``jax.vjp``) — full activation recompute, the
``tensor_parallel/random.py:~240-311`` checkpoint story, traded for the
O(S) memory bound.

Because the backward is *explicit* (grads accumulated in the same scan), the
whole schedule is wrapped in ``jax.custom_vjp``: ``loss_fn`` still composes
with ``jax.value_and_grad``/``make_train_step``, but differentiation returns
the 1F1B-accumulated grads instead of autodiffing through the scan (which
would buffer O(M) carries). Forward-only calls run a lean forward pipeline
with streamed losses (no stash, no vjps).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.p2p_communication import ring_shift
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = [
    "make_pipelined_loss_fn",
    "forward_backward_pipelining_without_interleaving",
]


def _index_microbatch(batch: Any, m) -> Any:
    return jax.tree.map(
        lambda x: lax.dynamic_index_in_dim(x, m, 0, keepdims=False), batch)


def _select(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _zeros_of(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def _zero_cotangent(batch):
    """Cotangents for the (non-differentiable) batch: float0 for integer
    leaves, zeros for float leaves."""
    def one(x):
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return jnp.zeros_like(x)
        return np.zeros(x.shape, jax.dtypes.float0)
    return jax.tree.map(one, batch)


def _axis_info(axis_name: str):
    pipelined = axis_bound(axis_name)
    S = axis_size(axis_name) if pipelined else 1
    i = lax.axis_index(axis_name) if pipelined else 0
    return pipelined, S, i


def _init_batch_grads(batch):
    """(bgacc0 | None, accum_fn) — input-cotangent accumulators for the
    float leaves of ``batch`` (int leaves hold a dummy scalar; the common
    all-int GPT batch allocates nothing). Shared by both 1F1B schedules."""
    has_float = any(jnp.issubdtype(x.dtype, jnp.inexact)
                    for x in jax.tree_util.tree_leaves(batch))
    if not has_float:
        return None, None
    bgacc0 = jax.tree.map(
        lambda x: (jnp.zeros(x.shape, jnp.float32)
                   if jnp.issubdtype(x.dtype, jnp.inexact) else
                   jnp.zeros((), jnp.float32)), batch)

    def accum(bgacc, m, *contribs):
        """Add per-microbatch input-grad contributions into slot ``m`` of
        the [M, ...]-shaped accumulators (float0 cotangents of int leaves
        are dropped)."""
        def one(acc, x, *gs):
            if not jnp.issubdtype(x.dtype, jnp.inexact):
                return acc
            total = sum((g.astype(jnp.float32) for g in gs),
                        jnp.zeros(x.shape[1:], jnp.float32))
            cur = lax.dynamic_index_in_dim(acc, m, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(acc, cur + total, m, 0)
        return jax.tree.map(one, bgacc, batch, *contribs)

    return bgacc0, accum


def _finalize_batch_grads(bgacc, batch):
    if bgacc is None:
        return None
    return jax.tree.map(
        lambda a, x: (a.astype(x.dtype)
                      if jnp.issubdtype(x.dtype, jnp.inexact)
                      else np.zeros(x.shape, jax.dtypes.float0)),
        bgacc, batch)


def _wrap_custom_vjp(forward_only_fn, fwd_bwd_fn):
    """Build the custom_vjp'd ``loss_fn(params, batch)`` both schedules
    share: primal = lean forward pipeline; differentiation returns the
    explicitly 1F1B-accumulated grads (params and float batch leaves)."""

    @jax.custom_vjp
    def loss_fn(params, batch):
        return forward_only_fn(params, batch)

    def _vjp_fwd(params, batch):
        loss, grads, bgrads = fwd_bwd_fn(params, batch)
        return loss, (grads, bgrads, batch)

    def _vjp_bwd(res, g):
        grads, bgrads, batch = res
        if bgrads is None:
            bg = _zero_cotangent(batch)
        else:
            bg = jax.tree.map(
                lambda x, orig: (x * g.astype(x.dtype)
                                 if jnp.issubdtype(orig.dtype, jnp.inexact)
                                 else x),
                bgrads, batch)
        return (jax.tree.map(lambda x: x * g.astype(x.dtype), grads), bg)

    loss_fn.defvjp(_vjp_fwd, _vjp_bwd)
    return loss_fn


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _broadcast_last_stage_loss(x, axis_name: str):
    """psum in the forward (replicating the last stage's masked loss to every
    rank), identity in the backward. Used by the autodiff-derived interleaved
    schedule: a plain ``psum`` would S-fold the gradients — per-rank autodiff
    seeds a cotangent of 1.0 on every rank's (identical) output and psum's
    transpose sums them; the last-stage mask already routes the single real
    cotangent, so the broadcast must be gradient-transparent."""
    return lax.psum(x, axis_name)


def _bcast_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _bcast_bwd(axis_name, _, g):
    return (g,)


_broadcast_last_stage_loss.defvjp(_bcast_fwd, _bcast_bwd)


def make_pipelined_loss_fn(
    preprocess_fn: Callable,
    stage_fn: Callable,
    postprocess_fn: Callable,
    num_microbatches: int,
    *,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
    stage_aux: bool = False,
) -> Callable:
    """Build ``loss_fn(params, batch) -> scalar`` running the 1F1B pipeline.

    Args:
      preprocess_fn: ``(params, microbatch) -> hidden`` — the first-stage
        input transform (embedding). Runs one microbatch per tick; only
        stage 0's result feeds the pipeline (its backward is seeded only on
        stage 0).
      stage_fn: ``(params, hidden, tick) -> hidden`` — applies this rank's
        layer chunk. Must be shape-preserving (homogeneous stages, the same
        constraint the reference's ``tensor_shape`` argument encodes).
        ``tick`` identifies the forward slot for dropout-stream purposes;
        the backward recompute replays the identical tick value.
      postprocess_fn: ``(params, hidden, microbatch) -> scalar`` — final
        norm + head + loss for one microbatch, streamed on the last stage.
      num_microbatches: M. Must be known statically (it sizes the scan).
      remat: accepted for API parity; the 1F1B backward *always* recomputes
        stage activations from the stashed inputs (that recompute is what
        buys the O(pipeline-depth) memory bound).
      stage_aux: when True, ``stage_fn`` returns ``(hidden, aux)`` with
        ``aux`` a pre-scaled scalar loss term (the MoE load-balancing
        loss): every rank's aux for every microbatch is added into the
        total loss, and the 1F1B backward seeds each stage's aux cotangent
        directly (the aux reaches the loss without riding the pipeline).

    The returned function must run inside ``shard_map`` with ``axis_name``
    bound (at world size 1 it degrades to sequential microbatching with
    per-microbatch backward — same flat memory). It composes with
    ``jax.value_and_grad``: differentiation returns the explicitly
    accumulated 1F1B grads via ``jax.custom_vjp``.
    """
    del remat  # the backward always recomputes; see docstring
    M = num_microbatches

    def _stage(params, h, t):
        out = stage_fn(params, h, t)
        return out if stage_aux else (out, jnp.zeros((), jnp.float32))

    # -- forward-only pipeline (primal when not differentiated) -------------

    def _forward_only(params, batch):
        pipelined, S, i = _axis_info(axis_name)
        mb0 = _index_microbatch(batch, 0)
        h_shape = jax.eval_shape(preprocess_fn, params, mb0)
        state0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), h_shape)

        def tick(carry, t):
            state, lacc = carry
            m_f = t - i
            mb_f = _index_microbatch(batch, jnp.clip(m_f, 0, M - 1))
            h0 = preprocess_fn(params, mb_f)
            h_in = _select(i == 0, h0, state) if pipelined else h0
            y, aux = _stage(params, h_in, t)
            fwd_valid = (m_f >= 0) & (m_f < M)
            lacc = lacc + jnp.where(fwd_valid, aux.astype(jnp.float32), 0.0)
            m_out = t - (S - 1)
            mb_out = _index_microbatch(batch, jnp.clip(m_out, 0, M - 1))
            l = postprocess_fn(params, y, mb_out)
            take = (i == S - 1) & (m_out >= 0) & (m_out < M)
            lacc = lacc + jnp.where(take, l.astype(jnp.float32), 0.0)
            state = ring_shift(y, axis_name=axis_name) if pipelined else y
            return (state, lacc), None

        (_, lacc), _ = lax.scan(
            tick, (state0, jnp.zeros((), jnp.float32)),
            jnp.arange(M + S - 1))
        loss = lacc / M
        # only the last stage accumulated real losses; psum replicates
        # (reference: losses live on the last stage only, ``:597``)
        return lax.psum(loss, axis_name) if pipelined else loss

    # -- fused forward+backward 1F1B (differentiation path) -----------------

    def _fwd_bwd(params, batch):
        pipelined, S, i = _axis_info(axis_name)
        B = 2 * (S - 1) + 1            # in-flight input-activation cap
        drain = 2 * (S - 1)            # bubble ticks (warmup + cooldown)
        mb0 = _index_microbatch(batch, 0)
        h_shape = jax.eval_shape(preprocess_fn, params, mb0)
        zeros_h = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), h_shape)
        stash0 = jax.tree.map(
            lambda s: jnp.zeros((B,) + s.shape, s.dtype), h_shape)
        gacc0 = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        bgacc0, _accum_batch_grads = _init_batch_grads(batch)

        def tick(carry, t):
            fwd_state, bwd_state, stash, gacc, bgacc, lacc = carry

            # ---- forward half: microbatch m_f = t - i ----
            m_f = t - i
            fwd_valid = (m_f >= 0) & (m_f < M)
            mb_f = _index_microbatch(batch, jnp.clip(m_f, 0, M - 1))
            h0 = preprocess_fn(params, mb_f)
            h_in = _select(i == 0, h0, fwd_state) if pipelined else h0
            slot_f = jnp.clip(m_f, 0, None) % B
            written = jax.tree.map(
                lambda s, h: lax.dynamic_update_index_in_dim(s, h, slot_f, 0),
                stash, h_in)
            stash = _select(fwd_valid, written, stash)
            y, aux = _stage(params, h_in, t)
            lacc = lacc + jnp.where(fwd_valid, aux.astype(jnp.float32), 0.0)

            # ---- backward half: microbatch m_b = t - 2(S-1) + i ----
            m_b = t - drain + i
            bwd_valid = (m_b >= 0) & (m_b < M)
            m_b_c = jnp.clip(m_b, 0, M - 1)
            mb_b = _index_microbatch(batch, m_b_c)
            slot_b = jnp.clip(m_b, 0, None) % B
            h_in_b = jax.tree.map(
                lambda s: lax.dynamic_index_in_dim(s, slot_b, 0,
                                                   keepdims=False), stash)
            tick_b = m_b + i           # the tick this forward originally ran
            (y_b, aux_b), vjp_stage = jax.vjp(
                lambda p, h: _stage(p, h, tick_b), params, h_in_b)
            l, vjp_post = jax.vjp(
                lambda h, p, mb: postprocess_fn(p, h, mb), y_b, params, mb_b)
            # loss cotangent born on the last stage (1/M for the mean)
            seed = jnp.where((i == S - 1) & bwd_valid,
                             1.0 / M, 0.0).astype(l.dtype)
            g_y_post, g_p_post, g_mb_post = vjp_post(seed)
            g_y = (_select(i == S - 1, g_y_post, bwd_state)
                   if pipelined else g_y_post)
            g_y = _select(bwd_valid, g_y, _zeros_of(g_y))
            # aux joins the loss as sum(aux)/M on every rank: seed 1/M
            aux_seed = jnp.where(bwd_valid, 1.0 / M, 0.0).astype(aux_b.dtype)
            g_p_stage, g_h = vjp_stage((g_y, aux_seed))
            # preprocess backward, seeded only on stage 0
            _, vjp_pre = jax.vjp(
                lambda p, mb: preprocess_fn(p, mb), params, mb_b)
            g_p_pre, g_mb_pre = vjp_pre(
                _select(i == 0, g_h, _zeros_of(g_h)) if pipelined else g_h)

            gacc = jax.tree.map(
                lambda a, s_, p_, e: a + s_.astype(jnp.float32)
                + p_.astype(jnp.float32) + e.astype(jnp.float32),
                gacc, g_p_stage, g_p_post, g_p_pre)
            if bgacc is not None:
                # contributions are zero off-stage/off-schedule (linear vjps
                # of zero seeds); bubble ticks add zeros into a clipped slot
                bgacc = _accum_batch_grads(bgacc, m_b_c, g_mb_pre, g_mb_post)
            lacc = lacc + jnp.where((i == S - 1) & bwd_valid,
                                    l.astype(jnp.float32), 0.0)

            # ---- ring comms: activations down, cotangents up ----
            if pipelined:
                fwd_state = ring_shift(y, axis_name=axis_name)
                bwd_state = ring_shift(g_h, reverse=True, axis_name=axis_name)
            return (fwd_state, bwd_state, stash, gacc, bgacc, lacc), None

        carry0 = (zeros_h, zeros_h, stash0, gacc0, bgacc0,
                  jnp.zeros((), jnp.float32))
        (_, _, _, gacc, bgacc, lacc), _ = lax.scan(
            tick, carry0, jnp.arange(M + drain))
        loss = lacc / M
        if pipelined:
            loss = lax.psum(loss, axis_name)
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), gacc, params)
        return loss, grads, _finalize_batch_grads(bgacc, batch)

    return _wrap_custom_vjp(_forward_only, _fwd_bwd)


def forward_backward_pipelining_without_interleaving(
    forward_step_func: Any,
    batch: Any,
    params: Any,
    *,
    num_microbatches: int,
    forward_only: bool = False,
    grad_scaler: Optional[Callable] = None,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
):
    """Reference-shaped driver (``fwd_bwd_pipelining_without_interleaving.py:
    241``): returns ``(loss, grads)`` (grads ``None`` when ``forward_only``).

    ``forward_step_func`` here is the triple ``(preprocess_fn, stage_fn,
    postprocess_fn)`` — the stage decomposition the reference gets implicitly
    from which ``nn.Module`` chunk lives on each rank (``build_model``,
    ``schedules/common.py:30-150``).
    """
    preprocess_fn, stage_fn, postprocess_fn = forward_step_func
    loss_fn = make_pipelined_loss_fn(
        preprocess_fn, stage_fn, postprocess_fn, num_microbatches,
        axis_name=axis_name, remat=remat)
    if forward_only:
        return loss_fn(params, batch), None
    if grad_scaler is None:
        return jax.value_and_grad(loss_fn)(params, batch)

    def scaled(p, b):
        loss = loss_fn(p, b)
        return grad_scaler(loss), loss  # differentiate scaled, report unscaled

    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params, batch)
    return loss, grads
