"""Non-interleaved pipelined schedule (the 1F1B capability).

Reference: ``apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py:241-597`` — warmup forwards,
steady-state 1F1B with fused ``send_forward_recv_backward``, cooldown
backwards, all driven eagerly per-rank with NCCL p2p.

TPU design: the forward pipeline is a ``lax.scan`` over ``M + S - 1`` ticks.
Per tick every stage applies its layer chunk to the activation it holds, then
the whole ring does one ``ppermute`` shift (exactly the lock-step p2p pattern
of the reference's steady state). Stage 0 injects microbatch ``t`` at tick
``t``; stage ``S-1``'s output at tick ``t`` is microbatch ``t - (S-1)`` and is
collected into an output buffer. The loss is computed once, batched over all
collected microbatch outputs, masked to the last stage, and ``psum``-reduced.

The backward schedule is **derived, not written**: ``jax.grad`` through the
scan produces the reverse pipeline (the VJP of ``ppermute`` is the opposite
ring shift), with per-tick stage recompute under ``jax.checkpoint`` bounding
live activations — the role 1F1B's in-flight-microbatch cap plays in the
reference.

Stages run redundant compute during bubble ticks (zeros flow through); that is
the pipeline bubble made explicit — the same ``(S-1)/M`` overhead the
reference pays in idle waits.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.p2p_communication import ring_shift
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

__all__ = [
    "make_pipelined_loss_fn",
    "forward_backward_pipelining_without_interleaving",
]


def _index_microbatch(batch: Any, m) -> Any:
    return jax.tree.map(
        lambda x: lax.dynamic_index_in_dim(x, m, 0, keepdims=False), batch)


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _broadcast_last_stage_loss(x, axis_name: str):
    """psum in the forward (replicating the last stage's masked loss to every
    rank), identity in the backward.

    A plain ``psum`` here would S-fold the gradients: per-rank autodiff seeds
    a cotangent of 1.0 on *every* rank's (identical) output and psum's
    transpose sums them. The last-stage mask already routes the single real
    cotangent, so the broadcast must be gradient-transparent."""
    return lax.psum(x, axis_name)


def _bcast_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _bcast_bwd(axis_name, _, g):
    return (g,)


_broadcast_last_stage_loss.defvjp(_bcast_fwd, _bcast_bwd)


def make_pipelined_loss_fn(
    preprocess_fn: Callable,
    stage_fn: Callable,
    postprocess_fn: Callable,
    num_microbatches: int,
    *,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
) -> Callable:
    """Build ``loss_fn(params, batch) -> scalar`` running the pipeline.

    Args:
      preprocess_fn: ``(params, microbatch) -> hidden`` — the first-stage
        input transform (embedding). Evaluated batched over all microbatches
        up front; only stage 0's copy feeds the pipeline (other stages'
        results carry zero gradient through the injection select).
      stage_fn: ``(params, hidden, tick) -> hidden`` — applies this rank's
        layer chunk. Must be shape-preserving (homogeneous stages, the same
        constraint the reference's ``tensor_shape`` argument encodes).
      postprocess_fn: ``(params, hidden, microbatch) -> scalar`` — final norm
        + head + loss for one microbatch. Evaluated batched after the loop;
        only the last stage's value survives the mask.
      num_microbatches: M. Must be known statically (it sizes the scan).
      remat: wrap ``stage_fn`` in ``jax.checkpoint`` so the backward pipeline
        recomputes stage activations instead of storing every tick's
        intermediates (the activation-recompute story of
        ``tensor_parallel/random.py:~240-311``).

    The returned function must run inside ``shard_map`` with ``axis_name``
    bound (at world size 1 it degrades to sequential microbatching).
    """
    M = num_microbatches

    def loss_fn(params, batch):
        staged = jax.checkpoint(stage_fn) if remat else stage_fn

        pipelined = axis_bound(axis_name)
        S = lax.axis_size(axis_name) if pipelined else 1
        i = lax.axis_index(axis_name) if pipelined else 0

        # Embed all microbatches batched (one big MXU-friendly gather) rather
        # than per tick.
        injected = jax.vmap(lambda mb: preprocess_fn(params, mb))(batch)
        state0 = jax.tree.map(lambda x: jnp.zeros_like(x[0]), injected)
        outbuf0 = jax.tree.map(jnp.zeros_like, injected)

        def tick(carry, t):
            state, outbuf = carry
            m_in = jnp.clip(t, 0, M - 1)
            inj = _index_microbatch(injected, m_in)
            h = (jax.tree.map(lambda a, b: jnp.where(i == 0, a, b), inj, state)
                 if pipelined else inj)
            y = staged(params, h, t)
            # stage S-1's tick-t output is microbatch t-(S-1); bubble ticks
            # (m_out < 0) write garbage into slot 0, overwritten at t = S-1.
            m_out = jnp.clip(t - (S - 1), 0, M - 1)
            outbuf = jax.tree.map(
                lambda buf, leaf: lax.dynamic_update_index_in_dim(
                    buf, leaf, m_out, 0), outbuf, y)
            state = ring_shift(y, axis_name=axis_name) if pipelined else y
            return (state, outbuf), None

        (_, outbuf), _ = lax.scan(
            tick, (state0, outbuf0), jnp.arange(M + S - 1))

        losses = jax.vmap(
            lambda y, mb: postprocess_fn(params, y, mb))(outbuf, batch)
        local = jnp.mean(losses)
        if not pipelined:
            return local
        # only the last stage holds real outputs; broadcast the masked value
        # so every rank returns the same scalar (reference: losses live on
        # the last stage only, ``:597``, then are broadcast by the caller).
        return _broadcast_last_stage_loss(
            jnp.where(i == S - 1, local, 0.0), axis_name)

    return loss_fn


def forward_backward_pipelining_without_interleaving(
    forward_step_func: Any,
    batch: Any,
    params: Any,
    *,
    num_microbatches: int,
    forward_only: bool = False,
    grad_scaler: Optional[Callable] = None,
    axis_name: str = PIPELINE_AXIS,
    remat: bool = True,
):
    """Reference-shaped driver (``fwd_bwd_pipelining_without_interleaving.py:
    241``): returns ``(loss, grads)`` (grads ``None`` when ``forward_only``).

    ``forward_step_func`` here is the triple ``(preprocess_fn, stage_fn,
    postprocess_fn)`` — the stage decomposition the reference gets implicitly
    from which ``nn.Module`` chunk lives on each rank (``build_model``,
    ``schedules/common.py:30-150``).
    """
    preprocess_fn, stage_fn, postprocess_fn = forward_step_func
    loss_fn = make_pipelined_loss_fn(
        preprocess_fn, stage_fn, postprocess_fn, num_microbatches,
        axis_name=axis_name, remat=remat)
    if forward_only:
        return loss_fn(params, batch), None
    if grad_scaler is None:
        return jax.value_and_grad(loss_fn)(params, batch)

    def scaled(p, b):
        loss = loss_fn(p, b)
        return grad_scaler(loss), loss  # differentiate scaled, report unscaled

    (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params, batch)
    return loss, grads
