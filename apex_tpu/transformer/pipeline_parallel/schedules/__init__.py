"""Schedule selector.

Reference: ``apex/transformer/pipeline_parallel/schedules/__init__.py:22-35``
picks among no-pipelining / 1F1B / interleaved based on the pipeline world
size and virtual-pipeline setting. Same selection logic here.
"""

from __future__ import annotations

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_no_pipelining import (
    forward_backward_no_pipelining,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    forward_backward_pipelining_without_interleaving,
    make_pipelined_loss_fn,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_with_interleaving import (
    forward_backward_pipelining_with_interleaving,
    make_interleaved_pipelined_loss_fn,
)

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "make_pipelined_loss_fn",
    "make_interleaved_pipelined_loss_fn",
]


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size=None):
    """Reference: ``schedules/__init__.py:22-35``."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = (
            parallel_state.get_pipeline_model_parallel_world_size())
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
