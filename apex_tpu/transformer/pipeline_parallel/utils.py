"""Pipeline-parallel training utilities.

Reference: ``apex/transformer/pipeline_parallel/utils.py:58-357`` — the
microbatch-calculator singleton, batch slicing, loss averaging over the DP
group, TP-aware parameter norms, ltor mask construction, memory reporting.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import DATA_AXIS
from apex_tpu.transformer.pipeline_parallel._timers import Timers
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = [
    "setup_microbatch_calculator",
    "get_micro_batch_size",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "get_timers",
    "split_batch_into_microbatches",
    "get_kth_microbatch",
    "average_losses_across_data_parallel_group",
    "calc_params_l2_norm",
    "get_ltor_masks_and_position_ids",
    "report_memory",
    "print_rank_0",
]

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TIMERS: Optional[Timers] = None
_GLOBAL_AUTORESUME = None


def setup_microbatch_calculator(rank: int, rampup_batch_size: Optional[List[int]],
                                global_batch_size: int, micro_batch_size: int,
                                data_parallel_size: int) -> None:
    """Reference: ``pipeline_parallel/utils.py:58-78`` (singleton guard)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized.")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def _destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_num_microbatches() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_micro_batch_size() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.micro_batch_size


def get_current_global_batch_size() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True) -> None:
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(
        consumed_samples, consistency_check)


def get_timers() -> Timers:
    """Reference: ``utils.py:146-157``."""
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS


def get_autoresume():
    """Reference: ``utils.py:142-144`` (ADLR AutoResume hook; None here —
    elastic/autoresume integration is environment-specific)."""
    return _GLOBAL_AUTORESUME


def split_batch_into_microbatches(batch: Any, num_microbatches: int) -> Any:
    """Reshape each leaf ``[B, ...] -> [M, B/M, ...]`` (microbatch-major),
    the layout the schedules scan over. Analog of the reference's repeated
    ``get_kth_microbatch`` slicing (``utils.py:196-208``)."""

    def one(x):
        B = x.shape[0]
        if B % num_microbatches:
            raise ValueError(
                f"batch dim ({B}) not divisible by num_microbatches "
                f"({num_microbatches})")
        return x.reshape(num_microbatches, B // num_microbatches,
                         *x.shape[1:])

    return jax.tree.map(one, batch)


def get_kth_microbatch(batch: Optional[Any], k: int) -> Any:
    """Reference: ``utils.py:196-208`` — slice microbatch ``k`` out of a
    batch whose leaves are ``[B, ...]`` with implicit microbatch-major order."""
    if batch is None:
        return None
    return jax.tree.map(lambda x: x[k], batch)


def average_losses_across_data_parallel_group(losses,
                                              axis_name: str = DATA_AXIS):
    """Reference: ``utils.py:242-250`` — allreduce/mean losses over DP."""
    averaged = jnp.stack([jnp.asarray(l).reshape(()) for l in losses])
    if axis_bound(axis_name):
        averaged = lax.pmean(averaged, axis_name)
    return averaged


def calc_params_l2_norm(params: Any, *, tensor_axis: str = "tensor",
                        shared_specs: Any = None) -> jax.Array:
    """Global L2 norm of parameters (reference ``utils.py:~220-240``
    ``calc_params_l2_norm``; the reference skips TP-duplicated params on
    non-owner ranks so each parameter is counted once).

    ``shared_specs``: optional PartitionSpec pytree matching ``params``.
    Inside ``shard_map``, leaves whose spec does NOT mention ``tensor_axis``
    are replicated across it — their identical per-rank contribution is
    divided by the axis size so the closing ``psum`` counts them once.
    Without ``shared_specs`` every leaf is assumed axis-sharded.
    """
    if not axis_bound(tensor_axis):
        leaves = jax.tree.leaves(params)
        sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
        return jnp.sqrt(sq)

    size = axis_size(tensor_axis)
    if shared_specs is None:
        shared_flags = jax.tree.map(lambda _: False, params)
    else:
        shared_flags = jax.tree.map(
            lambda s: tensor_axis not in jax.tree.leaves(tuple(s)),
            shared_specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    sq = jnp.zeros((), jnp.float32)
    for leaf, replicated in zip(jax.tree.leaves(params),
                                jax.tree.leaves(shared_flags)):
        contrib = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        sq = sq + (contrib / size if replicated else contrib)
    return jnp.sqrt(lax.psum(sq, tensor_axis))


def get_ltor_masks_and_position_ids(data: jax.Array,
                                    eod_token: int,
                                    reset_position_ids: bool = False,
                                    reset_attention_mask: bool = False,
                                    eod_mask_loss: bool = False):
    """Left-to-right masks + position ids (reference ``utils.py:265-357``).

    Returns ``(attention_mask [b,1,s,s] bool — True = masked out,
    loss_mask [b,s] f32, position_ids [b,s] i32)``. The document-reset
    variants rebuild positions after each EOD token.
    """
    b, s = data.shape
    causal = jnp.triu(jnp.ones((s, s), jnp.bool_), k=1)
    attention_mask = jnp.broadcast_to(causal, (b, 1, s, s))

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    if reset_position_ids or reset_attention_mask:
        # segment id = number of EODs strictly before each position
        is_eod = (data == eod_token).astype(jnp.int32)
        segments = jnp.cumsum(is_eod, axis=1) - is_eod
        if reset_position_ids:
            # position within segment: global pos minus pos of segment start
            seg_change = jnp.concatenate(
                [jnp.zeros((b, 1), jnp.bool_), segments[:, 1:] != segments[:, :-1]],
                axis=1)
            start_pos = jnp.where(seg_change, position_ids, 0)
            start_of_segment = lax.associative_scan(
                jnp.maximum, start_pos, axis=1)
            position_ids = position_ids - start_of_segment
        if reset_attention_mask:
            cross_doc = segments[:, :, None] != segments[:, None, :]
            attention_mask = attention_mask | cross_doc[:, None, :, :]
    return attention_mask, loss_mask, position_ids


def report_memory(name: str) -> None:
    """Reference: ``utils.py:253-263`` — print device memory stats."""
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    giga = 1024 ** 3
    used = stats.get("bytes_in_use", 0) / giga
    peak = stats.get("peak_bytes_in_use", 0) / giga
    limit = stats.get("bytes_limit", 0) / giga
    print(f"[{name}] memory (GB) | in use: {used:.2f} | peak: {peak:.2f} "
          f"| limit: {limit:.2f}", flush=True)


def print_rank_0(message: str) -> None:
    """Reference: ``utils.py:159-168`` — JAX is single-controller per host;
    print on process index 0."""
    if jax.process_index() == 0:
        print(message, flush=True)
