"""Device-synchronized named timers.

Reference: ``apex/transformer/pipeline_parallel/_timers.py:6-83`` — named
timers that ``cuda.synchronize()`` around ``time.time()``. The TPU analog
synchronizes by blocking on outstanding device work
(``jax.block_until_ready`` has no global variant, so we block on a trivial
device op, the documented JAX idiom for a device fence).
"""

from __future__ import annotations

import time
from typing import Dict

import jax
import jax.numpy as jnp

__all__ = ["Timers"]


def _device_sync():
    jnp.zeros(()).block_until_ready()


class _Timer:
    """Reference ``_timers.py:6-48``."""

    def __init__(self, name: str):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = time.time()

    def start(self):
        assert not self.started_, "timer has already been started"
        _device_sync()
        self.start_time = time.time()
        self.started_ = True

    def stop(self):
        assert self.started_, "timer is not started"
        _device_sync()
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        started_ = self.started_
        if self.started_:
            self.stop()
        elapsed_ = self.elapsed_
        if reset:
            self.reset()
        if started_:
            self.start()
        return elapsed_


class Timers:
    """Group of timers (reference ``_timers.py:51-83``)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names, writer, iteration, normalizer=1.0, reset=False):
        assert normalizer > 0.0
        for name in names:
            value = self.timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(f"{name}-time", value, iteration)

    def log(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            elapsed_time = (
                self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer)
            string += f" | {name}: {elapsed_time:.2f}"
        print(string, flush=True)
