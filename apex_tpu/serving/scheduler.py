"""FCFS admission scheduling for the serving engine.

The queue half of continuous batching (Orca-style — PAPERS.md survey of
request-level schedulers; TorchTitan's serving siblings ship the same
split): the engine owns device state (slots, caches, jitted steps), the
scheduler owns the host-side request queue and the admission policy.

Design points:

- **FCFS, head-of-line honest**: requests are admitted strictly in
  arrival order. If the head cannot be admitted (no free slot, policy
  hook defers), nothing behind it jumps the line — fairness is the
  contract; smarter policies plug in via ``admission_hook``.
- **Bounded queue = backpressure**: ``submit`` past ``max_queue`` raises
  :class:`QueueFullError` so callers shed load at the edge instead of
  growing an unbounded host-side backlog.
- **Bucketed prefill**: prompts prefill at power-of-two padded lengths
  (:func:`bucket_for`), so the number of distinct prefill shapes — and
  therefore XLA compiles — is ``log2(max_len)``-bounded no matter how
  ragged the traffic is.
- **Decode-starvation cap**: while any slot is decoding, at most
  ``max_prefills_per_tick`` prefills are admitted per engine tick, so a
  deep queue of arrivals cannot stall in-flight requests' token cadence;
  with nothing decoding, admission bursts to fill all free slots.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, List, Optional, Tuple

from apex_tpu.serving.request import Request

__all__ = ["QueueFullError", "DeadlineExpiredError", "SchedulerConfig",
           "FCFSScheduler", "prefill_buckets", "bucket_for"]


class QueueFullError(RuntimeError):
    """The bounded admission queue is full — shed load upstream."""


class DeadlineExpiredError(RuntimeError):
    """The request's deadline had already elapsed at submit time (its
    ``arrival_ts`` is older than ``deadline_s``) — fast-fail instead of
    queuing work that can only ever finish as a timeout."""


def prefill_buckets(max_len: int) -> Tuple[int, ...]:
    """Padded prefill lengths: powers of two up to ``max_len``, plus
    ``max_len`` itself — the complete, static set of prefill shapes the
    engine can ever compile."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    buckets = []
    b = 1
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def bucket_for(length: int, max_len: int) -> int:
    """Smallest bucket that fits ``length`` tokens."""
    if length < 1:
        raise ValueError(f"length must be >= 1, got {length}")
    if length > max_len:
        raise ValueError(f"length {length} exceeds max_len {max_len}")
    for b in prefill_buckets(max_len):
        if b >= length:
            return b
    raise AssertionError("unreachable: max_len bucket fits by construction")


@dataclass
class SchedulerConfig:
    """Knobs for :class:`FCFSScheduler`.

    ``admission_hook`` is the policy extension point: called with the
    head-of-queue request right before admission; returning False defers
    it (and, FCFS, everything behind it) to a later tick — enough to
    express cost caps, per-tenant throttles, or load-aware admission
    without subclassing.
    """

    max_queue: int = 64
    #: decode-starvation cap — prefills admitted per tick while any slot
    #: is mid-decode (a tick always runs one decode step for all active
    #: slots, so in-flight requests advance at least once per tick)
    max_prefills_per_tick: int = 1
    admission_hook: Optional[Callable[[Request], bool]] = None

    def __post_init__(self):
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_prefills_per_tick < 1:
            raise ValueError(
                f"max_prefills_per_tick must be >= 1, got "
                f"{self.max_prefills_per_tick}")


@dataclass
class _Queued:
    request: Request
    submit_ts: float


class FCFSScheduler:
    """Bounded FIFO admission queue with deadline expiry."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()
        self._queue: Deque[_Queued] = deque()

    @property
    def depth(self) -> int:
        return len(self._queue)

    @property
    def queued_tokens(self) -> int:
        """Total PROMPT tokens waiting in line — the token-denominated
        companion to ``depth``. A backlog of long prompts costs far more
        prefill work than the same depth of short ones; the supervisor's
        deadline-shed projection and the fleet Router's cost estimate
        both fold this in (docs/serving.md#chunked-prefill)."""
        return sum(q.request.prompt_len for q in self._queue)

    def submit(self, request: Request, now: float) -> None:
        # deadline fast-fail: a request whose budget elapsed before it
        # reached the queue (stale arrival_ts) can only ever time out —
        # reject it at the edge instead of letting it rot in line
        start = request.arrival_ts if request.arrival_ts is not None \
            else now
        if request.deadline_s is not None and \
                now - start > request.deadline_s:
            raise DeadlineExpiredError(
                f"request {request.request_id} deadline "
                f"({request.deadline_s}s) already elapsed "
                f"{now - start - request.deadline_s:.3f}s before submit")
        if len(self._queue) >= self.config.max_queue:
            raise QueueFullError(
                f"admission queue full ({self.config.max_queue}); "
                f"request {request.request_id} rejected — retry with "
                f"backoff or raise SchedulerConfig.max_queue")
        self._queue.append(_Queued(request, start))

    def requeue_front(self, request: Request, submit_ts: float) -> None:
        """Put a popped request BACK at the head of the line, keeping its
        original ``submit_ts`` (deadline clock keeps running). Used when
        the engine discovers, after ``pop_admissible`` said yes, that the
        resources it predicted are gone (a concurrent intern-index
        eviction reshaped the page pool) — FCFS honesty demands the
        request retries from the front, not the back. Deliberately
        bypasses ``max_queue``: the request already held a queue
        position."""
        self._queue.appendleft(_Queued(request, submit_ts))

    def snapshot(self) -> List[Tuple[Request, float]]:
        """Queued (request, submit_ts) pairs in FCFS order, non-popping —
        the supervisor's restart path uses this to requeue survivors."""
        return [(q.request, q.submit_ts) for q in self._queue]

    def cancel(self, request_id: int) -> Optional[Tuple[Request, float]]:
        """Remove a still-queued request; (request, submit_ts) or None."""
        for i, q in enumerate(self._queue):
            if q.request.request_id == request_id:
                del self._queue[i]
                return q.request, q.submit_ts
        return None

    def expire(self, now: float) -> List[Tuple[Request, float]]:
        """Pop queued requests whose deadline elapsed while waiting."""
        expired, kept = [], deque()
        for q in self._queue:
            d = q.request.deadline_s
            if d is not None and now - q.submit_ts > d:
                expired.append((q.request, q.submit_ts))
            else:
                kept.append(q)
        self._queue = kept
        return expired

    def pop_admissible(self, free_slots: int, decoding: bool, *,
                       predicate: Optional[Callable[[Request], str]] = None,
                       shed: Optional[List[Tuple[Request, float]]] = None
                       ) -> List[Tuple[Request, float]]:
        """FCFS batch for this tick: up to ``free_slots`` requests, capped
        at ``max_prefills_per_tick`` while decode traffic is in flight
        (the starvation cap). Stops at the first head the admission hook
        defers — no queue jumping.

        ``predicate(request)`` refines admission per request (the
        engine's pages-aware policy): ``"admit"`` pops and admits,
        ``"defer"`` head-blocks like the admission hook (resources will
        free up — wait, FCFS honest), ``"shed"`` pops the request
        WITHOUT admitting it and appends ``(request, submit_ts)`` to the
        caller's ``shed`` list (it can never be satisfied — the caller
        records the rejection). The predicate runs after the admission
        hook and only counts admitted requests against the cap."""
        cap = free_slots
        if decoding:
            cap = min(cap, self.config.max_prefills_per_tick)
        admitted: List[Tuple[Request, float]] = []
        hook = self.config.admission_hook
        while self._queue and len(admitted) < cap:
            head = self._queue[0]
            if hook is not None and not hook(head.request):
                break
            if predicate is not None:
                verdict = predicate(head.request)
                if verdict == "defer":
                    break
                if verdict == "shed":
                    self._queue.popleft()
                    if shed is not None:
                        shed.append((head.request, head.submit_ts))
                    continue
                if verdict != "admit":
                    raise ValueError(
                        f"admission predicate must return 'admit', "
                        f"'defer', or 'shed'; got {verdict!r}")
            self._queue.popleft()
            admitted.append((head.request, head.submit_ts))
        return admitted
