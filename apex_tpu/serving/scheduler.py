"""FCFS admission scheduling for the serving engine.

The queue half of continuous batching (Orca-style — PAPERS.md survey of
request-level schedulers; TorchTitan's serving siblings ship the same
split): the engine owns device state (slots, caches, jitted steps), the
scheduler owns the host-side request queue and the admission policy.

Design points:

- **Class-aware, head-of-line honest**: each priority class
  (:data:`~apex_tpu.serving.request.PRIORITIES`) keeps its own FIFO
  lane; dispatch is strict-priority across lanes and FCFS inside one.
  A single-class workload (everything ``standard``, the default) is
  byte-identical to plain FCFS. If the selected head cannot be admitted
  (no free slot, policy hook defers), nothing jumps the line — resources
  it is waiting on will free up, so admitting around it would starve it;
  smarter policies plug in via ``admission_hook``.
- **Anti-starvation aging**: a ``batch`` head that has waited longer
  than ``batch_aging_s`` competes at ``standard`` rank, so a steady
  stream of standard traffic cannot starve batch forever. Aging never
  lifts batch above ``interactive``, and never bypasses a brownout
  admission floor (``set_admission_floor`` filters on the TRUE class).
- **Bounded queue = backpressure**: ``submit`` past ``max_queue`` raises
  :class:`QueueFullError` so callers shed load at the edge instead of
  growing an unbounded host-side backlog.
- **Bucketed prefill**: prompts prefill at power-of-two padded lengths
  (:func:`bucket_for`), so the number of distinct prefill shapes — and
  therefore XLA compiles — is ``log2(max_len)``-bounded no matter how
  ragged the traffic is.
- **Decode-starvation cap**: while any slot is decoding, at most
  ``max_prefills_per_tick`` prefills are admitted per engine tick, so a
  deep queue of arrivals cannot stall in-flight requests' token cadence;
  with nothing decoding, admission bursts to fill all free slots.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from apex_tpu.serving.request import (PRIORITIES, PRIORITY_BATCH,
                                      PRIORITY_RANK, PRIORITY_STANDARD,
                                      Request)

__all__ = ["QueueFullError", "DeadlineExpiredError", "SchedulerConfig",
           "FCFSScheduler", "prefill_buckets", "bucket_for"]


class QueueFullError(RuntimeError):
    """The bounded admission queue is full — shed load upstream."""


class DeadlineExpiredError(RuntimeError):
    """The request's deadline had already elapsed at submit time (its
    ``arrival_ts`` is older than ``deadline_s``) — fast-fail instead of
    queuing work that can only ever finish as a timeout."""


def prefill_buckets(max_len: int) -> Tuple[int, ...]:
    """Padded prefill lengths: powers of two up to ``max_len``, plus
    ``max_len`` itself — the complete, static set of prefill shapes the
    engine can ever compile."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    buckets = []
    b = 1
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def bucket_for(length: int, max_len: int) -> int:
    """Smallest bucket that fits ``length`` tokens."""
    if length < 1:
        raise ValueError(f"length must be >= 1, got {length}")
    if length > max_len:
        raise ValueError(f"length {length} exceeds max_len {max_len}")
    for b in prefill_buckets(max_len):
        if b >= length:
            return b
    raise AssertionError("unreachable: max_len bucket fits by construction")


@dataclass
class SchedulerConfig:
    """Knobs for :class:`FCFSScheduler`.

    ``admission_hook`` is the policy extension point: called with the
    head-of-queue request right before admission; returning False defers
    it (and, FCFS, everything behind it) to a later tick — enough to
    express cost caps, per-tenant throttles, or load-aware admission
    without subclassing.
    """

    max_queue: int = 64
    #: decode-starvation cap — prefills admitted per tick while any slot
    #: is mid-decode (a tick always runs one decode step for all active
    #: slots, so in-flight requests advance at least once per tick)
    max_prefills_per_tick: int = 1
    admission_hook: Optional[Callable[[Request], bool]] = None
    #: anti-starvation floor: a queued ``batch`` head older than this
    #: competes at ``standard`` rank (never above ``interactive``)
    batch_aging_s: float = 30.0

    def __post_init__(self):
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_prefills_per_tick < 1:
            raise ValueError(
                f"max_prefills_per_tick must be >= 1, got "
                f"{self.max_prefills_per_tick}")
        if self.batch_aging_s < 0.0:
            raise ValueError(
                f"batch_aging_s must be >= 0, got {self.batch_aging_s}")


@dataclass
class _Queued:
    request: Request
    submit_ts: float
    #: process-wide arrival order — totally orders requests ACROSS the
    #: per-class lanes (snapshot/restart replay arrival order exactly;
    #: requeue_front entries get negative orders so they sort first)
    order: int = 0


class FCFSScheduler:
    """Bounded, priority-class-aware admission queue with deadline
    expiry. The name survives from the single-lane original: dispatch is
    still FCFS *inside* a class, and an all-``standard`` workload
    behaves exactly as before."""

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()
        self._queues: Dict[str, Deque[_Queued]] = {
            p: deque() for p in PRIORITIES}
        self._next_order = 0
        self._next_front = -1
        #: admission floor rank — classes with a TRUE rank above this are
        #: not dispatched (brownout's "pause batch/standard" rungs)
        self._floor_rank = PRIORITY_RANK[PRIORITY_BATCH]

    def _lane(self, request: Request) -> Deque[_Queued]:
        return self._queues[request.sampling.priority]

    def _all(self) -> List[_Queued]:
        out = [q for lane in self._queues.values() for q in lane]
        out.sort(key=lambda q: q.order)
        return out

    @property
    def depth(self) -> int:
        return sum(len(lane) for lane in self._queues.values())

    @property
    def queued_tokens(self) -> int:
        """Total PROMPT tokens waiting in line — the token-denominated
        companion to ``depth``. A backlog of long prompts costs far more
        prefill work than the same depth of short ones; the supervisor's
        deadline-shed projection and the fleet Router's cost estimate
        both fold this in (docs/serving.md#chunked-prefill)."""
        return sum(q.request.prompt_len
                   for lane in self._queues.values() for q in lane)

    def queued_tokens_by_class(self) -> Dict[str, int]:
        """Queued PROMPT tokens split per priority class, so the
        supervisor can price an interactive submit's deadline-shed
        projection against only the backlog that would actually run
        ahead of it (a deep batch queue must not inflate the estimate
        for everyone)."""
        return {p: sum(q.request.prompt_len for q in lane)
                for p, lane in self._queues.items()}

    def depth_by_class(self) -> Dict[str, int]:
        """Queue depth split per priority class."""
        return {p: len(lane) for p, lane in self._queues.items()}

    def set_admission_floor(self, priority: Optional[str]) -> None:
        """Pause dispatch of classes BELOW ``priority`` (higher rank):
        the brownout ladder's "pause batch admissions" rung. ``None``
        (or ``"batch"``) restores dispatch of every class. Paused
        requests stay queued — deadline expiry still applies, and
        recovery resumes them in arrival order. The floor filters on
        a request's TRUE class, so aging cannot tunnel through it."""
        if priority is None:
            self._floor_rank = PRIORITY_RANK[PRIORITY_BATCH]
            return
        if priority not in PRIORITY_RANK:
            raise ValueError(
                f"priority must be one of {PRIORITIES} or None, "
                f"got {priority!r}")
        self._floor_rank = PRIORITY_RANK[priority]

    @property
    def admission_floor(self) -> str:
        """The lowest-ranked class currently admissible."""
        return PRIORITIES[self._floor_rank]

    def _effective_rank(self, priority: str, head: _Queued,
                        now: Optional[float]) -> int:
        rank = PRIORITY_RANK[priority]
        if (priority == PRIORITY_BATCH and now is not None
                and now - head.submit_ts > self.config.batch_aging_s):
            rank = PRIORITY_RANK[PRIORITY_STANDARD]
        return rank

    def _select_class(self, now: Optional[float]) -> Optional[str]:
        """The class whose head dispatches next: lowest effective rank
        (aging may promote a stale batch head to standard rank), oldest
        arrival on ties, honoring the admission floor."""
        best, best_key = None, None
        for p, lane in self._queues.items():
            if not lane or PRIORITY_RANK[p] > self._floor_rank:
                continue
            head = lane[0]
            key = (self._effective_rank(p, head, now), head.order)
            if best_key is None or key < best_key:
                best, best_key = p, key
        return best

    def head(self, now: Optional[float] = None
             ) -> Optional[Tuple[Request, float]]:
        """The (request, submit_ts) that ``pop_admissible`` would
        consider next, non-popping — the engine's preemption check peeks
        here to ask whether a blocked higher-class head justifies
        parking a running lower-class slot."""
        p = self._select_class(now)
        if p is None:
            return None
        head = self._queues[p][0]
        return head.request, head.submit_ts

    def submit(self, request: Request, now: float) -> None:
        # deadline fast-fail: a request whose budget elapsed before it
        # reached the queue (stale arrival_ts) can only ever time out —
        # reject it at the edge instead of letting it rot in line
        start = request.arrival_ts if request.arrival_ts is not None \
            else now
        if request.deadline_s is not None and \
                now - start > request.deadline_s:
            raise DeadlineExpiredError(
                f"request {request.request_id} deadline "
                f"({request.deadline_s}s) already elapsed "
                f"{now - start - request.deadline_s:.3f}s before submit")
        if self.depth >= self.config.max_queue:
            raise QueueFullError(
                f"admission queue full ({self.config.max_queue}); "
                f"request {request.request_id} rejected — retry with "
                f"backoff or raise SchedulerConfig.max_queue")
        self._lane(request).append(_Queued(request, start, self._next_order))
        self._next_order += 1

    def requeue_front(self, request: Request, submit_ts: float) -> None:
        """Put a popped request BACK at the head of its class lane,
        keeping its original ``submit_ts`` (deadline clock keeps
        running). Used when the engine discovers, after
        ``pop_admissible`` said yes, that the resources it predicted are
        gone (a concurrent intern-index eviction reshaped the page pool)
        — FCFS honesty demands the request retries from the front, not
        the back. Deliberately bypasses ``max_queue``: the request
        already held a queue position."""
        self._lane(request).appendleft(
            _Queued(request, submit_ts, self._next_front))
        self._next_front -= 1

    def snapshot(self) -> List[Tuple[Request, float]]:
        """Queued (request, submit_ts) pairs in arrival order across all
        classes, non-popping — the supervisor's restart path uses this
        to requeue survivors."""
        return [(q.request, q.submit_ts) for q in self._all()]

    def cancel(self, request_id: int) -> Optional[Tuple[Request, float]]:
        """Remove a still-queued request; (request, submit_ts) or None."""
        for lane in self._queues.values():
            for i, q in enumerate(lane):
                if q.request.request_id == request_id:
                    del lane[i]
                    return q.request, q.submit_ts
        return None

    def expire(self, now: float) -> List[Tuple[Request, float]]:
        """Pop queued requests whose deadline elapsed while waiting —
        including requests a brownout admission floor is holding back
        (paused does not mean immortal)."""
        dead: List[_Queued] = []
        for p, lane in self._queues.items():
            kept: Deque[_Queued] = deque()
            for q in lane:
                d = q.request.deadline_s
                if d is not None and now - q.submit_ts > d:
                    dead.append(q)
                else:
                    kept.append(q)
            self._queues[p] = kept
        dead.sort(key=lambda q: q.order)
        return [(q.request, q.submit_ts) for q in dead]

    def pop_admissible(self, free_slots: int, decoding: bool, *,
                       predicate: Optional[Callable[[Request], str]] = None,
                       shed: Optional[List[Tuple[Request, float]]] = None,
                       now: Optional[float] = None
                       ) -> List[Tuple[Request, float]]:
        """The admission batch for this tick: up to ``free_slots``
        requests, capped at ``max_prefills_per_tick`` while decode
        traffic is in flight (the starvation cap). Heads are taken in
        strict-priority order across class lanes (FCFS inside a lane,
        batch aging per ``batch_aging_s`` when ``now`` is given). Stops
        at the first head the admission hook defers — no queue jumping,
        in ANY lane: a deferred head is waiting on resources that will
        free up, and dispatching a lower class around it would invert
        the priority order the moment they do.

        ``predicate(request)`` refines admission per request (the
        engine's pages-aware policy): ``"admit"`` pops and admits,
        ``"defer"`` head-blocks like the admission hook (resources will
        free up — wait, FCFS honest), ``"shed"`` pops the request
        WITHOUT admitting it and appends ``(request, submit_ts)`` to the
        caller's ``shed`` list (it can never be satisfied — the caller
        records the rejection). The predicate runs after the admission
        hook and only counts admitted requests against the cap."""
        cap = free_slots
        if decoding:
            cap = min(cap, self.config.max_prefills_per_tick)
        admitted: List[Tuple[Request, float]] = []
        hook = self.config.admission_hook
        while len(admitted) < cap:
            p = self._select_class(now)
            if p is None:
                break
            lane = self._queues[p]
            head = lane[0]
            if hook is not None and not hook(head.request):
                break
            if predicate is not None:
                verdict = predicate(head.request)
                if verdict == "defer":
                    break
                if verdict == "shed":
                    lane.popleft()
                    if shed is not None:
                        shed.append((head.request, head.submit_ts))
                    continue
                if verdict != "admit":
                    raise ValueError(
                        f"admission predicate must return 'admit', "
                        f"'defer', or 'shed'; got {verdict!r}")
            lane.popleft()
            admitted.append((head.request, head.submit_ts))
        return admitted
