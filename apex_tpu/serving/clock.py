"""The serving clock seam — every time read in ``serving/`` and
``loadtest/`` goes through this module.

The Router/supervisor/scheduler stack is pure host-side logic, but until
this module existed it read ``time.monotonic()``/``time.time()`` directly
in ~50 places, welding the control plane to real wall-clock time. That
made every fleet experiment pay for real seconds (drain grace periods,
autoscaler cooldowns, canary windows) and made systematic exploration of
interleavings impossible — a schedule explorer cannot enumerate "what if
the cooldown expired before the drain finished" when the clock is the
kernel's.

Three reads, one seam:

- :func:`now` — the monotonic clock: durations, deadlines, cooldowns,
  drain grace. Never steps backwards; not meaningful across processes.
- :func:`wall` — the epoch clock: the ``"wall"`` stamp on telemetry
  records so events correlate across hosts and runs.
- :func:`sleep` — open-loop pacing (the loadtest runner's arrival gaps,
  the supervisor's restart backoff).

By default they delegate to :class:`SystemClock` (the real ``time``
module — production behavior is byte-identical). Under
:func:`use_clock` a :class:`VirtualClock` substitutes: time advances
only when the driver says so (``clock.advance(5.0)``), sleeps return
instantly after advancing, and a million-tick fleet scenario runs in
milliseconds of real time. This is the first leg of the ROADMAP
"million-user scheduling lab": the model checker
(:mod:`apex_tpu.analysis.mc`) and a future discrete-event simulator
both drive the REAL fleet code through this seam.

The seam is enforced statically: lint rule APX011
(:mod:`apex_tpu.analysis.rules.apx011_wall_clock`) fails the tier-1
gate on any direct ``time.time``/``time.monotonic``/``perf_counter``
read in ``serving/`` or ``loadtest/`` outside this module.

Thread-safety: the active clock is swapped under a lock, and
:class:`VirtualClock` serializes its own state — supervisor watchdog
threads may read it while the driver advances it.
"""

from __future__ import annotations

import threading
import time as _time  # the ONE sanctioned wall-clock import in serving/
from contextlib import contextmanager
from typing import Iterator, Optional

__all__ = ["Clock", "SystemClock", "VirtualClock",
           "now", "wall", "sleep", "get_clock", "use_clock"]


class Clock:
    """The time interface serving code programs against."""

    def now(self) -> float:
        """Monotonic seconds — durations, deadlines, cooldowns."""
        raise NotImplementedError

    def wall(self) -> float:
        """Epoch seconds — the ``"wall"`` stamp on telemetry records."""
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        """Pause the caller for ``seconds`` (virtually or for real)."""
        raise NotImplementedError


class SystemClock(Clock):
    """Production clock: delegates to the real ``time`` module."""

    def now(self) -> float:
        return _time.monotonic()

    def wall(self) -> float:
        return _time.time()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            _time.sleep(seconds)


class VirtualClock(Clock):
    """Deterministic clock for simulation and model checking.

    Time advances ONLY via :meth:`advance` (or a :meth:`sleep`, which
    models the caller waiting by advancing the clock and returning
    immediately). ``start``/``epoch`` pin the initial monotonic and
    wall readings so replays are bit-identical run to run.
    """

    def __init__(self, start: float = 1000.0,
                 epoch: float = 1_700_000_000.0):
        self._lock = threading.Lock()
        self._now = float(start)
        self._epoch_offset = float(epoch) - float(start)

    def now(self) -> float:
        with self._lock:
            return self._now

    def wall(self) -> float:
        with self._lock:
            return self._now + self._epoch_offset

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> float:
        """Move time forward by ``seconds`` (negative is refused — the
        monotonic contract holds for virtual time too). Returns the new
        reading."""
        if seconds < 0:
            raise ValueError(f"cannot advance a clock by {seconds}")
        with self._lock:
            self._now += float(seconds)
            return self._now


_lock = threading.Lock()
_active: Clock = SystemClock()


def get_clock() -> Clock:
    """The process-wide active clock (a :class:`SystemClock` unless a
    driver installed a virtual one via :func:`use_clock`)."""
    return _active


def now() -> float:
    """Monotonic seconds from the active clock."""
    return _active.now()


def wall() -> float:
    """Epoch seconds from the active clock."""
    return _active.wall()


def sleep(seconds: float) -> None:
    """Sleep on the active clock (instant under a virtual clock)."""
    _active.sleep(seconds)


@contextmanager
def use_clock(clock: Optional[Clock]) -> Iterator[Clock]:
    """Install ``clock`` as the active clock for the ``with`` body,
    restoring the previous clock on exit. ``None`` means a fresh
    :class:`SystemClock`. Reentrant; the restore nests correctly."""
    global _active
    installed = clock if clock is not None else SystemClock()
    with _lock:
        previous = _active
        _active = installed
    try:
        yield installed
    finally:
        with _lock:
            _active = previous
