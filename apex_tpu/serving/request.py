"""Request/result types for the serving engine.

A :class:`Request` is one user generation call: a prompt, a budget of new
tokens, per-request sampling parameters, and optional deadline/EOS. The
engine turns each terminal request into a :class:`RequestResult` carrying
the generated tokens, the finish reason, and the latency breakdown
(queue/prefill/decode/total) that feeds the ``kind="request"`` JSONL
records and the monitor report's serving section.

Validation lives here, at construction time — a malformed request must
fail loudly at ``submit()`` instead of deep inside a jitted trace (the
same contract :func:`apex_tpu.models.generation.generate` enforces for
``max_new_tokens``/``top_k``).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from apex_tpu.observability.trace import new_trace_id

__all__ = ["SamplingParams", "Request", "RequestResult",
           "FINISH_EOS", "FINISH_LENGTH", "FINISH_CANCELLED",
           "FINISH_TIMEOUT", "FINISH_REJECTED", "FINISH_ERROR",
           "FINISH_REASONS",
           "PRIORITY_INTERACTIVE", "PRIORITY_STANDARD", "PRIORITY_BATCH",
           "PRIORITIES", "PRIORITY_RANK"]

#: priority classes a request can declare (SamplingParams.priority) —
#: dispatch order under contention (docs/serving.md#priority-preemption-
#: and-quotas). Rank 0 is the most latency-sensitive; the scheduler
#: dispatches strictly by rank (FCFS inside a class) and the engine may
#: preempt a lower class to admit a blocked higher one.
PRIORITY_INTERACTIVE = "interactive"    # user-facing, never degraded first
PRIORITY_STANDARD = "standard"          # the default class
PRIORITY_BATCH = "batch"                # best-effort, first to brownout
PRIORITIES = (PRIORITY_INTERACTIVE, PRIORITY_STANDARD, PRIORITY_BATCH)
#: class -> dispatch rank (lower dispatches first)
PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}

#: terminal outcomes a request can reach (RequestResult.finish_reason)
FINISH_EOS = "eos"              # emitted its eos_token
FINISH_LENGTH = "length"        # hit max_new_tokens
FINISH_CANCELLED = "cancelled"  # cancel() — queued or mid-decode
FINISH_TIMEOUT = "timeout"      # deadline_s elapsed — queued or mid-decode
FINISH_REJECTED = "rejected"    # queue full / expired deadline / shed at submit
FINISH_ERROR = "error"          # engine fault: quarantined slot or retry
#                                 budget exhausted — never silently lost
FINISH_REASONS = (FINISH_EOS, FINISH_LENGTH, FINISH_CANCELLED,
                  FINISH_TIMEOUT, FINISH_REJECTED, FINISH_ERROR)

_REQUEST_IDS = itertools.count()


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling: ``temperature == 0`` is greedy (the parity
    anchor against :func:`~apex_tpu.models.generation.generate`); with
    ``temperature > 0`` the engine samples from the (optionally
    ``top_k``-truncated) softmax, keyed by ``seed`` folded with the
    absolute position of each generated token — one request's stream is
    deterministic in (seed, prompt) and independent of what else shares
    the batch.

    ``adapter_id`` selects a LoRA adapter loaded in the engine's
    :class:`~apex_tpu.lora.AdapterStore` (docs/serving.md#multi-lora);
    ``None`` is base-model traffic (the bank's zero adapter). An id the
    engine doesn't know fast-fails at ``submit()`` with
    :class:`~apex_tpu.lora.UnknownAdapterError`.

    ``priority`` is the request's scheduling class (one of
    :data:`PRIORITIES`). It orders dispatch under contention and selects
    which traffic the brownout ladder degrades first; it never changes
    WHAT tokens a request produces, only WHEN they are produced
    (docs/serving.md#priority-preemption-and-quotas)."""

    temperature: float = 0.0
    top_k: Optional[int] = None
    seed: int = 0
    adapter_id: Optional[str] = None
    priority: str = PRIORITY_STANDARD

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.adapter_id is not None and (
                not isinstance(self.adapter_id, str) or not self.adapter_id):
            raise ValueError(
                f"adapter_id must be None or a non-empty string, "
                f"got {self.adapter_id!r}")
        if self.priority not in PRIORITY_RANK:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, "
                f"got {self.priority!r}")


@dataclass
class Request:
    """One generation request.

    ``deadline_s`` is a wall-clock budget relative to submission: a
    request still queued (or still decoding) when it elapses finishes as
    ``timeout`` — queued requests never silently rot behind a long
    backlog. ``request_id`` is assigned process-wide; pass an explicit id
    to correlate with an external system.

    ``arrival_ts`` is an optional ``time.monotonic()`` stamp of when the
    request entered the wider system (an API gateway, a prior engine
    incarnation). When set, ``deadline_s`` counts from it instead of
    from ``submit()`` — so a request that spent its whole budget in
    transit fast-fails at admission, and the supervisor's restart
    continuations keep honoring the ORIGINAL deadline.

    ``trace_id`` names the request's span timeline
    (:mod:`apex_tpu.observability.trace`): minted fresh per request,
    carried verbatim onto restart/migration continuations (which get a
    NEW request object but the same trace), and stamped onto every
    ``kind="span"`` row and the terminal ``kind="request"`` record.
    """

    prompt: Sequence[int]
    max_new_tokens: int
    sampling: SamplingParams = field(default_factory=SamplingParams)
    eos_token: Optional[int] = None
    deadline_s: Optional[float] = None
    request_id: int = field(default_factory=lambda: next(_REQUEST_IDS))
    arrival_ts: Optional[float] = None
    trace_id: str = field(default_factory=new_trace_id)

    def __post_init__(self):
        self.prompt = [int(t) for t in self.prompt]
        if not self.prompt:
            raise ValueError("prompt must be non-empty")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be positive, got {self.deadline_s}")

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def total_len(self) -> int:
        return len(self.prompt) + self.max_new_tokens


@dataclass
class RequestResult:
    """Terminal outcome of one request.

    ``tokens`` are the GENERATED ids only (no prompt echo), including the
    ``eos_token`` when that is what ended the request — exactly the
    ``out[:, prompt_len:]`` slice of a per-request ``generate()`` call
    truncated at its first EOS. Latencies are host wall-clock seconds:
    ``queue_s`` (submit -> prefill start), ``prefill_s``, ``decode_s``
    (first decode participation -> finish) and ``total_s`` (submit ->
    finish); a request that never left the queue has zero prefill/decode.

    ``ttft_s`` (time to first token: submit -> the first generated token
    materializing on the host) and ``tpot_s`` (time per output token: the
    mean inter-token interval over the decode stream) are the serving
    SLO primitives (:mod:`apex_tpu.observability.slo`) — stamped from
    the engine's own token timestamps, NOT reconstructed by adding the
    coarse queue/prefill buckets. ``None`` when unmeasurable: ``ttft_s``
    for a request that produced no token, ``tpot_s`` below two tokens.

    ``replica_id`` is the serving replica that retired the request —
    set by engines running under a :class:`~apex_tpu.serving.fleet.\
ReplicaFleet`; ``None`` on a single-engine deployment or a fleet-level
    outcome (shed at the fleet front door, retired mid-migration), and
    OMITTED from the JSONL record when ``None`` so pre-fleet report
    readers keep working unchanged.

    ``adapter_id`` echoes the request's LoRA adapter (``None`` for base
    traffic) so per-tenant latency/throughput can be sliced straight
    from the request records; omitted from the JSONL when ``None``.

    ``trace_id`` joins the record to its ``kind="span"`` timeline;
    omitted when ``None`` (pre-tracing producers), in which case span
    conservation is vacuous for the record.

    ``prefill_chunks`` counts the chunk programs a token-budgeted
    (chunked) prefill ran for this request
    (docs/serving.md#chunked-prefill) — ``None`` on the monolithic
    path, and omitted from the JSONL record so pre-chunking readers
    keep working unchanged.

    ``priority`` echoes the request's scheduling class so per-class
    goodput can be sliced straight from the request records (the
    ``priority_storm`` gate's ``goodput_interactive`` SLO); ``None``
    on pre-priority producers and omitted from the JSONL when ``None``.
    """

    request_id: int
    prompt_len: int
    tokens: List[int]
    finish_reason: str
    queue_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0
    total_s: float = 0.0
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    replica_id: Optional[int] = None
    adapter_id: Optional[str] = None
    trace_id: Optional[str] = None
    prefill_chunks: Optional[int] = None
    priority: Optional[str] = None

    @property
    def new_tokens(self) -> int:
        return len(self.tokens)

    @property
    def tokens_per_s(self) -> Optional[float]:
        """Generation rate over the in-engine (non-queue) lifetime."""
        busy = self.prefill_s + self.decode_s
        if not self.tokens or busy <= 0.0:
            return None
        return len(self.tokens) / busy

    def record(self, wall: float) -> dict:
        """The ``kind="request"`` JSONL record the engine emits into its
        :class:`~apex_tpu.observability.MetricsRegistry` sinks — the
        per-request counterpart of the trainer's ``kind="step"`` rows."""
        rec = {"kind": "request", "request_id": self.request_id,
               "finish_reason": self.finish_reason,
               "prompt_len": self.prompt_len,
               "new_tokens": self.new_tokens,
               "queue_s": self.queue_s, "prefill_s": self.prefill_s,
               "decode_s": self.decode_s, "total_s": self.total_s,
               "wall": wall}
        # optional fields are OMITTED (not null) when unmeasured, so the
        # records stay readable by pre-TTFT report readers and the
        # summary's per-field guards
        if self.trace_id is not None:
            rec["trace_id"] = self.trace_id
        if self.replica_id is not None:
            rec["replica_id"] = self.replica_id
        if self.adapter_id is not None:
            rec["adapter_id"] = self.adapter_id
        if self.ttft_s is not None:
            rec["ttft_s"] = self.ttft_s
        if self.tpot_s is not None:
            rec["tpot_s"] = self.tpot_s
        if self.prefill_chunks is not None:
            rec["prefill_chunks"] = self.prefill_chunks
        if self.priority is not None:
            rec["priority"] = self.priority
        tps = self.tokens_per_s
        if tps is not None:
            rec["tokens_per_s"] = tps
        return rec
