"""Per-tenant quotas: token-bucket rate limits and concurrency caps.

A multi-tenant fleet shares one pool of slots and KV pages; without
quotas a single tenant submitting at 10x everyone else (the classic
noisy neighbor) fills every queue and the OTHER tenants' deadline sheds
pay for it. :class:`QuotaLedger` is the fleet front door's per-tenant
admission gate (ISSUE 20): each tenant — keyed by the request's
``adapter_id``, with ``"base"`` for base-model traffic — gets

- a **token bucket** (``rate_rps`` refill, ``burst`` capacity): each
  admitted request consumes one bucket token, so sustained throughput is
  capped at ``rate_rps`` while short bursts up to ``burst`` pass;
- a **concurrent-request cap** (``max_inflight``): non-terminal requests
  the tenant may hold across the fleet at once;
- a **KV-page cap** (``max_pages``): the worst-case page footprint
  (``ceil(total_len / page_size)`` per request, the same worst case the
  engine's admission reservation uses) the tenant may pin at once.

An over-quota submit is **shed** (typed ``requests_shed_quota`` counter,
terminal ``rejected`` record, :class:`QuotaExceededError`) for hard
quotas, or **deferred** (parked in the fleet backlog, re-checked every
tick until the bucket refills) for ``soft=True`` quotas — throttled,
never lost. Every knob's zero value means "unlimited", so a partial
quota spec constrains only what it names. See
docs/serving.md#priority-preemption-and-quotas.

The ledger is pure host-side bookkeeping (no jax, no engine access) —
unit-testable with a virtual clock, which is how the mc model checker
drives it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from apex_tpu.serving.supervisor import EngineUnavailableError

__all__ = ["QuotaExceededError", "TenantQuota", "QuotaConfig",
           "QuotaLedger", "QUOTA_ADMIT", "QUOTA_DEFER", "QUOTA_SHED",
           "BASE_TENANT"]

#: ledger verdicts for one submit
QUOTA_ADMIT = "admit"   # within quota: commit and dispatch
QUOTA_DEFER = "defer"   # soft limit hit: backlog until the bucket refills
QUOTA_SHED = "shed"     # hard limit hit: reject terminally

#: tenant key for base-model traffic (``adapter_id is None``)
BASE_TENANT = "base"


class QuotaExceededError(EngineUnavailableError):
    """A hard per-tenant quota rejected the submit. The request IS
    recorded terminally (``finish_reason="rejected"``, counter
    ``requests_shed_quota``) — the same fail-fast contract as every
    other admission shed in this stack."""


@dataclass(frozen=True)
class TenantQuota:
    """One tenant's limits (0 = unlimited for every knob).

    ``soft=True`` turns the shed verdict into a defer: the over-quota
    request waits in the fleet backlog and is re-checked every tick —
    throttled to the quota rate instead of rejected."""

    rate_rps: float = 0.0
    burst: float = 1.0
    max_inflight: int = 0
    max_pages: int = 0
    soft: bool = False

    def __post_init__(self):
        if self.rate_rps < 0:
            raise ValueError(
                f"rate_rps must be >= 0, got {self.rate_rps}")
        if self.burst < 1.0:
            raise ValueError(
                f"burst must be >= 1 (one request must fit), got "
                f"{self.burst}")
        if self.max_inflight < 0:
            raise ValueError(
                f"max_inflight must be >= 0, got {self.max_inflight}")
        if self.max_pages < 0:
            raise ValueError(
                f"max_pages must be >= 0, got {self.max_pages}")


@dataclass(frozen=True)
class QuotaConfig:
    """The fleet's quota table: per-tenant entries plus an optional
    ``default`` applied to tenants not named. No entry and no default
    means the tenant is unlimited."""

    tenants: Dict[str, TenantQuota] = field(default_factory=dict)
    default: Optional[TenantQuota] = None

    def __post_init__(self):
        for key, q in self.tenants.items():
            if not isinstance(key, str) or not key:
                raise ValueError(
                    f"tenant keys must be non-empty strings, got {key!r}")
            if not isinstance(q, TenantQuota):
                raise TypeError(
                    f"quota for tenant {key!r} must be a TenantQuota, "
                    f"got {type(q).__name__}")
        if self.default is not None \
                and not isinstance(self.default, TenantQuota):
            raise TypeError(
                f"default must be None or a TenantQuota, got "
                f"{type(self.default).__name__}")

    def quota_for(self, tenant: str) -> Optional[TenantQuota]:
        return self.tenants.get(tenant, self.default)


class QuotaLedger:
    """Runtime state of the quota table: one token bucket plus
    inflight/page ledgers per tenant. Deterministic given the caller's
    clock — time only enters through the ``now`` arguments."""

    def __init__(self, config: Optional[QuotaConfig] = None):
        self.config = config or QuotaConfig()
        self._tokens: Dict[str, float] = {}
        self._stamp: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}
        self._pages: Dict[str, int] = {}

    @staticmethod
    def tenant(request) -> str:
        """The request's tenant key: its ``adapter_id``, or
        :data:`BASE_TENANT` for base-model traffic."""
        return request.sampling.adapter_id or BASE_TENANT

    def inflight(self, tenant: str) -> int:
        return self._inflight.get(tenant, 0)

    def pages_held(self, tenant: str) -> int:
        return self._pages.get(tenant, 0)

    def bucket_tokens(self, tenant: str, now: float) -> Optional[float]:
        """Current bucket level after refill (None when the tenant has
        no rate limit) — the quota-math unit tests read this."""
        q = self.config.quota_for(tenant)
        if q is None or q.rate_rps <= 0:
            return None
        return self._refill(tenant, q, now)

    def _refill(self, tenant: str, q: TenantQuota, now: float) -> float:
        tokens = self._tokens.get(tenant, q.burst)
        stamp = self._stamp.get(tenant)
        if stamp is not None and now > stamp:
            tokens = min(q.burst, tokens + (now - stamp) * q.rate_rps)
        self._tokens[tenant] = tokens
        self._stamp[tenant] = now
        return tokens

    def verdict(self, tenant: str, now: float, *, pages: int = 0
                ) -> Tuple[str, Optional[str]]:
        """``(QUOTA_ADMIT | QUOTA_DEFER | QUOTA_SHED, limit_name)`` for
        one prospective submit. Pure check — nothing is consumed until
        :meth:`commit` (so a request shed downstream never burns a
        bucket token)."""
        q = self.config.quota_for(tenant)
        if q is None:
            return QUOTA_ADMIT, None
        over: Optional[str] = None
        if q.rate_rps > 0 and self._refill(tenant, q, now) < 1.0:
            over = "rate"
        elif q.max_inflight > 0 \
                and self.inflight(tenant) >= q.max_inflight:
            over = "inflight"
        elif q.max_pages > 0 \
                and self.pages_held(tenant) + pages > q.max_pages:
            over = "pages"
        if over is None:
            return QUOTA_ADMIT, None
        return (QUOTA_DEFER if q.soft else QUOTA_SHED), over

    def commit(self, tenant: str, now: float, *, pages: int = 0) -> None:
        """Consume the admission: one bucket token, one inflight slot,
        the request's worst-case pages. Pair every commit with exactly
        one :meth:`release` at the request's terminal state."""
        q = self.config.quota_for(tenant)
        if q is None:
            return
        if q.rate_rps > 0:
            self._tokens[tenant] = self._refill(tenant, q, now) - 1.0
        self._inflight[tenant] = self.inflight(tenant) + 1
        if pages:
            self._pages[tenant] = self.pages_held(tenant) + pages

    def release(self, tenant: str, *, pages: int = 0) -> None:
        """Return the inflight slot and pages (bucket tokens are spent,
        not returned — rate is an admission rate, not a concurrency
        bound)."""
        if self.config.quota_for(tenant) is None:
            return
        self._inflight[tenant] = max(0, self.inflight(tenant) - 1)
        if pages:
            self._pages[tenant] = max(0, self.pages_held(tenant) - pages)
