"""Tensor-parallel serving engine over the device mesh.

One :class:`~apex_tpu.serving.InferenceEngine` serves from one chip's
HBM; a model too large (or a batch too hungry) for one chip needs the
decode step itself spread over the mesh. :class:`ShardedEngine` is the
same engine — same slot pool, same scheduler, same quarantine and
telemetry, same host-side arrays — with its three device programs
(decode / bucketed prefill / quarantine scrub) wrapped in ``shard_map``
over the ``tensor`` mesh axis (via the :mod:`apex_tpu.utils.sharding`
shims), reusing the :mod:`apex_tpu.transformer` TP layers the multichip
training dryruns already hold parity with:

- **Parameters** shard by the model's own partition spec
  (``model.spec()``): column/row-parallel QKV and MLP blocks, the
  vocab-sharded embedding doubling as the LM head.
- **The flat KV slot pool shards on the heads axis**: each rank owns
  the ``[max_slots, max_len, local_kv_heads * head_dim]`` slice whose
  head block its QKV projection computes, so prefill's scatter and
  decode's one-row append stay rank-local — no KV traffic crosses the
  mesh, exactly like the training-side cache layout under TP.
- **Logits are gathered to full vocab inside the step** (the same
  ``all_gather`` the generation path uses), so sampling and the
  per-slot integrity flags run replicated and every rank agrees on the
  next token — the host-side engine logic cannot tell it is driving a
  sharded program.

Parity bar (tier-1/slow tests): decode on a tp=2 CPU mesh is
TOKEN-EXACT against the unsharded engine, greedy and sampled, with zero
decode retraces — the same bar every multichip training dryrun meets.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import PartitionSpec as P

from apex_tpu.serving.engine import EngineConfig, InferenceEngine
from apex_tpu.transformer import parallel_state
from apex_tpu.utils.sharding import shard_map

__all__ = ["ShardedEngine"]


class ShardedEngine(InferenceEngine):
    """Tensor-parallel :class:`~apex_tpu.serving.InferenceEngine`; see
    the module docstring. ``mesh`` defaults to the initialized
    :mod:`~apex_tpu.transformer.parallel_state` mesh
    (``initialize_model_parallel(tensor_model_parallel_size=tp)``)."""

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 *, mesh=None, metrics=None, faults=None,
                 replica_id: Optional[int] = None, adapters=None):
        self.mesh = mesh if mesh is not None else parallel_state.get_mesh()
        c = model.config
        self._tp = self.mesh.shape[c.axis_name]
        if c.kv_heads % self._tp:
            raise ValueError(
                f"kv heads ({c.kv_heads}) must be divisible by the "
                f"tensor-parallel size ({self._tp}); with GQA/MQA keep "
                f"num_query_groups a multiple of tp")
        if c.vocab_size % self._tp:
            raise ValueError(
                f"vocab_size ({c.vocab_size}) must be divisible by the "
                f"tensor-parallel size ({self._tp}) — the embedding / LM "
                f"head shard on the vocab dim (pad the vocab, as training "
                f"TP does)")
        if c.sequence_parallel:
            raise ValueError(
                "ShardedEngine decodes single tokens per slot — "
                "sequence_parallel has nothing to shard; build the model "
                "with sequence_parallel=False for serving")
        super().__init__(model, params, config, metrics=metrics,
                         faults=faults, replica_id=replica_id,
                         adapters=adapters)

    # -- sharding specs ---------------------------------------------------

    def _param_spec(self):
        """``model.spec()`` reshaped to match the engine's prepared
        params: the one-time ``preslice_layer_params`` turns the stacked
        ``[L, ...]`` transformer layers into a per-layer LIST, so the
        stacked spec's leading (layer) dim is stripped and the per-layer
        spec repeated."""
        spec = self.model.spec()
        layers = self._params.get("transformer", {}).get("layers")
        if isinstance(layers, (list, tuple)):
            is_spec = lambda x: isinstance(x, P)           # noqa: E731
            per_layer = jax.tree_util.tree_map(
                lambda s: P(*tuple(s)[1:]),
                spec["transformer"]["layers"], is_leaf=is_spec)
            spec = dict(spec)
            spec["transformer"] = dict(spec["transformer"])
            spec["transformer"]["layers"] = [per_layer] * len(layers)
        return spec

    def _cache_spec(self):
        """Both KV pool layouts — flat ``[max_slots, max_len, kv_heads *
        head_dim]`` rows and the paged ``[n_pages, page_size, kv_heads *
        head_dim]`` pool — shard the same fused heads*head_dim minor dim
        over the tensor axis: each rank's contiguous block is exactly
        the head slice its QKV projection produces (page tables stay
        host-side/replicated; the mapping is identical on every rank).
        Quantized pools nest the per-page scale sidecar ``[n_pages,
        kv_heads]`` alongside each int8 pool, sharded on ITS heads dim —
        every rank holds exactly the scales of the head block it owns,
        so quantize/rescale/dequant stay rank-local too."""
        axis = self.model.config.axis_name
        if getattr(self, "_quantized", False):
            half = (P(None, None, axis), P(None, axis))
            pair = (half, half)
        else:
            pair = (P(None, None, axis), P(None, None, axis))
        return [pair for _ in range(self.model.config.num_layers)]

    def _lora_spec(self):
        """Spec for the LoRA adapter bank argument. Both LoRA targets
        (QKV, dense_h_to_4h) are column-parallel, so each ``B`` bank
        ``[L, n_adapters+1, r, out]`` shards its OUT dim over the tensor
        axis — each rank's slice is exactly the out block its projection
        computes, so ``y += (x @ A) @ B`` stays rank-local with zero
        collective cost (the rank-r inner product replicates). ``A``
        banks replicate (their dims are hidden x r on every target).
        With no :class:`~apex_tpu.lora.AdapterStore` the bank argument
        is ``None`` (an empty pytree) and a bare replicated spec
        suffices."""
        if self.adapters is None:
            return P()
        axis = self.model.config.axis_name
        target = {"A": P(), "B": P(None, None, None, axis)}
        return {t: target for t in self.adapters.bank}

    def _build_step_fns(self, donate: bool):
        """The base engine's step bodies, ``shard_map``-wrapped over the
        mesh: params by ``model.spec()``, KV pool on the heads axis,
        tokens/positions/sampling params — and, under ``kv_layout=
        "paged"``, the page table — replicated. The bodies themselves
        are INHERITED — this class changes where the math runs, not what
        it computes."""
        mesh = self.mesh
        pspec = self._param_spec()
        cspec = self._cache_spec()
        rep = P()
        lspec = self._lora_spec()
        reset = None
        if self.pages is not None:
            # paged bodies take one extra replicated arg (the page
            # table / the slot's table row) right after the pool. The
            # speculative verify body has the SAME arity — the [n]
            # token vector becomes the [n, k] window matrix, still
            # replicated — so the spec structure is unchanged.
            decode_body = (self._spec_decode_body if self._spec
                           else self._paged_decode_body)
            decode = shard_map(
                decode_body, mesh=mesh,
                in_specs=(pspec, cspec, rep, rep, rep, rep, rep, rep,
                          rep, lspec),
                out_specs=(rep, rep, cspec))
            prefill = shard_map(
                self._paged_prefill_body, mesh=mesh,
                in_specs=(pspec, cspec, rep, rep, rep, rep, rep, rep,
                          rep, lspec),
                out_specs=(rep, rep, cspec))
            # suffix prefill (prefix-cache hit): the gather/scatter of
            # shared pages is rank-local on each rank's head slice, so
            # sharding follows the pool spec; everything scalar — start,
            # lengths, sampling, the skip_first flag — replicates
            suffix = shard_map(
                self._suffix_prefill_body, mesh=mesh,
                in_specs=(pspec, cspec, rep, rep, rep, rep, rep, rep,
                          rep, rep, rep, rep, lspec),
                out_specs=(rep, rep, cspec))
            # chunked prefill (docs/serving.md#chunked-prefill) rides
            # the suffix program on the paged layout — the chunk offset
            # is a traced scalar, so no extra sharded wiring exists
            chunk = None
            scrub = shard_map(
                self._paged_scrub_body, mesh=mesh,
                in_specs=(cspec, rep), out_specs=cspec)
            if self._quantized:
                reset = shard_map(
                    self._reset_scales_body, mesh=mesh,
                    in_specs=(cspec, rep), out_specs=cspec)
        else:
            decode = shard_map(
                self._decode_body, mesh=mesh,
                in_specs=(pspec, cspec, rep, rep, rep, rep, rep, rep,
                          lspec),
                out_specs=(rep, rep, cspec))
            prefill = shard_map(
                self._prefill_body, mesh=mesh,
                in_specs=(pspec, cspec, rep, rep, rep, rep, rep, rep,
                          rep, lspec),
                out_specs=(rep, cspec))
            suffix = None
            # the flat chunk program scatters a bucketed K/V slice into
            # each rank's own head block of the slot row — rank-local,
            # same spec shape as flat prefill plus the start offset
            chunk = shard_map(
                self._flat_chunk_body, mesh=mesh,
                in_specs=(pspec, cspec, rep, rep, rep, rep, rep, rep,
                          rep, rep, rep, lspec),
                out_specs=(rep, rep, cspec))
            scrub = shard_map(
                self._scrub_body, mesh=mesh,
                in_specs=(cspec, rep), out_specs=cspec)
        donate_args = (1,) if donate else ()
        return (jax.jit(decode, donate_argnums=donate_args),
                jax.jit(prefill, donate_argnums=donate_args),
                None if suffix is None else
                jax.jit(suffix, donate_argnums=donate_args),
                None if chunk is None else
                jax.jit(chunk, donate_argnums=donate_args),
                jax.jit(scrub, donate_argnums=(0,) if donate else ()),
                None if reset is None else
                jax.jit(reset, donate_argnums=(0,) if donate else ()))
