"""Brownout ladder: staged degradation under sustained overload.

When the fleet is saturated past what autoscaling can absorb (bounds
hit, or pressure rising faster than replicas can build), the remaining
choice is WHICH traffic degrades. Without a policy that choice is made
implicitly — FCFS queues and deadline sheds hit interactive users first,
exactly backwards. The :class:`BrownoutController` (ISSUE 20) makes it
explicit: polled from ``ReplicaFleet.tick`` like the autoscaler, it
reads the same :meth:`~apex_tpu.observability.FleetMetrics.signals`
stream and walks a ladder of increasingly aggressive rungs, degrading
best-effort traffic first and touching standard traffic only as the
last step before the existing shed machinery takes over:

====  ================  ==================================================
rung  name              effect
====  ================  ==================================================
0     ``normal``        no degradation
1     ``pause_batch``   admission floor ``standard``: queued batch
                        requests stop dispatching (they stay queued;
                        deadlines still apply)
2     ``preempt_batch`` one-shot: every RUNNING batch slot is parked
                        (:meth:`~apex_tpu.serving.EngineSupervisor.\
preempt_class`) and its token-exact resume continuation re-queued —
                        slots and pages hand over to higher classes now
3     ``clamp_batch``   batch submits get ``max_new_tokens`` clamped to
                        ``clamp_max_new_tokens`` — best-effort work
                        still flows, but each admission is bounded
4     ``pause_standard``  admission floor ``interactive``: only
                        interactive traffic dispatches
====  ================  ==================================================

Escalation requires ``hot_polls`` consecutive polls with per-replica
queue pressure above ``queue_depth_high``; recovery (one rung at a
time, in reverse) requires ``cool_polls`` consecutive polls below
``queue_depth_low``. Pressure counts only ADMISSIBLE queued work:
requests held by the current rung's own admission floor are excluded,
so a paused class's (intentionally) retained backlog can never keep
the ladder hot — without that exclusion a pure-batch storm would wedge
at the top rung forever instead of breathing back down. The gap between the two thresholds plus the
streak requirement is the hysteresis that keeps the ladder from
flapping. Every transition emits a typed ``kind="brownout"`` record
plus a ``brownout_escalate``/``brownout_recover`` event+counter pair
the monitor reconciles key-for-key
(docs/serving.md#priority-preemption-and-quotas).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from apex_tpu.observability.fleet_metrics import FleetMetrics
from apex_tpu.serving import clock
from apex_tpu.serving.request import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    PRIORITY_RANK,
    PRIORITY_STANDARD,
    Request,
)
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["BrownoutConfig", "BrownoutController", "BROWNOUT_RUNGS"]

_LOG = get_logger(__name__)

#: ladder rungs in escalation order (index == rung number)
BROWNOUT_RUNGS = ("normal", "pause_batch", "preempt_batch",
                  "clamp_batch", "pause_standard")
_RUNG_PAUSE_BATCH = 1
_RUNG_PREEMPT_BATCH = 2
_RUNG_CLAMP_BATCH = 3
_RUNG_PAUSE_STANDARD = 4


@dataclass(frozen=True)
class BrownoutConfig:
    """Ladder knobs (docs/serving.md#priority-preemption-and-quotas).

    Pressure is queued requests per dispatchable replica — the same
    ``queue_depth`` / ``replicas_dispatchable`` ratio the autoscaler
    triggers on, so the two controllers agree about what "overloaded"
    means. ``queue_depth_high`` must exceed ``queue_depth_low``; the
    band between them is the neutral zone where streaks reset.
    ``max_rung`` caps how far the ladder may escalate (default: the
    whole ladder)."""

    poll_interval_s: float = 0.25
    queue_depth_high: float = 8.0
    queue_depth_low: float = 2.0
    hot_polls: int = 2
    cool_polls: int = 2
    clamp_max_new_tokens: int = 32
    max_rung: int = len(BROWNOUT_RUNGS) - 1

    def __post_init__(self):
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s must be > 0, got {self.poll_interval_s}")
        if self.queue_depth_high <= 0:
            raise ValueError(
                f"queue_depth_high must be > 0, got "
                f"{self.queue_depth_high}")
        if not 0 <= self.queue_depth_low < self.queue_depth_high:
            raise ValueError(
                f"queue_depth_low ({self.queue_depth_low}) must be in "
                f"[0, queue_depth_high={self.queue_depth_high}) — "
                f"overlapping bands would flap")
        if self.hot_polls < 1:
            raise ValueError(
                f"hot_polls must be >= 1, got {self.hot_polls}")
        if self.cool_polls < 1:
            raise ValueError(
                f"cool_polls must be >= 1, got {self.cool_polls}")
        if self.clamp_max_new_tokens < 1:
            raise ValueError(
                f"clamp_max_new_tokens must be >= 1, got "
                f"{self.clamp_max_new_tokens}")
        if not 0 <= self.max_rung < len(BROWNOUT_RUNGS):
            raise ValueError(
                f"max_rung must be in [0, {len(BROWNOUT_RUNGS) - 1}], "
                f"got {self.max_rung}")


class BrownoutController:
    """The degradation policy; polled via :meth:`maybe_step` from
    ``ReplicaFleet.tick`` (after the autoscaler and sentinel, so it
    sees the tick's final queue state). Holds its OWN
    :class:`FleetMetrics` view — window privacy, same as the
    autoscaler."""

    def __init__(self, config: Optional[BrownoutConfig] = None):
        self.config = config or BrownoutConfig()
        self.rung = 0
        self._fm: Optional[FleetMetrics] = None
        self._last_poll: Optional[float] = None
        self._hot = 0
        self._cool = 0
        #: applied transitions, for tests/drivers: (now, action, rung,
        #: pressure) tuples in order
        self.transitions: List[Tuple[float, str, int, float]] = []

    @property
    def rung_name(self) -> str:
        return BROWNOUT_RUNGS[self.rung]

    def admission_floor(self) -> Optional[str]:
        """The scheduler floor the current rung implies (None = all
        classes dispatch)."""
        if self.rung >= _RUNG_PAUSE_STANDARD:
            return PRIORITY_INTERACTIVE
        if self.rung >= _RUNG_PAUSE_BATCH:
            return PRIORITY_STANDARD
        return None

    def clamp(self, request: Request) -> Request:
        """At ``clamp_batch`` and above, bound a batch request's
        ``max_new_tokens`` to the configured clamp — same ids, same
        deadline clock, same trace, so exactly-once accounting and span
        conservation are untouched. Everything else passes through."""
        cap = self.config.clamp_max_new_tokens
        if (self.rung < _RUNG_CLAMP_BATCH
                or request.sampling.priority != PRIORITY_BATCH
                or request.max_new_tokens <= cap):
            return request
        return Request(
            prompt=list(request.prompt), max_new_tokens=cap,
            sampling=request.sampling, eos_token=request.eos_token,
            deadline_s=request.deadline_s,
            request_id=request.request_id,
            arrival_ts=request.arrival_ts, trace_id=request.trace_id)

    @staticmethod
    def pressure(signals: dict) -> float:
        """Queued requests per dispatchable replica — pure, so the
        ladder policy is unit-testable from a signals dict alone."""
        dispatchable = max(1, signals.get("replicas_dispatchable") or 0)
        return (signals.get("queue_depth") or 0) / dispatchable

    def _held_depth(self, fleet) -> int:
        """Queued requests the CURRENT admission floor is holding.
        They are excluded from the pressure the ladder judges: a paused
        class keeps its backlog queued by design, and counting it would
        let the ladder escalate on (and then never recover from) its
        own backpressure — a pure-batch storm would wedge at the top
        rung with batch starved forever instead of breathing back down
        once the admissible queue drains."""
        floor = self.admission_floor()
        if floor is None:
            return 0
        rank = PRIORITY_RANK[floor]
        held = 0
        for replica in fleet.replicas:
            by = getattr(replica.supervisor.engine,
                         "queued_depth_by_class", None)
            if by is not None:
                held += sum(n for p, n in by().items()
                            if PRIORITY_RANK[p] > rank)
        for req in getattr(fleet, "_backlog", ()):
            if PRIORITY_RANK.get(req.sampling.priority, 0) > rank:
                held += 1
        return held

    def maybe_step(self, fleet, now: Optional[float] = None
                   ) -> Optional[str]:
        """One poll: read signals, update streaks, move at most one
        rung. Returns ``"escalate"``/``"recover"`` when a transition
        was applied, else None. Safe to call every tick — the poll
        interval is enforced internally, and the current rung's
        admission floor is re-asserted each poll so replicas built
        mid-brownout (autoscale-ups, rebuilds) inherit it."""
        if now is None:
            now = clock.now()
        if (self._last_poll is not None
                and now - self._last_poll < self.config.poll_interval_s):
            return None
        self._last_poll = now
        if self._fm is None or self._fm.fleet is not fleet:
            self._fm = FleetMetrics(fleet)
        signals = dict(self._fm.signals())
        signals["queue_depth"] = max(
            0, (signals.get("queue_depth") or 0) - self._held_depth(fleet))
        pressure = self.pressure(signals)
        self._assert_floor(fleet)
        cfg = self.config
        if pressure > cfg.queue_depth_high:
            self._hot += 1
            self._cool = 0
            if self._hot >= cfg.hot_polls and self.rung < cfg.max_rung:
                return self._apply(fleet, self.rung + 1, "escalate",
                                   pressure, now)
        elif pressure < cfg.queue_depth_low:
            self._cool += 1
            self._hot = 0
            if self._cool >= cfg.cool_polls and self.rung > 0:
                return self._apply(fleet, self.rung - 1, "recover",
                                   pressure, now)
        else:
            # neutral zone: neither streak advances — the hysteresis
            # band that keeps a noisy signal from walking the ladder
            self._hot = 0
            self._cool = 0
        return None

    def _assert_floor(self, fleet) -> None:
        floor = self.admission_floor()
        for replica in fleet.replicas:
            fn = getattr(replica.supervisor, "set_admission_floor", None)
            if fn is not None:
                fn(floor)

    def _apply(self, fleet, new_rung: int, action: str,
               pressure: float, now: float) -> str:
        self.rung = new_rung
        self._hot = 0
        self._cool = 0
        self._assert_floor(fleet)
        parked = 0
        if action == "escalate" and new_rung == _RUNG_PREEMPT_BATCH:
            # one-shot at entry: park every running batch slot; the
            # floor (already at "standard") keeps new ones from starting
            from apex_tpu.serving.fleet.router import REPLICA_ACTIVE
            for replica in fleet.replicas:
                if replica.state != REPLICA_ACTIVE:
                    continue
                fn = getattr(replica.supervisor, "preempt_class", None)
                if fn is not None:
                    parked += fn(PRIORITY_BATCH, cause="brownout")
        self.transitions.append((now, action, new_rung, pressure))
        counter = ("brownouts_escalated" if action == "escalate"
                   else "brownouts_recovered")
        event = ("brownout_escalate" if action == "escalate"
                 else "brownout_recover")
        fleet.metrics.inc(counter)
        log_event(_LOG, event, rung=new_rung,
                  rung_name=self.rung_name, pressure=pressure,
                  parked=parked)
        fleet.metrics.event(event, rung=new_rung,
                            rung_name=self.rung_name,
                            pressure=pressure, parked=parked)
        fleet.metrics.emit_record({
            "kind": "brownout", "action": action, "rung": new_rung,
            "rung_name": self.rung_name, "pressure": pressure,
            "parked": parked, "wall": clock.wall()})
        return action
