"""apex_tpu.serving.fleet — horizontally scaled serving.

The fleet layer turns one supervised engine into a serving TIER:
:class:`ReplicaFleet` runs N :class:`~apex_tpu.serving.EngineSupervisor`
replicas behind a single ``submit()`` front door with least-loaded
dispatch (:class:`Router`), fleet-wide admission control (an open
breaker removes a replica from the dispatch set;
:class:`FleetUnavailableError` only when none remain), and draining
restarts that migrate in-flight work token-exact to peers so a rebuild
never drops capacity below N−1. :class:`ShardedEngine` is the
scale-up counterpart: the same engine with its decode/prefill programs
tensor-parallel over the device mesh and the flat KV slot pool sharded
on the heads axis. See docs/serving.md#fleet.

On top of the fleet sit the two halves of the train->serve loop
(PR 16): :class:`Autoscaler` grows and shrinks the fleet between
``min_replicas``/``max_replicas`` under live SLO pressure
(docs/serving.md#autoscaling), and :class:`Deployment` rolls freshly
trained checkpoints or LoRA adapters through canary-scored draining
restarts with automatic rollback
(docs/serving.md#continuous-deployment).
"""

from apex_tpu.serving.fleet.autoscale import AutoscaleConfig, Autoscaler
from apex_tpu.serving.fleet.brownout import (
    BROWNOUT_RUNGS,
    BrownoutConfig,
    BrownoutController,
)
from apex_tpu.serving.fleet.deploy import (
    DEPLOY_CANARY,
    DEPLOY_COMPLETE,
    DEPLOY_DRAINING,
    DEPLOY_REJECTED,
    DEPLOY_ROLLED_BACK,
    DEPLOY_ROLLING,
    DEPLOY_ROLLING_BACK,
    DEPLOY_UNLOADING,
    CanaryConfig,
    Deployment,
)
from apex_tpu.serving.fleet.router import (
    REPLICA_ACTIVE,
    REPLICA_DRAINING,
    REPLICA_FAILED,
    REPLICA_PROBING,
    FleetConfig,
    FleetUnavailableError,
    ReplicaFleet,
    Router,
)
from apex_tpu.serving.fleet.quota import (
    QuotaConfig,
    QuotaExceededError,
    QuotaLedger,
    TenantQuota,
)
from apex_tpu.serving.fleet.sharded import ShardedEngine

__all__ = [
    "ReplicaFleet",
    "Router",
    "FleetConfig",
    "FleetUnavailableError",
    "ShardedEngine",
    "AutoscaleConfig",
    "Autoscaler",
    "CanaryConfig",
    "Deployment",
    "REPLICA_ACTIVE",
    "REPLICA_DRAINING",
    "REPLICA_PROBING",
    "REPLICA_FAILED",
    "DEPLOY_ROLLING",
    "DEPLOY_DRAINING",
    "DEPLOY_CANARY",
    "DEPLOY_ROLLING_BACK",
    "DEPLOY_UNLOADING",
    "DEPLOY_COMPLETE",
    "DEPLOY_ROLLED_BACK",
    "DEPLOY_REJECTED",
    "TenantQuota",
    "QuotaConfig",
    "QuotaLedger",
    "QuotaExceededError",
    "BrownoutConfig",
    "BrownoutController",
    "BROWNOUT_RUNGS",
]
