"""Replica fleet: N supervised engines behind one ``submit()`` front door.

PRs 4–6 built ONE supervised engine: continuous batching, crash-only
restart recovery, admission control, and a load-test gate — all on a
single chip. The "millions of users" leg of the ROADMAP needs the same
semantics horizontally: :class:`ReplicaFleet` runs ``n_replicas``
:class:`~apex_tpu.serving.EngineSupervisor`-wrapped replicas (each a
full engine: own slot pool, own KV caches, own jitted programs — or a
:class:`~apex_tpu.serving.fleet.ShardedEngine` spanning the device
mesh) behind a single front door, composing the existing primitives the
way TorchTitan composes parallelism primitives into one entry point:

- **Least-loaded dispatch** (:class:`Router`): each submit goes to the
  replica minimizing ``queue_depth × EWMA(service_s)`` — the SAME
  service-time estimate the supervisor's deadline shedding maintains
  (:attr:`~apex_tpu.serving.EngineSupervisor.service_estimate_s`), so
  routing and shedding agree about how loaded a replica is — plus the
  supervisor's token-aware surcharge
  (:attr:`~apex_tpu.serving.EngineSupervisor.queued_token_excess_s`)
  so a backlog of unusually LONG prompts prices above the same depth
  of short ones. Ties break by depth then replica id, keeping runs
  deterministic.
- **Prefix-affinity dispatch**: the router hashes each prompt's
  page-aligned prefix with the SAME chain the engine's prefix cache
  interns (:func:`~apex_tpu.serving.prefix.prefix_hash_chain`) and
  folds a BOUNDED discount into the least-loaded cost for replicas
  that recently served a matching prefix — their intern index likely
  still holds the pages, so the request prefills only its suffix
  there. Bounded means multiplicative, at most
  ``prefix_affinity_weight < 1``: a hot replica's cost can shrink but
  never reach zero, so load still sheds to cold peers. Residency is
  tracked from dispatch history (bounded LRU per replica) and
  invalidated on rebuild — a rebuilt replica has an empty intern
  index, so stale affinity would route misses at it.
- **Sticky routing**: an admitted request stays on its replica;
  ``cancel()`` and result harvesting follow it there (and through a
  migration to wherever it went).
- **Fleet-wide admission control**: a replica with an OPEN circuit
  breaker leaves the dispatch set instead of fast-failing the caller —
  traffic flows to healthy peers, while the sick replica keeps ticking
  so its breaker can half-open and probe.
  :class:`FleetUnavailableError` (recorded terminally, like every
  rejection in this stack) only when NO replica is dispatchable.
- **Draining restarts**: :meth:`ReplicaFleet.drain_restart` quiesces a
  replica — dispatch stops, in-flight work either finishes in place or
  is handed to a peer through the supervisor's token-exact
  re-prefill continuations
  (:meth:`~apex_tpu.serving.EngineSupervisor.detach_for_migration`) —
  then rebuilds it from scratch (fresh supervisor, fresh engine, fresh
  jit; the service-time EWMA is CARRIED so the rebuilt replica does not
  shed blind), health-probes it with a real one-token request, and
  rejoins it to the dispatch set. Only one replica may drain at a time,
  so a rebuild never drops fleet capacity below N−1.

Telemetry follows the serving contract: fleet counters
(``fleet_dispatches`` = Σ ``replica<i>_dispatches``, ``replica_drains``,
``replica_rebuilds``, ``requests_migrated``, ``requests_shed_fleet``)
are incremented at the same sites as their ``kind="event"`` incident
records, every terminal request record carries the ``replica_id`` that
retired it, and ``python -m apex_tpu.monitor`` renders a fleet section
reconciling the two key-for-key.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.observability import MetricsRegistry
from apex_tpu.observability.fleet_metrics import ReplicaRegistry
from apex_tpu.observability.trace import (
    SPAN_DECODE,
    SPAN_MIGRATION,
    SPAN_SHED,
    emit_span,
)
from apex_tpu.serving import clock
from apex_tpu.serving.engine import EngineConfig
from apex_tpu.serving.prefix import (
    adapter_salt,
    common_chain_len,
    prefix_hash_chain,
    prefix_salt,
)
from apex_tpu.serving.request import (
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_REJECTED,
    FINISH_TIMEOUT,
    PRIORITY_RANK,
    PRIORITY_STANDARD,
    Request,
    RequestResult,
    SamplingParams,
)
from apex_tpu.serving.scheduler import DeadlineExpiredError, QueueFullError
from apex_tpu.serving.supervisor import (
    BREAKER_OPEN,
    EngineSupervisor,
    EngineUnavailableError,
    SupervisorConfig,
)
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["FleetUnavailableError", "FleetConfig", "Router", "ReplicaFleet",
           "REPLICA_ACTIVE", "REPLICA_DRAINING", "REPLICA_PROBING",
           "REPLICA_FAILED"]

_LOG = get_logger(__name__)

#: replica lifecycle states (``ReplicaFleet.replica_states``)
REPLICA_ACTIVE = "active"        # in the dispatch set (breaker permitting)
REPLICA_DRAINING = "draining"    # quiescing: no new dispatches
REPLICA_PROBING = "probing"      # rebuilt, health probe in flight
REPLICA_FAILED = "failed"        # rebuild probes exhausted; out for good

#: declared up front so the final snapshot carries every key even when
#: an incident type never fired — the monitor's fleet section reconciles
#: these against the event stream key-for-key
_FLEET_COUNTERS = ("fleet_dispatches", "replica_drains", "replica_rebuilds",
                   "requests_migrated", "requests_shed_fleet",
                   # autoscaling + continuous deployment (PR 16): each
                   # counter pairs with a same-named kind="event" record
                   "replica_scale_ups", "replica_scale_downs",
                   "deploys_started", "deploys_completed",
                   "deploys_rolled_back", "deploys_rejected",
                   "canary_promotions",
                   # per-tenant quotas + the brownout ladder (ISSUE 20):
                   # same counter<->event pairing contract
                   "requests_shed_quota", "requests_deferred_quota",
                   "brownouts_escalated", "brownouts_recovered")


class FleetUnavailableError(EngineUnavailableError):
    """No replica is dispatchable: every one is drained, failed, or has
    an open circuit breaker. The request IS recorded terminally
    (``finish_reason="rejected"``) — the fleet-wide analogue of the
    supervisor's fail-fast contract."""


@dataclass
class FleetConfig:
    """Fleet sizing and drain-lifecycle knobs (docs/serving.md#fleet).

    ``migrate_on_drain`` picks the drain policy: True hands in-flight
    work to peers immediately (token-exact re-prefill — the drain
    completes as fast as one rebuild), False lets the draining replica
    finish its own work first (no migration cost, slower drain).
    ``probe_on_rebuild`` gates the health probe — a real one-token
    greedy request served end-to-end before the replica rejoins;
    ``max_rebuild_probes`` failed probes mark the replica FAILED
    instead of looping a persistently-broken rebuild forever.
    ``prefix_affinity_weight`` caps the routing discount for replicas
    with a resident matching prefix (0 disables affinity; must stay
    < 1 so load always dominates a full-prefix match).
    """

    n_replicas: int = 2
    migrate_on_drain: bool = True
    probe_on_rebuild: bool = True
    max_rebuild_probes: int = 3
    prefix_affinity_weight: float = 0.3

    def __post_init__(self):
        if self.n_replicas < 1:
            raise ValueError(
                f"n_replicas must be >= 1, got {self.n_replicas}")
        if self.max_rebuild_probes < 1:
            raise ValueError(
                f"max_rebuild_probes must be >= 1, got "
                f"{self.max_rebuild_probes}")
        if not 0.0 <= self.prefix_affinity_weight < 1.0:
            raise ValueError(
                f"prefix_affinity_weight must be in [0, 1), got "
                f"{self.prefix_affinity_weight}")


class _Replica:
    """One fleet slot: a supervisor plus its lifecycle state.

    ``retire_on_drain`` marks a scale-down: when the drain empties, the
    replica is REMOVED from the fleet (:meth:`ReplicaFleet._finish_retire`)
    instead of rebuilt — the terminal leg of ``retire_replica``.
    """

    __slots__ = ("replica_id", "supervisor", "state", "dispatches",
                 "probe_id", "probe_attempts", "retire_on_drain")

    def __init__(self, replica_id: int, supervisor: EngineSupervisor):
        self.replica_id = replica_id
        self.supervisor = supervisor
        self.state = REPLICA_ACTIVE
        self.dispatches = 0
        self.probe_id: Optional[int] = None
        self.probe_attempts = 0
        self.retire_on_drain = False


class _FleetTracked:
    """Fleet-side state of one admitted-and-not-yet-terminal request —
    survives replica migrations the way the supervisor's ``_Tracked``
    survives engine rebuilds."""

    __slots__ = ("request", "first_submit_ts", "prefix", "order",
                 "replica_id", "migrations")

    def __init__(self, request: Request, submit_ts: float, order: int):
        self.request = request
        self.first_submit_ts = submit_ts
        self.prefix: List[int] = []   # tokens recovered from drained peers
        self.order = order
        self.replica_id: Optional[int] = None   # current home (sticky)
        self.migrations = 0


class Router:
    """The dispatch policy: least loaded first, prefix-affinity aware.

    Cost of a replica is ``depth × service_s`` where ``depth`` counts
    everything already committed to it (queued + backlogged + active
    slots) and ``service_s`` is the supervisor's deadline-shedding EWMA
    — before the first completion the EWMA is unknown and the replica
    costs 0, which deliberately attracts traffic to fresh (just
    rebuilt) replicas. Deterministic: ties break by depth, then id.

    When the fleet hands :meth:`pick` a prefix hash chain, the cost is
    discounted multiplicatively for replicas whose recent dispatch
    history (:meth:`note_dispatch`, a bounded per-replica LRU of
    chains) contains a matching prefix run:
    ``cost × (1 − weight × share)`` with ``share`` the matched fraction
    of the request's chain. The discount is BOUNDED by
    ``affinity_weight < 1`` — a perfect match shrinks the cost by at
    most that factor, so a deeply-loaded hot replica still loses to an
    idle cold one and affinity can never starve the fleet onto one
    replica. On exact cost-and-depth ties the better match wins (that
    is what routes a cold fleet's repeat prefixes together before any
    EWMA exists). :meth:`invalidate` forgets a replica's residency when
    its engine is rebuilt (fresh intern index — nothing is resident).
    """

    def __init__(self, affinity_weight: float = 0.0,
                 residency_capacity: int = 128):
        if not 0.0 <= affinity_weight < 1.0:
            raise ValueError(
                f"affinity_weight must be in [0, 1), got "
                f"{affinity_weight}")
        if residency_capacity < 1:
            raise ValueError(
                f"residency_capacity must be >= 1, got "
                f"{residency_capacity}")
        self.affinity_weight = affinity_weight
        self.residency_capacity = residency_capacity
        self._resident: Dict[int, "OrderedDict[Tuple[int, ...], None]"] \
            = {}

    @staticmethod
    def depth(replica: _Replica) -> int:
        sup = replica.supervisor
        return sup.queued_count + sup.active_count

    @classmethod
    def cost(cls, replica: _Replica) -> Tuple[float, int, int]:
        depth = cls.depth(replica)
        service = replica.supervisor.service_estimate_s
        # depth x EWMA(service) underprices a backlog of LONG prompts —
        # fold in the supervisor's token-aware surcharge (0.0 until the
        # per-token prefill rate has been measured, so a fresh replica
        # still costs exactly 0 and routing stays deterministic)
        base = depth * service if service is not None else 0.0
        # getattr: the router prices any supervisor-shaped object (test
        # stubs included); no surcharge is indistinguishable from a
        # not-yet-measured one
        base += getattr(replica.supervisor, "queued_token_excess_s", 0.0)
        return (base, depth, replica.replica_id)

    def affinity(self, replica_id: int,
                 chain: Optional[Sequence[int]]) -> float:
        """Matched fraction of ``chain`` best resident on a replica,
        in [0, 1] — 0 when no chain, no residency, or no common run."""
        if not chain:
            return 0.0
        resident = self._resident.get(replica_id)
        if not resident:
            return 0.0
        best = 0
        for r in resident:
            n = common_chain_len(r, chain)
            if n > best:
                best = n
        return best / len(chain)

    def pick(self, candidates: Sequence[_Replica],
             chain: Optional[Sequence[int]] = None) -> _Replica:
        if not candidates:
            raise ValueError("no candidates to route to")
        w = self.affinity_weight

        def key(replica: _Replica):
            base, depth, rid = self.cost(replica)
            share = self.affinity(replica.replica_id, chain) \
                if w > 0.0 else 0.0
            # -share: on exact (cost, depth) ties prefer the replica
            # holding the longer resident run — replica id still breaks
            # true ties, keeping routing deterministic
            return (base * (1.0 - w * share), depth, -share, rid)

        return min(candidates, key=key)

    def note_dispatch(self, replica_id: int,
                      chain: Optional[Sequence[int]]) -> None:
        """Record that a prompt with this chain was dispatched to the
        replica — its engine will intern the prefix on prefill, so the
        run becomes resident there. Bounded LRU per replica."""
        if not chain:
            return
        resident = self._resident.setdefault(replica_id, OrderedDict())
        resident[tuple(chain)] = None
        resident.move_to_end(tuple(chain))
        while len(resident) > self.residency_capacity:
            resident.popitem(last=False)

    def invalidate(self, replica_id: int) -> None:
        """Forget a replica's residency (engine rebuilt: empty intern
        index)."""
        self._resident.pop(replica_id, None)


class ReplicaFleet:
    """Horizontally scaled serving tier; see the module docstring. The
    driving surface mirrors :class:`~apex_tpu.serving.EngineSupervisor`
    (``submit`` / ``cancel`` / ``tick`` / ``serve`` / ``close``,
    results in :attr:`completed`), so the loadtest runner and other
    drivers work against either unchanged.

    ``faults`` may be a single ``ServingFaultInjector`` (applied to
    replica 0) or a ``{replica_id: injector}`` dict; injector call
    counters keep advancing across replica rebuilds, so a scheduled
    fault fires exactly once fleet-wide.
    """

    def __init__(self, model, params,
                 config: Optional[EngineConfig] = None, *,
                 supervisor: Optional[SupervisorConfig] = None,
                 fleet: Optional[FleetConfig] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 faults=None, router: Optional[Router] = None,
                 engine_factory=None, adapters=None, autoscale=None,
                 sentinel=None, quotas=None, brownout=None):
        self._model = model
        self._params = params
        #: shared LoRA :class:`~apex_tpu.lora.AdapterStore` — every
        #: replica's supervisor (and engine incarnation) reads the SAME
        #: store, so one load()/unload() takes effect fleet-wide and a
        #: migrated continuation finds its adapter on the new replica
        self._adapters = adapters
        self.config = config or EngineConfig()
        self.supervisor_config = supervisor or SupervisorConfig()
        self.fleet = fleet or FleetConfig()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.metrics.declare_counters(*_FLEET_COUNTERS)
        self.metrics.declare_counters(
            *(f"replica{i}_dispatches"
              for i in range(self.fleet.n_replicas)))
        self.router = router if router is not None else Router(
            affinity_weight=self.fleet.prefix_affinity_weight)
        self._engine_factory = engine_factory
        # affinity chains only mean something when replicas actually
        # intern prefixes — flat layout / prefix_cache=False fleets
        # route purely least-loaded (chain stays None)
        self._route_chains = (self.config.kv_layout == "paged"
                              and self.config.prefix_cache
                              and self.router.affinity_weight > 0.0)
        self._route_salt = prefix_salt(model.config)
        if faults is None:
            self._faults: Dict[int, object] = {}
        elif isinstance(faults, dict):
            self._faults = dict(faults)
        else:
            self._faults = {0: faults}
        unknown = set(self._faults) - set(range(self.fleet.n_replicas))
        if unknown:
            raise ValueError(
                f"faults keyed by unknown replica ids {sorted(unknown)}; "
                f"fleet has replicas 0..{self.fleet.n_replicas - 1}")
        self.completed: Dict[int, RequestResult] = {}
        self._tracked: Dict[int, _FleetTracked] = {}
        #: migrated continuations waiting for a dispatchable peer
        self._backlog: List[Request] = []
        self._order = 0
        self._closed = False
        self._engine_restarts_base = 0   # restarts of already-rebuilt sups
        #: per-replica registry views (fleet_metrics.ReplicaRegistry):
        #: every producer call lands on BOTH the replica's local state
        #: and the shared fleet registry, so the global stream/counters
        #: are unchanged while FleetMetrics can split by replica. One
        #: view per replica id, surviving rebuilds — a replica's
        #: counters are cumulative over its whole slot in the fleet.
        self.replica_metrics: Dict[int, ReplicaRegistry] = {}
        #: registry views of RETIRED replicas — removed from every live
        #: per-replica view but still folded into FleetMetrics' merged
        #: counters/histograms, so scaling a replica away never
        #: un-counts the work it did
        self.retired_replica_metrics: Dict[int, ReplicaRegistry] = {}
        #: per-replica weight overrides (canary deploys): a replica id
        #: present here rebuilds with THESE params instead of
        #: ``self._params``; a rollback pops the entry and rebuilds
        self._replica_params: Dict[int, Any] = {}
        #: monotonic id source for scale-ups — retired ids are never
        #: reused, so records/counters stay unambiguous across churn
        self._next_replica_id = self.fleet.n_replicas
        self._deployment = None
        self.replicas: List[_Replica] = [
            _Replica(i, self._build_supervisor(i))
            for i in range(self.fleet.n_replicas)]
        if autoscale is not None:
            from apex_tpu.serving.fleet.autoscale import (
                Autoscaler,
                AutoscaleConfig,
            )
            if isinstance(autoscale, Autoscaler):
                self.autoscaler: Optional[Autoscaler] = autoscale
            elif isinstance(autoscale, AutoscaleConfig):
                self.autoscaler = Autoscaler(autoscale)
            else:
                raise TypeError(
                    f"autoscale must be an AutoscaleConfig or Autoscaler, "
                    f"got {type(autoscale).__name__}")
            cfg = self.autoscaler.config
            if not (cfg.min_replicas <= self.fleet.n_replicas
                    <= cfg.max_replicas):
                raise ValueError(
                    f"n_replicas={self.fleet.n_replicas} outside the "
                    f"autoscaler's [{cfg.min_replicas}, "
                    f"{cfg.max_replicas}] bounds")
        else:
            self.autoscaler = None
        if sentinel is not None:
            from apex_tpu.observability.sentinel import (
                DriftSentinel,
                SentinelConfig,
            )
            if isinstance(sentinel, DriftSentinel):
                self.sentinel: Optional[DriftSentinel] = sentinel
            elif isinstance(sentinel, SentinelConfig):
                self.sentinel = DriftSentinel(sentinel)
            else:
                raise TypeError(
                    f"sentinel must be a SentinelConfig or DriftSentinel, "
                    f"got {type(sentinel).__name__}")
        else:
            self.sentinel = None
        if quotas is not None:
            from apex_tpu.serving.fleet.quota import QuotaConfig, QuotaLedger
            if isinstance(quotas, QuotaLedger):
                self.quota: Optional[QuotaLedger] = quotas
            elif isinstance(quotas, QuotaConfig):
                self.quota = QuotaLedger(quotas)
            else:
                raise TypeError(
                    f"quotas must be a QuotaConfig or QuotaLedger, "
                    f"got {type(quotas).__name__}")
        else:
            self.quota = None
        #: rid -> (tenant, pages) the quota ledger holds for it —
        #: committed at dispatch, released at the terminal state
        self._quota_held: Dict[int, Tuple[str, int]] = {}
        #: backlogged rids waiting on a soft quota (re-checked per tick)
        self._quota_deferred: set = set()
        if brownout is not None:
            from apex_tpu.serving.fleet.brownout import (
                BrownoutConfig,
                BrownoutController,
            )
            if isinstance(brownout, BrownoutController):
                self.brownout: Optional[BrownoutController] = brownout
            elif isinstance(brownout, BrownoutConfig):
                self.brownout = BrownoutController(brownout)
            else:
                raise TypeError(
                    f"brownout must be a BrownoutConfig or "
                    f"BrownoutController, got {type(brownout).__name__}")
        else:
            self.brownout = None

    def _build_supervisor(self, replica_id: int,
                          service_s: Optional[float] = None
                          ) -> EngineSupervisor:
        reg = self.replica_metrics.get(replica_id)
        if reg is None:
            reg = self.replica_metrics[replica_id] = ReplicaRegistry(
                self.metrics, replica_id)
        return EngineSupervisor(
            self._model,
            self._replica_params.get(replica_id, self._params),
            self.config,
            supervisor=self.supervisor_config, metrics=reg,
            faults=self._faults.get(replica_id), replica_id=replica_id,
            service_s=service_s, engine_factory=self._engine_factory,
            adapters=self._adapters)

    # -- introspection ----------------------------------------------------

    def _replica(self, replica_id: int) -> Optional[_Replica]:
        """Id-keyed lookup — replica ids are NOT list indices once
        scale-up/down churn starts (ids are monotonic, never reused)."""
        for replica in self.replicas:
            if replica.replica_id == replica_id:
                return replica
        return None

    @property
    def n_replicas(self) -> int:
        return len(self.replicas)

    @property
    def topology_busy(self) -> Optional[int]:
        """Replica id currently draining or probing, else None — one
        topology change (drain, scale, deploy step) at a time."""
        for r in self.replicas:
            if r.state in (REPLICA_DRAINING, REPLICA_PROBING):
                return r.replica_id
        return None

    @property
    def deployment(self):
        """The current (or most recent) :class:`~apex_tpu.serving.fleet.\
deploy.Deployment`, or None if :meth:`deploy` was never called."""
        return self._deployment

    @property
    def replica_states(self) -> Dict[int, str]:
        return {r.replica_id: r.state for r in self.replicas}

    @property
    def restarts(self) -> int:
        """Engine restarts across the fleet's whole history (rebuilt
        replicas included) — what the loadtest runner reports."""
        return self._engine_restarts_base + sum(
            r.supervisor.restarts for r in self.replicas)

    @property
    def inflight_count(self) -> int:
        """Non-terminal client requests plus in-flight health probes —
        nonzero means :meth:`tick` still has work to advance."""
        return len(self._tracked) + sum(
            1 for r in self.replicas if r.probe_id is not None)

    @property
    def inflight_ids(self) -> List[int]:
        """Ids of non-terminal CLIENT requests (probes are fleet-internal
        and excluded) — what a driver cancels to abort early."""
        return sorted(self._tracked)

    def dispatch_set(self) -> List[_Replica]:
        """Replicas currently taking new work: ACTIVE and breaker not
        open. Draining / probing / failed replicas are excluded — that is
        what makes a restart 'draining' rather than disruptive."""
        return [r for r in self.replicas
                if r.state == REPLICA_ACTIVE
                and r.supervisor.breaker_state != BREAKER_OPEN]

    def _chain_for(self, request: Request) -> Optional[Tuple[int, ...]]:
        """The request's prefix hash chain for affinity routing — the
        SAME chain (same salt, same page size) the target engine will
        look up and intern, or None when affinity is off."""
        if not self._route_chains:
            return None
        # same adapter fold the engine applies: a tenant's chains only
        # collide with that tenant's resident pages
        salt = adapter_salt(self._route_salt, request.sampling.adapter_id)
        return prefix_hash_chain(request.prompt, self.config.page_size,
                                 salt) or None

    # -- admission --------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Route one request to the least-loaded dispatchable replica.
        Raises :class:`FleetUnavailableError` when no replica can take
        work (recorded terminally), or whatever the chosen replica's own
        admission gates raise (queue full, deadline shed — also recorded
        terminally, by the replica, with its ``replica_id``)."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        now = clock.now()
        tenant = pages = None
        if self.quota is not None:
            from apex_tpu.serving.fleet.quota import (
                QUOTA_DEFER,
                QUOTA_SHED,
                QuotaLedger,
            )
            tenant = QuotaLedger.tenant(request)
            pages = self._quota_pages(request)
            verdict, limit = self.quota.verdict(tenant, now, pages=pages)
            if verdict == QUOTA_SHED:
                self._shed_quota(request, tenant, limit, now)   # raises
            if verdict == QUOTA_DEFER:
                self._defer_quota(request, tenant, limit, now)
                return request.request_id
        if self.brownout is not None:
            # at the clamp rung and above, batch submits get a bounded
            # token budget (same ids/deadline/trace — accounting intact)
            request = self.brownout.clamp(request)
        candidates = self.dispatch_set()
        if not candidates:
            self._shed_fleet(request, now)
        # an active adapter-canary deployment pins its tenant's traffic
        # to the canary replica (when dispatchable) so the canary window
        # actually observes the adapter under live load
        dep = self._deployment
        if dep is not None and not dep.done:
            pin = dep.pin_replica(request)
            if pin is not None:
                pinned = [r for r in candidates if r.replica_id == pin]
                if pinned:
                    candidates = pinned
        chain = self._chain_for(request)
        replica = self.router.pick(candidates, chain=chain)
        tr = _FleetTracked(request, now, self._order)
        self._order += 1
        self._tracked[request.request_id] = tr
        try:
            replica.supervisor.submit(request)
        except Exception:
            # the replica recorded the rejection terminally (with its
            # replica_id); keep the fleet's view consistent
            self._harvest_replica(replica, now)
            self._tracked.pop(request.request_id, None)
            raise
        tr.replica_id = replica.replica_id
        self._count_dispatch(replica)
        self.router.note_dispatch(replica.replica_id, chain)
        if self.quota is not None and tenant is not None:
            self.quota.commit(tenant, now, pages=pages or 0)
            self._quota_held[request.request_id] = (tenant, pages or 0)
        return request.request_id

    # -- per-tenant quotas -------------------------------------------------

    def _quota_pages(self, request: Request) -> int:
        """Worst-case KV page footprint the engine's admission will
        reserve (0 on non-paged layouts — the page cap is then inert)."""
        if self.config.kv_layout != "paged":
            return 0
        ps = self.config.page_size
        return -(-request.total_len // ps)

    def _quota_release(self, request_id: int) -> None:
        """Return a terminal request's quota holdings (idempotent)."""
        held = self._quota_held.pop(request_id, None)
        if held is not None and self.quota is not None:
            self.quota.release(held[0], pages=held[1])
        self._quota_deferred.discard(request_id)

    def _shed_quota(self, request: Request, tenant: str,
                    limit: Optional[str], now: float) -> None:
        """Hard quota exceeded: terminal ``rejected`` record + the typed
        ``requests_shed_quota`` counter + ``request_shed`` (reason
        ``quota``) event, then raise — the same contract as
        :meth:`_shed_fleet`, scoped to one tenant."""
        from apex_tpu.serving.fleet.quota import QuotaExceededError
        self.metrics.inc("requests_submitted")
        self.metrics.inc("requests_shed_quota")
        self.metrics.inc(f"requests_{FINISH_REJECTED}")
        start = request.arrival_ts if request.arrival_ts is not None \
            else now
        result = RequestResult(
            request_id=request.request_id, prompt_len=request.prompt_len,
            tokens=[], finish_reason=FINISH_REJECTED,
            queue_s=now - start, total_s=now - start,
            adapter_id=request.sampling.adapter_id,
            trace_id=request.trace_id,
            priority=request.sampling.priority)
        self.completed[request.request_id] = result
        wall = clock.wall()
        emit_span(self.metrics, SPAN_SHED, trace_id=request.trace_id,
                  request_id=request.request_id, start_s=start,
                  end_s=now, wall=wall, detail="quota")
        self.metrics.emit_record(result.record(wall=wall))
        log_event(_LOG, "request_shed", request_id=request.request_id,
                  reason="quota", tenant=tenant, limit=limit)
        self.metrics.event("request_shed", request_id=request.request_id,
                           reason="quota", tenant=tenant, limit=limit)
        raise QuotaExceededError(
            f"request {request.request_id} shed: tenant {tenant!r} is "
            f"over its {limit} quota")

    def _defer_quota(self, request: Request, tenant: str,
                     limit: Optional[str], now: float) -> None:
        """Soft quota exceeded: throttle instead of shed — the request
        joins the fleet backlog (counted submitted NOW, dispatched as a
        resubmission later) and is re-checked against the ledger every
        tick until its bucket refills or its deadline expires."""
        self.metrics.inc("requests_submitted")
        self.metrics.inc("requests_deferred_quota")
        tr = _FleetTracked(request, now, self._order)
        self._order += 1
        self._tracked[request.request_id] = tr
        self._quota_deferred.add(request.request_id)
        self._backlog.append(request)
        log_event(_LOG, "request_quota_deferred",
                  request_id=request.request_id, tenant=tenant,
                  limit=limit)
        self.metrics.event("request_quota_deferred",
                           request_id=request.request_id, tenant=tenant,
                           limit=limit)

    def _count_dispatch(self, replica: _Replica) -> None:
        replica.dispatches += 1
        self.metrics.inc("fleet_dispatches")
        self.metrics.inc(f"replica{replica.replica_id}_dispatches")

    def _shed_fleet(self, request: Request, now: float) -> None:
        """No dispatchable replica: terminal ``rejected`` record +
        counters + ``request_shed`` (reason ``fleet``) event, then
        raise — the same contract as the supervisor's ``_shed``."""
        self.metrics.inc("requests_submitted")
        self.metrics.inc("requests_shed_fleet")
        self.metrics.inc(f"requests_{FINISH_REJECTED}")
        start = request.arrival_ts if request.arrival_ts is not None \
            else now
        result = RequestResult(
            request_id=request.request_id, prompt_len=request.prompt_len,
            tokens=[], finish_reason=FINISH_REJECTED,
            queue_s=now - start, total_s=now - start,
            adapter_id=request.sampling.adapter_id,
            trace_id=request.trace_id,
            priority=request.sampling.priority)
        self.completed[request.request_id] = result
        wall = clock.wall()
        # front-door shed: one shed phase span, no replica_id (the
        # request never reached one)
        emit_span(self.metrics, SPAN_SHED, trace_id=request.trace_id,
                  request_id=request.request_id, start_s=start,
                  end_s=now, wall=wall, detail="fleet")
        self.metrics.emit_record(result.record(wall=wall))
        states = {r.replica_id: (BREAKER_OPEN
                                 if r.supervisor.breaker_state ==
                                 BREAKER_OPEN and r.state == REPLICA_ACTIVE
                                 else r.state)
                  for r in self.replicas}
        log_event(_LOG, "request_shed", request_id=request.request_id,
                  reason="fleet", replicas=str(states))
        self.metrics.event("request_shed", request_id=request.request_id,
                           reason="fleet", replicas=str(states))
        raise FleetUnavailableError(
            f"request {request.request_id} shed at the fleet front door: "
            f"no dispatchable replica (states: {states}) — every replica "
            f"is draining, failed, or has an open circuit breaker")

    def cancel(self, request_id: int) -> bool:
        """Cancel wherever the request currently lives: the migration
        backlog, or (sticky) the replica it was dispatched to."""
        now = clock.now()
        tr = self._tracked.get(request_id)
        if tr is None:
            return False
        for i, cont in enumerate(self._backlog):
            if cont.request_id == request_id:
                del self._backlog[i]
                self._tracked.pop(request_id)
                self._quota_release(request_id)
                self._retire_fleet(tr, "cancelled", now)
                return True
        if tr.replica_id is None:
            return False
        replica = self._replica(tr.replica_id)
        if replica is None:
            return False
        found = replica.supervisor.cancel(request_id)
        if found:
            self._harvest_replica(replica, now)
        return found

    # -- the fleet tick ---------------------------------------------------

    def tick(self) -> List[RequestResult]:
        """One fleet iteration: re-home migrated work, tick every live
        replica (each runs at most one decode step), harvest terminal
        results, and advance any drain/probe lifecycle. Returns requests
        that reached a terminal state in the fleet's view."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        before = set(self.completed)
        self._dispatch_backlog()
        for replica in list(self.replicas):
            if replica.state == REPLICA_FAILED:
                continue
            replica.supervisor.tick()
            self._harvest_replica(replica, clock.now())
        self._advance_drains()
        now = clock.now()
        if self._deployment is not None and not self._deployment.done:
            self._deployment.step(self, now)
        if self.autoscaler is not None:
            self.autoscaler.maybe_scale(self, now)
        if self.sentinel is not None:
            # after the autoscaler so a scale decision's effect on queue
            # depth and the anomaly that provoked it share a tick stamp
            self.sentinel.maybe_poll(self, now)
        if self.brownout is not None:
            # last: the ladder reacts to pressure the autoscaler could
            # not absorb (bounds hit, or building too slowly)
            self.brownout.maybe_step(self, now)
        return [self.completed[rid] for rid in sorted(
            set(self.completed) - before)]

    def serve(self, requests: Sequence[Request], *,
              on_tick: Optional[Callable[["ReplicaFleet", int], None]]
              = None, max_ticks: Optional[int] = None
              ) -> List[RequestResult]:
        """Serve ``requests`` to completion across the fleet. Requests
        rejected at admission (fleet or replica gates) are terminal
        immediately with ``finish_reason="rejected"`` — every submitted
        request reaches exactly one terminal state."""
        pending = list(requests)
        ids = [r.request_id for r in pending]
        ticks = 0
        while pending or self.inflight_count:
            while pending:
                req = pending[0]
                targets = self.dispatch_set()
                if targets and all(
                        Router.depth(t) >= self.config.scheduler.max_queue
                        for t in targets):
                    break       # every queue is full: tick, then retry
                pending.pop(0)
                try:
                    self.submit(req)
                except (EngineUnavailableError, QueueFullError,
                        DeadlineExpiredError):
                    pass        # already recorded terminally
            self.tick()
            ticks += 1
            if on_tick is not None:
                on_tick(self, ticks)
            if max_ticks is not None and ticks >= max_ticks:
                break
        return [self.completed[i] for i in ids if i in self.completed]

    # -- draining restarts ------------------------------------------------

    def drain_restart(self, replica_id: int) -> None:
        """Begin a draining restart of one replica: quiesce (leave the
        dispatch set), migrate or finish its in-flight work, rebuild,
        health-probe, rejoin. Progress happens across :meth:`tick`
        calls; fleet capacity never drops below N−1 because only one
        replica may be draining/probing at a time (a second request
        raises ``RuntimeError`` instead of silently stacking drains)."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        replica = self._replica(replica_id)
        if replica is None:
            raise ValueError(
                f"no replica {replica_id} (fleet has "
                f"{sorted(r.replica_id for r in self.replicas)})")
        if replica.state != REPLICA_ACTIVE:
            raise RuntimeError(
                f"replica {replica_id} is {replica.state}, not active")
        busy = self.topology_busy
        if busy is not None:
            raise RuntimeError(
                f"replica {busy} is already draining/probing — one "
                f"restart at a time keeps fleet capacity at N-1")
        replica.state = REPLICA_DRAINING
        self.metrics.inc("replica_drains")
        inflight = replica.supervisor.inflight_count
        log_event(_LOG, "replica_drain", replica_id=replica_id,
                  inflight=inflight,
                  migrate=self.fleet.migrate_on_drain)
        self.metrics.event("replica_drain", replica_id=replica_id,
                           inflight=inflight,
                           migrate=self.fleet.migrate_on_drain)
        if self.fleet.migrate_on_drain:
            self._migrate_from(replica)
        self._advance_drains()

    def _migrate_from(self, replica: _Replica) -> None:
        """Detach the draining replica's non-terminal work as token-exact
        continuations and queue them for peers."""
        now = clock.now()
        conts = replica.supervisor.detach_for_migration()
        self._harvest_replica(replica, now)   # detach may retire some
        for cont, recovered in conts:
            tr = self._tracked.get(cont.request_id)
            if tr is None:      # cancelled between snapshot and handover
                continue
            tr.prefix += recovered
            tr.replica_id = None
            tr.migrations += 1
            self.metrics.inc("requests_migrated")
            log_event(_LOG, "request_migrated",
                      request_id=cont.request_id,
                      from_replica=replica.replica_id,
                      tokens_carried=len(recovered))
            self.metrics.event("request_migrated",
                               request_id=cont.request_id,
                               from_replica=replica.replica_id,
                               tokens_carried=len(recovered))
            # mark span (zero-width): the handoff instant — the carried
            # token count explains any TTFT/decode split across replicas
            emit_span(self.metrics, SPAN_MIGRATION,
                      trace_id=cont.trace_id,
                      request_id=cont.request_id, start_s=now,
                      end_s=now, wall=clock.wall(),
                      from_replica=replica.replica_id,
                      tokens_carried=len(recovered))
            self._backlog.append(cont)
        self._dispatch_backlog()

    def _dispatch_backlog(self) -> None:
        """Re-home backlogged work — migrated continuations and
        quota-deferred submits — on the least-loaded peer with queue
        room, in priority order (rank, then arrival order) so a
        backlogged interactive request never waits behind batch.
        Deferred entries are re-checked against the quota ledger (and
        their deadline) first; whatever cannot be placed yet stays
        backlogged and keeps being retried every tick — never dropped."""
        if not self._backlog:
            return
        self._backlog.sort(key=lambda c: (
            PRIORITY_RANK.get(c.sampling.priority,
                              PRIORITY_RANK[PRIORITY_STANDARD]),
            self._tracked[c.request_id].order
            if c.request_id in self._tracked else 0))
        kept: List[Request] = []
        for cont in self._backlog:
            rid = cont.request_id
            tr = self._tracked.get(rid)
            if tr is None:
                continue        # cancelled while backlogged
            now = clock.now()
            deferred = rid in self._quota_deferred
            tenant = pages = None
            if deferred:
                start = cont.arrival_ts if cont.arrival_ts is not None \
                    else tr.first_submit_ts
                if cont.deadline_s is not None \
                        and now - start > cont.deadline_s:
                    # a throttled request whose bucket never refilled in
                    # time — terminal, never silently dropped
                    self._tracked.pop(rid)
                    self._quota_release(rid)
                    self._retire_fleet(tr, FINISH_TIMEOUT, now)
                    continue
                if self.quota is not None:
                    from apex_tpu.serving.fleet.quota import (
                        QUOTA_ADMIT,
                        QuotaLedger,
                    )
                    tenant = QuotaLedger.tenant(cont)
                    pages = self._quota_pages(cont)
                    verdict, _ = self.quota.verdict(tenant, now,
                                                    pages=pages)
                    if verdict != QUOTA_ADMIT:
                        kept.append(cont)
                        continue
            candidates = [r for r in self.dispatch_set()
                          if Router.depth(r)
                          < self.config.scheduler.max_queue]
            if not candidates:
                kept.append(cont)
                continue
            # the continuation's prompt is the stitched original-plus-
            # recovered-tokens the peer will actually prefill, so its
            # chain (a superset of the original's) is the right
            # affinity key
            chain = self._chain_for(cont)
            replica = self.router.pick(candidates, chain=chain)
            try:
                replica.supervisor.submit(cont, resubmission=True)
            except (QueueFullError, DeadlineExpiredError,
                    EngineUnavailableError):
                # recorded terminally by the replica — harvest below
                self._harvest_replica(replica, clock.now())
                continue
            tr.replica_id = replica.replica_id
            self._count_dispatch(replica)
            self.router.note_dispatch(replica.replica_id, chain)
            if deferred:
                self._quota_deferred.discard(rid)
                if self.quota is not None and tenant is not None:
                    self.quota.commit(tenant, now, pages=pages or 0)
                    self._quota_held[rid] = (tenant, pages or 0)
        self._backlog = kept

    def _advance_drains(self) -> None:
        """Move the drain/probe lifecycle forward: rebuild (or, for a
        scale-down, retire) a drained-out replica, then score its health
        probe. Iterates a copy — retirement mutates ``self.replicas``."""
        for replica in list(self.replicas):
            if (replica.state == REPLICA_DRAINING
                    and replica.supervisor.inflight_count == 0):
                if replica.retire_on_drain:
                    self._finish_retire(replica)
                    continue
                self._rebuild(replica)
            if replica.state == REPLICA_PROBING:
                self._check_probe(replica)

    # -- autoscaling: add / retire replicas -------------------------------

    def add_replica(self) -> int:
        """Scale up by one replica (the autoscaler's up-leg, also usable
        directly). The new replica gets a fresh, never-reused id and
        joins through the SAME health-probe gate as a rebuild: it enters
        the dispatch set only after a real one-token probe request
        succeeds (``probe_on_rebuild`` permitting). One topology change
        at a time — raises ``RuntimeError`` while another replica is
        draining or probing. Returns the new replica id."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        busy = self.topology_busy
        if busy is not None:
            raise RuntimeError(
                f"replica {busy} is draining/probing — one topology "
                f"change at a time")
        rid = self._next_replica_id
        self._next_replica_id += 1
        self.metrics.declare_counters(f"replica{rid}_dispatches")
        replica = _Replica(rid, self._build_supervisor(rid))
        self.replicas.append(replica)
        self.metrics.inc("replica_scale_ups")
        log_event(_LOG, "replica_scale_up", replica_id=rid,
                  n_replicas=len(self.replicas))
        self.metrics.event("replica_scale_up", replica_id=rid,
                           n_replicas=len(self.replicas))
        if self.fleet.probe_on_rebuild:
            replica.state = REPLICA_PROBING
            self._launch_probe(replica)
        else:
            replica.state = REPLICA_ACTIVE
        return rid

    def retire_replica(self, replica_id: int) -> None:
        """Scale down by retiring one replica (the autoscaler's
        down-leg): drain it through the migrate-or-finish machinery —
        no request dropped — then REMOVE it from the fleet entirely
        (its id never comes back; its counters fold into the retired
        ledger so fleet totals still reconcile). One topology change at
        a time; the last active replica cannot be retired."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        replica = self._replica(replica_id)
        if replica is None:
            raise ValueError(
                f"no replica {replica_id} (fleet has "
                f"{sorted(r.replica_id for r in self.replicas)})")
        if replica.state != REPLICA_ACTIVE:
            raise RuntimeError(
                f"replica {replica_id} is {replica.state}, not active")
        busy = self.topology_busy
        if busy is not None:
            raise RuntimeError(
                f"replica {busy} is draining/probing — one topology "
                f"change at a time")
        others = [r for r in self.replicas
                  if r.state == REPLICA_ACTIVE and r is not replica]
        if not others:
            raise RuntimeError(
                f"replica {replica_id} is the last active replica — "
                f"retiring it would empty the dispatch set")
        replica.state = REPLICA_DRAINING
        replica.retire_on_drain = True
        self.metrics.inc("replica_scale_downs")
        inflight = replica.supervisor.inflight_count
        log_event(_LOG, "replica_scale_down", replica_id=replica_id,
                  inflight=inflight, n_replicas=len(self.replicas))
        self.metrics.event("replica_scale_down", replica_id=replica_id,
                           inflight=inflight,
                           n_replicas=len(self.replicas))
        if self.fleet.migrate_on_drain:
            self._migrate_from(replica)
        self._advance_drains()

    def _finish_retire(self, replica: _Replica) -> None:
        """Terminal leg of a scale-down: the drain has emptied — close
        the supervisor, remove the id from the fleet, the router's
        residency/cost tables, and every live per-replica metrics view
        (the registry moves to ``retired_replica_metrics`` so merged
        fleet totals keep reconciling with the parent)."""
        rid = replica.replica_id
        self._harvest_replica(replica, clock.now())
        self._engine_restarts_base += replica.supervisor.restarts
        replica.supervisor.close()
        self.replicas.remove(replica)
        self.router.invalidate(rid)
        reg = self.replica_metrics.pop(rid, None)
        if reg is not None:
            self.retired_replica_metrics[rid] = reg
        log_event(_LOG, "replica_retired", replica_id=rid,
                  n_replicas=len(self.replicas))
        self.metrics.event("replica_retired", replica_id=rid,
                           n_replicas=len(self.replicas))

    # -- continuous deployment --------------------------------------------

    def deploy(self, checkpoint_dir: Optional[str] = None, *,
               step: Optional[int] = None, adapter=None, canary=None):
        """Start a rolling canary deployment
        (docs/serving.md#continuous-deployment). Exactly one of
        ``checkpoint_dir`` (roll every replica onto the committed
        sharded checkpoint at ``step``, default latest, via draining
        restarts) or ``adapter`` (``(adapter_id, factors)`` — hot-load
        a LoRA adapter through the shared ``AdapterStore`` and canary
        it on one replica, gated on its per-tenant SLO score).

        The checkpoint is fsck-verified BEFORE the first drain — a
        corrupt step raises
        :class:`~apex_tpu.checkpoint.CheckpointCorruptionError` here
        (recorded as ``deploy_rejected``) and no replica is touched.
        Progress then happens across :meth:`tick` calls; watch
        :attr:`deployment`. Raises ``RuntimeError`` if a deployment is
        already in progress."""
        from apex_tpu.serving.fleet.deploy import Deployment
        if self._closed:
            raise RuntimeError("fleet is closed")
        if self._deployment is not None and not self._deployment.done:
            raise RuntimeError(
                f"deployment {self._deployment.describe()} is already "
                f"in progress — one rollout at a time")
        dep = Deployment(checkpoint_dir=checkpoint_dir, step=step,
                         adapter=adapter, canary=canary)
        try:
            dep.start(self)
        except Exception:
            if dep.done:        # recorded as deploy_rejected: keep it
                self._deployment = dep   # visible (and non-blocking)
            raise
        self._deployment = dep
        return dep

    def _rebuild(self, replica: _Replica) -> None:
        """Tear down the drained supervisor and build a fresh one (new
        engine, slot pool, jit programs), carrying the service-time EWMA
        so post-rebuild deadline shedding is not blind."""
        old = replica.supervisor
        carried = old.service_estimate_s
        self._engine_restarts_base += old.restarts
        old.close()
        # the fresh engine's intern index is empty — stale affinity
        # would keep routing this replica's old prefixes at a replica
        # that now misses on all of them
        self.router.invalidate(replica.replica_id)
        replica.supervisor = self._build_supervisor(
            replica.replica_id, service_s=carried)
        self.metrics.inc("replica_rebuilds")
        log_event(_LOG, "replica_rebuild", replica_id=replica.replica_id,
                  carried_service_s=carried)
        self.metrics.event("replica_rebuild",
                           replica_id=replica.replica_id,
                           carried_service_s=carried)
        if self.fleet.probe_on_rebuild:
            replica.state = REPLICA_PROBING
            self._launch_probe(replica)
        else:
            replica.state = REPLICA_ACTIVE

    def _launch_probe(self, replica: _Replica) -> None:
        """One-token greedy health probe through the NORMAL submit path —
        counted and recorded like any request (conservation holds), so a
        replica only rejoins after serving real work end-to-end."""
        replica.probe_attempts += 1
        probe = Request(prompt=[0], max_new_tokens=1,
                        sampling=SamplingParams())
        replica.probe_id = probe.request_id
        try:
            replica.supervisor.submit(probe)
        except Exception:       # a probe the engine cannot even queue
            replica.probe_id = None
            self._probe_failed(replica)

    def _check_probe(self, replica: _Replica) -> None:
        if replica.probe_id is None:
            return
        res = replica.supervisor.completed.get(replica.probe_id)
        if res is None:
            return              # probe still in flight; keep ticking
        replica.probe_id = None
        if res.finish_reason in (FINISH_EOS, FINISH_LENGTH):
            replica.state = REPLICA_ACTIVE
            replica.probe_attempts = 0
        else:
            self._probe_failed(replica)

    def _probe_failed(self, replica: _Replica) -> None:
        if replica.probe_attempts >= self.fleet.max_rebuild_probes:
            replica.state = REPLICA_FAILED
            log_event(_LOG, "replica_failed",
                      replica_id=replica.replica_id,
                      probe_attempts=replica.probe_attempts)
            self.metrics.event("replica_failed",
                               replica_id=replica.replica_id,
                               probe_attempts=replica.probe_attempts)
            return
        self._rebuild(replica)  # another rebuild + probe round

    # -- harvesting -------------------------------------------------------

    def _harvest_replica(self, replica: _Replica, now: float) -> None:
        """Pull newly-terminal results from one replica into the fleet's
        view, stitching migrated requests back together (fleet-side
        prefix + the replica's continuation tokens, the ORIGINAL prompt
        length, total latency from the FIRST dispatch)."""
        sup = replica.supervisor
        done = [rid for rid in list(self._tracked)
                if rid in sup.completed]
        for rid in sorted(done, key=lambda r: self._tracked[r].order):
            tr = self._tracked.pop(rid)
            self._quota_release(rid)
            res = sup.completed[rid]
            if tr.prefix or tr.migrations:
                res = RequestResult(
                    request_id=rid, prompt_len=tr.request.prompt_len,
                    tokens=tr.prefix + res.tokens,
                    finish_reason=res.finish_reason,
                    queue_s=res.queue_s, prefill_s=res.prefill_s,
                    decode_s=res.decode_s,
                    total_s=now - tr.first_submit_ts,
                    ttft_s=None if tr.prefix else res.ttft_s,
                    tpot_s=res.tpot_s, replica_id=res.replica_id)
            self.completed[rid] = res

    def _retire_fleet(self, tr: _FleetTracked, reason: str,
                      now: float) -> RequestResult:
        """Terminal retirement by the fleet itself (cancelled from the
        migration backlog): one counter, one record, one event — the
        same contract as a replica-side finish."""
        rid = tr.request.request_id
        result = RequestResult(
            request_id=rid, prompt_len=tr.request.prompt_len,
            tokens=list(tr.prefix), finish_reason=reason,
            total_s=now - tr.first_submit_ts,
            adapter_id=tr.request.sampling.adapter_id,
            trace_id=tr.request.trace_id,
            priority=tr.request.sampling.priority)
        self.completed[rid] = result
        self.metrics.inc(f"requests_{reason}")
        wall = clock.wall()
        # no replica will ever finish this request (it died in the
        # migration backlog), so the fleet owns its timeline: one coarse
        # phase span over the whole fleet-tracked lifetime
        emit_span(self.metrics,
                  SPAN_DECODE if reason in (FINISH_EOS, FINISH_LENGTH)
                  else SPAN_SHED,
                  trace_id=tr.request.trace_id, request_id=rid,
                  start_s=tr.first_submit_ts, end_s=now, wall=wall,
                  detail="migration_backlog")
        self.metrics.emit_record(result.record(wall=wall))
        log_event(_LOG, f"request_{reason}", request_id=rid,
                  new_tokens=result.new_tokens)
        self.metrics.event(f"request_{reason}", request_id=rid,
                           new_tokens=result.new_tokens)
        return result

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Close every replica (releases slots, flushes the registry).
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        for replica in self.replicas:
            replica.supervisor.close()

    def __enter__(self) -> "ReplicaFleet":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False
