"""SLO-driven autoscaling for :class:`~apex_tpu.serving.fleet.ReplicaFleet`.

Closes the serve half of the ROADMAP's train->serve loop: the fleet's
size stops being frozen at construction. An :class:`Autoscaler` is a
policy object polled from the fleet tick loop
(``ReplicaFleet(..., autoscale=AutoscaleConfig(...))``). Each poll it
reads :meth:`~apex_tpu.observability.FleetMetrics.signals` — windowed
goodput, queue depth plus the token-weighted ``queued_tokens`` backlog,
merged TTFT/TPOT p99, slot/page occupancy — and decides between
``min_replicas`` and ``max_replicas``:

- **scale-up** spawns a replica through the existing
  rebuild-and-health-probe path (:meth:`ReplicaFleet.add_replica`): the
  new replica joins the dispatch set only after a real one-token probe
  request succeeds, so a scale-up can never route traffic at an engine
  that cannot serve.
- **scale-down** retires the least-loaded ACTIVE replica through
  ``drain_restart``'s migrate-or-finish machinery
  (:meth:`ReplicaFleet.retire_replica`): in-flight work migrates
  token-exact or finishes in place — no request dropped — and the id is
  removed from the router's cost/residency tables and every live
  per-replica metrics view.

Decisions are deliberately sluggish: a direction must hold for
``hysteresis_polls`` consecutive polls, at most one topology change per
``cooldown_s`` window, and the autoscaler holds entirely while any
replica is draining/probing or a deployment is rolling — signal noise
cannot flap the fleet. Every applied decision is emitted as a typed
``kind="autoscale"`` record plus a ``replica_scale_up``/
``replica_scale_down`` event+counter pair that the monitor's fleet
section reconciles key-for-key.

The policy itself (:meth:`Autoscaler.desired_direction`) is a pure
function of one signals dict — unit-testable without a fleet, an
engine, or jax.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from apex_tpu.observability.fleet_metrics import FleetMetrics
from apex_tpu.serving import clock
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["AutoscaleConfig", "Autoscaler"]

_LOG = get_logger(__name__)

#: signals keys echoed into each kind="autoscale" decision record — the
#: evidence the decision was made on, for the monitor's timeline
_DECISION_SIGNALS = ("replicas_total", "replicas_dispatchable",
                     "queue_depth", "queued_tokens", "inflight",
                     "goodput_window", "window_terminal", "window_s",
                     "ttft_p99_s", "slot_occupancy")


@dataclass(frozen=True)
class AutoscaleConfig:
    """Policy knobs (docs/serving.md#autoscaling).

    Scale-up triggers (any one suffices; 0 disables a trigger):

    - ``scale_up_queue_per_replica`` — queued requests per dispatchable
      replica above this means admission is outrunning capacity;
    - ``scale_up_queued_tokens_per_replica`` — same, token-weighted
      (a backlog of LONG prompts trips this before raw depth does);
    - ``scale_up_goodput`` — windowed goodput below this *with traffic
      in the window* (``window_terminal > 0``; an idle window's 0.0
      never scales up);
    - ``scale_up_ttft_p99_s`` — merged TTFT p99 above the SLO bound.

    Scale-down requires quiet on EVERY axis: queue per replica at or
    under ``scale_down_queue_per_replica`` AND slot occupancy at or
    under ``scale_down_slot_occupancy`` (an unmeasurable occupancy
    counts as quiet).

    Flap damping: a direction must hold ``hysteresis_polls``
    consecutive polls (spaced ``poll_interval_s`` apart) and applied
    decisions are at least ``cooldown_s`` apart.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    poll_interval_s: float = 0.25
    cooldown_s: float = 2.0
    hysteresis_polls: int = 2
    scale_up_queue_per_replica: float = 4.0
    scale_up_queued_tokens_per_replica: float = 0.0
    scale_up_goodput: float = 0.0
    scale_up_ttft_p99_s: float = 0.0
    scale_down_queue_per_replica: float = 0.5
    scale_down_slot_occupancy: float = 0.25

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s must be > 0, got {self.poll_interval_s}")
        if self.cooldown_s < 0:
            raise ValueError(
                f"cooldown_s must be >= 0, got {self.cooldown_s}")
        if self.hysteresis_polls < 1:
            raise ValueError(
                f"hysteresis_polls must be >= 1, got "
                f"{self.hysteresis_polls}")
        for knob in ("scale_up_queue_per_replica",
                     "scale_up_queued_tokens_per_replica",
                     "scale_up_goodput", "scale_up_ttft_p99_s",
                     "scale_down_queue_per_replica",
                     "scale_down_slot_occupancy"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob} must be >= 0, got {getattr(self, knob)}")
        if not 0.0 <= self.scale_up_goodput <= 1.0:
            raise ValueError(
                f"scale_up_goodput must be in [0, 1], got "
                f"{self.scale_up_goodput}")
        if (self.scale_up_queue_per_replica > 0
                and self.scale_down_queue_per_replica
                >= self.scale_up_queue_per_replica):
            raise ValueError(
                f"scale_down_queue_per_replica "
                f"({self.scale_down_queue_per_replica}) must be < "
                f"scale_up_queue_per_replica "
                f"({self.scale_up_queue_per_replica}) — overlapping "
                f"bands would flap")


class Autoscaler:
    """The fleet-size policy; polled via :meth:`maybe_scale` from
    ``ReplicaFleet.tick``. Holds its OWN :class:`FleetMetrics` view so
    its goodput window is private — an application also polling
    ``signals()`` on its own view cannot steal the autoscaler's window
    deltas."""

    def __init__(self, config: Optional[AutoscaleConfig] = None):
        self.config = config or AutoscaleConfig()
        self._fm: Optional[FleetMetrics] = None
        self._last_poll: Optional[float] = None
        self._last_action_ts: Optional[float] = None
        self._streak_dir: Optional[str] = None
        self._streak = 0
        #: applied decisions, for tests/drivers: (now, action,
        #: replica_id, reason) tuples in order
        self.decisions: List[Tuple[float, str, int, str]] = []

    # -- the pure policy ---------------------------------------------------

    def desired_direction(self, signals: dict
                          ) -> Tuple[Optional[str], Optional[str]]:
        """Map one signals dict to ``("up"|"down"|None, reason)`` —
        pure, no side effects, no fleet access."""
        cfg = self.config
        dispatchable = max(1, signals.get("replicas_dispatchable") or 0)
        queue_per = (signals.get("queue_depth") or 0) / dispatchable
        if (cfg.scale_up_queue_per_replica > 0
                and queue_per > cfg.scale_up_queue_per_replica):
            return "up", "queue_depth"
        tokens_per = (signals.get("queued_tokens") or 0) / dispatchable
        if (cfg.scale_up_queued_tokens_per_replica > 0
                and tokens_per > cfg.scale_up_queued_tokens_per_replica):
            return "up", "queued_tokens"
        # goodput is only evidence when the window saw traffic: an idle
        # window reports 0.0 with window_terminal == 0 — never scale on it
        if (cfg.scale_up_goodput > 0
                and (signals.get("window_terminal") or 0) > 0
                and signals.get("goodput_window", 1.0)
                < cfg.scale_up_goodput):
            return "up", "goodput"
        ttft = signals.get("ttft_p99_s")
        if (cfg.scale_up_ttft_p99_s > 0 and ttft is not None
                and ttft > cfg.scale_up_ttft_p99_s):
            return "up", "ttft_p99"
        occupancy = signals.get("slot_occupancy")
        if (queue_per <= cfg.scale_down_queue_per_replica
                and (occupancy is None
                     or occupancy <= cfg.scale_down_slot_occupancy)):
            return "down", "idle"
        return None, None

    # -- the fleet-side actuator ------------------------------------------

    def maybe_scale(self, fleet, now: Optional[float] = None
                    ) -> Optional[str]:
        """One poll: read signals, damp, and apply at most one topology
        change. Returns ``"up"``/``"down"`` when a change was applied,
        else None. Safe to call every tick — the poll interval is
        enforced internally."""
        if now is None:
            now = clock.now()
        if (self._last_poll is not None
                and now - self._last_poll < self.config.poll_interval_s):
            return None
        self._last_poll = now
        if self._fm is None or self._fm.fleet is not fleet:
            self._fm = FleetMetrics(fleet)
        signals = self._fm.signals()
        direction, reason = self.desired_direction(signals)
        # clamp to bounds BEFORE streak accounting: a direction the
        # bounds forbid is no direction at all
        n = fleet.n_replicas
        if direction == "up" and n >= self.config.max_replicas:
            direction = None
        if direction == "down" and n <= self.config.min_replicas:
            direction = None
        if direction is None:
            self._streak_dir, self._streak = None, 0
            return None
        if direction == self._streak_dir:
            self._streak += 1
        else:
            self._streak_dir, self._streak = direction, 1
        if self._streak < self.config.hysteresis_polls:
            return None
        if (self._last_action_ts is not None
                and now - self._last_action_ts < self.config.cooldown_s):
            return None
        # hold (without resetting the streak) while the fleet is mid
        # topology change or a deployment is rolling — one change at a
        # time is the fleet's invariant, not just ours
        if fleet.topology_busy is not None:
            return None
        deployment = getattr(fleet, "deployment", None)
        if deployment is not None and not deployment.done:
            return None
        if direction == "up":
            replica_id = fleet.add_replica()
        else:
            replica_id = self._retire_target(fleet)
            if replica_id is None:
                return None
            fleet.retire_replica(replica_id)
        self._last_action_ts = now
        self._streak_dir, self._streak = None, 0
        self.decisions.append((now, direction, replica_id, reason))
        excerpt = {k: signals.get(k) for k in _DECISION_SIGNALS}
        log_event(_LOG, f"autoscale_{direction}", replica_id=replica_id,
                  reason=reason, n_replicas=fleet.n_replicas)
        fleet.metrics.emit_record({
            "kind": "autoscale",
            "action": f"scale_{direction}",
            "replica_id": replica_id,
            "reason": reason,
            "n_replicas": fleet.n_replicas,
            "signals": excerpt,
            "wall": clock.wall()})
        return direction

    @staticmethod
    def _retire_target(fleet) -> Optional[int]:
        """Least-loaded ACTIVE replica; depth ties retire the YOUNGEST
        id (scale-ups unwind in reverse order, keeping the original
        replicas long-lived)."""
        from apex_tpu.serving.fleet.router import REPLICA_ACTIVE, Router
        candidates = [r for r in fleet.replicas
                      if r.state == REPLICA_ACTIVE]
        if len(candidates) < 2:
            return None
        target = min(candidates,
                     key=lambda r: (Router.depth(r), -r.replica_id))
        return target.replica_id
