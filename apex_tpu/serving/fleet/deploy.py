"""Continuous deployment: rolling canary weight updates with
auto-rollback for :class:`~apex_tpu.serving.fleet.ReplicaFleet`.

Closes the train half of the ROADMAP's train->serve loop: a freshly
trained checkpoint (or LoRA adapter) reaches the serving fleet without
a restart, through :meth:`ReplicaFleet.deploy`:

- **Checkpoint rollout** — the committed step is fsck-verified through
  the PR 8 path (:meth:`~apex_tpu.checkpoint.ShardedCheckpointManager.\
verify_step`, deep) BEFORE any replica is touched: a corrupt
  checkpoint rejects the deploy outright. The state is then
  elastically restored once (any saved topology -> the fleet's
  template) and rolled replica-by-replica via draining restarts — the
  same quiesce/migrate/rebuild/probe machinery as ``drain_restart``,
  so in-flight requests survive every transition token-exact and
  capacity never drops below N-1.
- **Canary scoring** — each rebuilt replica serves live traffic for a
  configurable window (:class:`CanaryConfig`) and is scored on its
  per-replica SLO metrics: error rate over scored terminals, TTFT/TPOT
  p99 against the incumbents' same-window p99. Integrity machinery
  cannot catch weights that are *numerically* poisoned (checksums pass
  on poisoned bytes; the one-token health probe emits argmax of NaN
  logits, a valid token) — the canary's live-traffic error rate is
  genuinely the first detector. Pass promotes the rollout to the next
  replica; fail freezes the rollout and auto-rolls the canary back to
  the incumbent weights through another draining restart — zero
  requests dropped, migrated requests keep their original
  ``trace_id``, exactly one terminal record each.
- **LoRA adapter canary** — ``deploy(adapter=(adapter_id, factors))``
  hot-loads the adapter into the shared
  :class:`~apex_tpu.lora.AdapterStore`, pins the tenant's traffic to
  one canary replica, and scores ONLY that tenant's results (the
  per-tenant ``slo_by_adapter`` slice). Fail quiesces the tenant's
  in-flight work, then unloads the adapter — base traffic never sees
  the canary at all.

Every decision is a typed ``kind="deploy"`` record plus an
event+counter pair (``deploy_start``/``deploys_started``,
``canary_promoted``/``canary_promotions``,
``deploy_rollback``/``deploys_rolled_back``,
``deploy_complete``/``deploys_completed``,
``deploy_rejected``/``deploys_rejected``) the monitor reconciles
key-for-key.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Set

from apex_tpu.checkpoint import (
    CheckpointCorruptionError,
    ShardedCheckpointManager,
)
from apex_tpu.observability.registry import percentile
from apex_tpu.serving import clock
from apex_tpu.serving.fleet.router import (
    REPLICA_ACTIVE,
    REPLICA_FAILED,
    Router,
)
from apex_tpu.serving.request import (
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    Request,
    RequestResult,
)
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["CanaryConfig", "Deployment",
           "DEPLOY_ROLLING", "DEPLOY_DRAINING", "DEPLOY_CANARY",
           "DEPLOY_ROLLING_BACK", "DEPLOY_UNLOADING",
           "DEPLOY_COMPLETE", "DEPLOY_ROLLED_BACK", "DEPLOY_REJECTED"]

_LOG = get_logger(__name__)

#: deployment lifecycle states (``Deployment.state``)
DEPLOY_ROLLING = "rolling"            # waiting to drain the next replica
DEPLOY_DRAINING = "draining"          # canary rebuilding on new weights
DEPLOY_CANARY = "canary"              # scoring window open
DEPLOY_ROLLING_BACK = "rolling_back"  # canary draining back to incumbent
DEPLOY_UNLOADING = "unloading"        # adapter rollback: tenant quiescing
DEPLOY_COMPLETE = "complete"          # every replica promoted
DEPLOY_ROLLED_BACK = "rolled_back"    # canary failed; incumbent restored
DEPLOY_REJECTED = "rejected"          # fsck failed before the first drain

_TERMINAL = (DEPLOY_COMPLETE, DEPLOY_ROLLED_BACK, DEPLOY_REJECTED)

#: finish reasons a canary score counts: successes + engine faults.
#: cancelled/timeout/rejected are driver- or load-caused, not evidence
#: about the canary's weights
_SCORED = (FINISH_EOS, FINISH_LENGTH, FINISH_ERROR)


@dataclass(frozen=True)
class CanaryConfig:
    """Canary scoring knobs (docs/serving.md#continuous-deployment).

    A promoted replica's window closes when BOTH ``window_s`` wall
    seconds have elapsed AND at least ``min_requests`` scored terminals
    landed on the canary — but never later than ``max_window_s``, at
    which point whatever evidence exists is scored (zero scored
    requests fails closed: an unobservable canary must not promote).

    ``max_error_rate`` bounds the canary's error share of scored
    terminals (0.0 = any engine error fails). ``latency_ratio`` gates
    the canary's TTFT/TPOT p99 at that multiple of the incumbents'
    same-window p99 (0 disables; only applied when both sides
    measured).
    """

    window_s: float = 0.5
    min_requests: int = 3
    max_window_s: float = 10.0
    max_error_rate: float = 0.0
    latency_ratio: float = 0.0

    def __post_init__(self):
        if self.window_s <= 0:
            raise ValueError(
                f"window_s must be > 0, got {self.window_s}")
        if self.min_requests < 0:
            raise ValueError(
                f"min_requests must be >= 0, got {self.min_requests}")
        if self.max_window_s < self.window_s:
            raise ValueError(
                f"max_window_s ({self.max_window_s}) must be >= "
                f"window_s ({self.window_s})")
        if not 0.0 <= self.max_error_rate <= 1.0:
            raise ValueError(
                f"max_error_rate must be in [0, 1], got "
                f"{self.max_error_rate}")
        if self.latency_ratio < 0:
            raise ValueError(
                f"latency_ratio must be >= 0, got {self.latency_ratio}")


def _p99(results: List[RequestResult], attr: str) -> Optional[float]:
    values = [getattr(r, attr) for r in results
              if getattr(r, attr) is not None]
    if not values:
        return None
    return percentile(values, 99)


class Deployment:
    """One rolling canary deployment; construct via
    :meth:`ReplicaFleet.deploy`, driven by :meth:`step` from the fleet
    tick loop. Exactly one of ``checkpoint_dir`` / ``adapter``."""

    def __init__(self, checkpoint_dir: Optional[str] = None, *,
                 step: Optional[int] = None, adapter=None,
                 canary: Optional[CanaryConfig] = None):
        if (checkpoint_dir is None) == (adapter is None):
            raise ValueError(
                "deploy exactly one of checkpoint_dir or adapter")
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_step = step
        self.adapter_id: Optional[str] = None
        self._adapter_factors = None
        if adapter is not None:
            try:
                self.adapter_id, self._adapter_factors = adapter
            except (TypeError, ValueError):
                raise ValueError(
                    "adapter must be an (adapter_id, factors) pair")
        self.canary = canary or CanaryConfig()
        self.state: Optional[str] = None     # None until start()
        self.rollback_reason: Optional[str] = None
        #: replica ids promoted onto the new weights, in order
        self.promoted: List[int] = []
        self.scores: List[dict] = []         # one entry per closed window
        self._queue: List[int] = []
        self._canary_rid: Optional[int] = None
        self._new_params: Any = None
        self._window_start: Optional[float] = None
        self._seen: Set[int] = set()
        self._canary_results: List[RequestResult] = []
        self._incumbent_results: List[RequestResult] = []

    # -- introspection -----------------------------------------------------

    @property
    def done(self) -> bool:
        return self.state in _TERMINAL

    @property
    def canary_replica(self) -> Optional[int]:
        return self._canary_rid

    def describe(self) -> str:
        if self.adapter_id is not None:
            return f"adapter:{self.adapter_id}"
        return f"checkpoint:{self.checkpoint_dir}@{self.checkpoint_step}"

    def pin_replica(self, request: Request) -> Optional[int]:
        """Replica to pin ``request`` to, or None. Only an adapter
        canary pins, only its own tenant, only while scoring — base
        traffic routes normally throughout."""
        if (self.adapter_id is not None
                and self.state == DEPLOY_CANARY
                and request.sampling.adapter_id == self.adapter_id):
            return self._canary_rid
        return None

    # -- record/event emission --------------------------------------------

    def _record(self, fleet, action: str, **fields) -> None:
        rec = {"kind": "deploy", "action": action,
               "target": self.describe(), "wall": clock.wall()}
        rec.update(fields)
        fleet.metrics.emit_record(rec)

    def _incident(self, fleet, event: str, counter: str,
                  **fields) -> None:
        """One counter increment co-sited with its same-named event —
        the serving telemetry contract the monitor reconciles."""
        fleet.metrics.inc(counter)
        log_event(_LOG, event, target=self.describe(), **fields)
        fleet.metrics.event(event, target=self.describe(), **fields)

    # -- lifecycle ---------------------------------------------------------

    def start(self, fleet) -> None:
        """Verify and stage the new weights; called from
        ``ReplicaFleet.deploy`` before the deployment is installed.
        Raises (after recording ``deploy_rejected``) when the
        checkpoint fails its fsck or the adapter cannot load — no
        replica has been touched yet in either case."""
        now = clock.now()
        if self.adapter_id is not None:
            self._start_adapter(fleet, now)
            return
        mgr = ShardedCheckpointManager(self.checkpoint_dir)
        step = self.checkpoint_step
        if step is None:
            step = mgr.latest_step()
        if step is None:
            self._reject(fleet, "no committed checkpoint step")
            raise CheckpointCorruptionError(
                f"{self.checkpoint_dir}: no committed step to deploy")
        self.checkpoint_step = int(step)
        try:
            # the PR 8 fsck path: per-shard checksums, manifest sha,
            # commit marker — BEFORE the first drain
            mgr.verify_step(self.checkpoint_step, deep=True)
        except CheckpointCorruptionError as e:
            self._reject(fleet, str(e))
            raise
        # elastic restore once, host-side — every replica rebuilds from
        # this same restored pytree (any saved topology -> the fleet's)
        self._new_params = mgr.restore_step(self.checkpoint_step,
                                            fleet._params)
        self._queue = [r.replica_id for r in fleet.replicas
                       if r.state != REPLICA_FAILED]
        self.state = DEPLOY_ROLLING
        self._incident(fleet, "deploy_start", "deploys_started",
                       replicas=len(self._queue))
        self._record(fleet, "start", replicas=list(self._queue))

    def _start_adapter(self, fleet, now: float) -> None:
        if fleet._adapters is None:
            raise ValueError(
                "fleet has no AdapterStore — construct it with "
                "adapters= to deploy a LoRA adapter")
        try:
            fleet._adapters.load(self.adapter_id, self._adapter_factors)
        except Exception as e:
            self._reject(fleet, str(e))
            raise
        # least-loaded ACTIVE replica hosts the pinned tenant traffic
        candidates = [r for r in fleet.replicas
                      if r.state == REPLICA_ACTIVE]
        if not candidates:
            fleet._adapters.unload(self.adapter_id)
            self._reject(fleet, "no active replica to canary on")
            raise RuntimeError("no active replica to canary the "
                               "adapter on")
        target = min(candidates,
                     key=lambda r: (Router.depth(r), r.replica_id))
        self._canary_rid = target.replica_id
        self._incident(fleet, "deploy_start", "deploys_started",
                       replica_id=self._canary_rid)
        self._record(fleet, "start", replica_id=self._canary_rid)
        self._open_window(fleet, now)

    def _reject(self, fleet, reason: str) -> None:
        self.state = DEPLOY_REJECTED
        self.rollback_reason = reason
        self._incident(fleet, "deploy_rejected", "deploys_rejected",
                       reason=reason)
        self._record(fleet, "rejected", reason=reason)

    # -- the tick-driven state machine ------------------------------------

    def step(self, fleet, now: float) -> None:
        """Advance one tick; called from ``ReplicaFleet.tick``."""
        if self.done:
            return
        if self.state == DEPLOY_ROLLING:
            self._step_rolling(fleet, now)
        elif self.state == DEPLOY_DRAINING:
            self._step_draining(fleet, now)
        elif self.state == DEPLOY_CANARY:
            self._step_canary(fleet, now)
        elif self.state == DEPLOY_ROLLING_BACK:
            self._step_rolling_back(fleet)
        elif self.state == DEPLOY_UNLOADING:
            self._step_unloading(fleet)

    def _step_rolling(self, fleet, now: float) -> None:
        if fleet.topology_busy is not None:
            return
        while self._queue:
            rid = self._queue[0]
            replica = fleet._replica(rid)
            if replica is None or replica.state != REPLICA_ACTIVE:
                self._queue.pop(0)   # retired/failed since start: skip
                continue
            break
        if not self._queue:
            self._complete(fleet)
            return
        rid = self._queue.pop(0)
        self._canary_rid = rid
        fleet._replica_params[rid] = self._new_params
        fleet.drain_restart(rid)
        self.state = DEPLOY_DRAINING

    def _step_draining(self, fleet, now: float) -> None:
        # unreachable for adapter deploys (no drain in that flow)
        replica = fleet._replica(self._canary_rid)
        if replica is None:
            self._begin_rollback(fleet, None, "replica_lost")
            return
        if replica.state == REPLICA_FAILED:
            # new weights cannot even pass the one-token probe
            self._begin_rollback(fleet, None, "probe_failed")
            return
        if replica.state == REPLICA_ACTIVE:
            self._open_window(fleet, now)

    def _open_window(self, fleet, now: float) -> None:
        self.state = DEPLOY_CANARY
        self._window_start = now
        self._seen = set(fleet.completed)
        self._canary_results = []
        self._incumbent_results = []

    def _collect(self, fleet) -> None:
        fresh = set(fleet.completed) - self._seen
        self._seen |= fresh
        for rid in fresh:
            res = fleet.completed[rid]
            if self.adapter_id is not None:
                if res.adapter_id == self.adapter_id:
                    self._canary_results.append(res)
                elif res.replica_id is not None:
                    self._incumbent_results.append(res)
            elif res.replica_id == self._canary_rid:
                self._canary_results.append(res)
            elif res.replica_id is not None:
                self._incumbent_results.append(res)

    def _step_canary(self, fleet, now: float) -> None:
        self._collect(fleet)
        elapsed = now - (self._window_start or now)
        if elapsed < self.canary.window_s:
            return
        scored = [r for r in self._canary_results
                  if r.finish_reason in _SCORED]
        if (len(scored) < self.canary.min_requests
                and elapsed < self.canary.max_window_s):
            return              # keep the window open for more evidence
        score = self._score(scored)
        self.scores.append(score)
        if score["pass"]:
            self.promoted.append(self._canary_rid)
            self._incident(fleet, "canary_promoted",
                           "canary_promotions",
                           replica_id=self._canary_rid)
            self._record(fleet, "canary_pass",
                         replica_id=self._canary_rid, score=score)
            if self.adapter_id is not None:
                self._complete(fleet)
            else:
                self.state = DEPLOY_ROLLING
            return
        self._begin_rollback(fleet, score, score["reason"])

    def _score(self, scored: List[RequestResult]) -> dict:
        cfg = self.canary
        errors = sum(1 for r in scored
                     if r.finish_reason == FINISH_ERROR)
        error_rate = errors / len(scored) if scored else None
        c_ttft = _p99(scored, "ttft_s")
        c_tpot = _p99(scored, "tpot_s")
        inc_scored = [r for r in self._incumbent_results
                      if r.finish_reason in _SCORED]
        i_ttft = _p99(inc_scored, "ttft_s")
        i_tpot = _p99(inc_scored, "tpot_s")
        verdict, reason = True, None
        if not scored:
            # fail closed: a canary no traffic reached is unprovable
            verdict, reason = False, "no_traffic"
        elif error_rate > cfg.max_error_rate:
            verdict, reason = False, "error_rate"
        elif cfg.latency_ratio > 0:
            if (c_ttft is not None and i_ttft is not None
                    and c_ttft > i_ttft * cfg.latency_ratio):
                verdict, reason = False, "ttft_p99"
            elif (c_tpot is not None and i_tpot is not None
                    and c_tpot > i_tpot * cfg.latency_ratio):
                verdict, reason = False, "tpot_p99"
        return {"pass": verdict, "reason": reason,
                "replica_id": self._canary_rid,
                "requests": len(scored), "errors": errors,
                "error_rate": error_rate,
                "max_error_rate": cfg.max_error_rate,
                "canary_ttft_p99_s": c_ttft,
                "incumbent_ttft_p99_s": i_ttft,
                "canary_tpot_p99_s": c_tpot,
                "incumbent_tpot_p99_s": i_tpot,
                "latency_ratio": cfg.latency_ratio,
                "incumbent_requests": len(inc_scored)}

    def _begin_rollback(self, fleet, score: Optional[dict],
                        reason: str) -> None:
        """Freeze the rollout and return the canary to the incumbent
        weights (checkpoint) or quiesce-and-unload (adapter)."""
        self.rollback_reason = reason
        self._incident(fleet, "deploy_rollback", "deploys_rolled_back",
                       replica_id=self._canary_rid, reason=reason)
        self._record(fleet, "rollback", replica_id=self._canary_rid,
                     reason=reason, score=score)
        if self.adapter_id is not None:
            self.state = DEPLOY_UNLOADING
            self._step_unloading(fleet)
            return
        fleet._replica_params.pop(self._canary_rid, None)
        replica = fleet._replica(self._canary_rid)
        if replica is None:
            self.state = DEPLOY_ROLLED_BACK
            return
        if replica.state == REPLICA_ACTIVE:
            # mid-canary fail: drain back — in-flight work migrates
            # token-exact with its original trace_ids
            fleet.drain_restart(self._canary_rid)
        elif replica.state == REPLICA_FAILED:
            # probe-failed on the NEW weights: rebuild directly onto
            # the incumbent params (the override is already popped)
            replica.probe_attempts = 0
            fleet._rebuild(replica)
        self.state = DEPLOY_ROLLING_BACK

    def _step_rolling_back(self, fleet) -> None:
        replica = fleet._replica(self._canary_rid)
        if replica is None or replica.state in (REPLICA_ACTIVE,
                                                 REPLICA_FAILED):
            # active: incumbent weights restored and probed. failed:
            # already recorded as replica_failed — a fleet incident,
            # not a deploy state; the rollout is over either way.
            self.state = DEPLOY_ROLLED_BACK

    def _step_unloading(self, fleet) -> None:
        """Adapter rollback: wait until no in-flight request of the
        tenant remains (unloading earlier would silently degrade their
        streams to base-model output mid-decode), then unload."""
        inflight = any(
            tr.request.sampling.adapter_id == self.adapter_id
            for tr in fleet._tracked.values())
        if inflight:
            return
        fleet._adapters.unload(self.adapter_id)
        self.state = DEPLOY_ROLLED_BACK

    def _complete(self, fleet) -> None:
        if self.adapter_id is None:
            # the new weights are now the fleet's baseline: future
            # rebuilds/scale-ups build from them with no override
            fleet._params = self._new_params
            for rid in list(fleet._replica_params):
                if fleet._replica_params[rid] is self._new_params:
                    fleet._replica_params.pop(rid)
        self.state = DEPLOY_COMPLETE
        self._incident(fleet, "deploy_complete", "deploys_completed",
                       promoted=len(self.promoted))
        self._record(fleet, "complete", promoted=list(self.promoted))
