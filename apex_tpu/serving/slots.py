"""Fixed-capacity decode slot pool with free-list allocation.

Each slot is one row of the engine's batched KV cache: a request holds
exactly one slot from prefill to retirement, and the pool's invariant —
every slot is either free or owned by exactly one request — is what the
scheduler tests mean by "no slot leaks". Allocation always hands out
the LOWEST free slot id so runs are deterministic (the same arrival
order always produces the same slot assignment, and therefore the same
decode batch layout).

Under the paged KV layout (``kv_layout="paged"``, docs/serving.md), a
slot row no longer reserves ``max_len`` cache memory; instead each slot
maps a variable number of fixed-size pages out of a shared
:class:`PagePool`. Pages are REFCOUNTED: a page may back the shared
prompt prefix of many slots at once (docs/serving.md#prefix-cache), so
the one-owner invariant generalizes to refcount conservation — every
page is either on the free heap or carries exactly as many references
as slot mappings plus intern-index entries that hold it, and it returns
to the heap only when the count reaches zero. A content-addressed
intern index (:meth:`PagePool.intern_prefix` /
:meth:`PagePool.match_prefix`) keeps page-aligned prompt prefixes
resident after their writer retires; an LRU over the interned entries
bounds the index and is evicted under allocation pressure instead of
shedding. :meth:`PagePool.check` asserts the full conservation
invariant and is what "no page leaks / no premature frees" means in the
tests.
"""

from __future__ import annotations

import heapq
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["SlotError", "SlotPool", "PageError", "PagePool"]


class SlotError(RuntimeError):
    """A slot-pool invariant was violated (double release, foreign id)."""


class PageError(RuntimeError):
    """A page-pool invariant was violated (leak, foreign page, double map)."""


class SlotPool:
    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._free: List[int] = list(range(capacity))  # already a heap
        self._active: set = set()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return len(self._active)

    @property
    def occupancy(self) -> float:
        """Active fraction in [0, 1] — the slot-occupancy histogram feed."""
        return len(self._active) / self.capacity

    def allocate(self) -> Optional[int]:
        """Lowest free slot id, or None when the pool is exhausted."""
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._active.add(slot)
        return slot

    def release(self, slot: int) -> None:
        if slot not in self._active:
            raise SlotError(
                f"release of slot {slot} which is not active "
                f"(double release or foreign id; active={sorted(self._active)})")
        self._active.remove(slot)
        heapq.heappush(self._free, slot)

    def reset(self) -> None:
        """Return EVERY slot to the free list — the supervisor's engine
        rebuild / ``close()`` path, where all in-flight occupants are
        being retired at once. Re-asserts the no-leak invariant after
        the rebuild; safe to call on an already-clean pool."""
        self._free = list(range(self.capacity))
        self._active.clear()
        self.check()

    def check(self) -> None:
        """Assert the no-leak invariant; raises :class:`SlotError`."""
        if len(self._free) + len(self._active) != self.capacity or \
                set(self._free) & self._active:
            raise SlotError(
                f"slot leak: {len(self._free)} free + "
                f"{len(self._active)} active != capacity {self.capacity}")


class PagePool:
    """Refcounted free-list allocator for the global KV page pool.

    Host-side bookkeeping only — the device arrays live in the engine.
    ``n_pages`` pool rows are handed out lowest-first as per-slot page
    lists; a slot's logical page order is its SHARED prefix pages (mapped
    read-only from the intern index) followed by its PRIVATE pages (fresh
    write targets for the suffix and decode tail). ``pages_per_slot``
    bounds one slot's list — it is the page-table width, i.e. the paged
    engine's ``max_len`` in pages.

    ``lru_capacity`` sizes the prefix-intern index (entries, not pages);
    0 disables interning entirely, which restores the PR 9 one-owner
    behavior bit-for-bit (``prefix_cache=False``). The conservation
    invariant either way: every page is on the free heap XOR its
    refcount equals its slot-list memberships plus intern-entry
    memberships (:meth:`check`).
    """

    def __init__(self, n_pages: int, page_size: int, pages_per_slot: int,
                 lru_capacity: int = 0):
        if n_pages < 1 or page_size < 1 or pages_per_slot < 1:
            raise ValueError(
                f"n_pages/page_size/pages_per_slot must be >= 1, got "
                f"{n_pages}/{page_size}/{pages_per_slot}")
        if lru_capacity < 0:
            raise ValueError(
                f"lru_capacity must be >= 0, got {lru_capacity}")
        self.n_pages = n_pages
        self.page_size = page_size
        self.pages_per_slot = pages_per_slot
        self.lru_capacity = lru_capacity
        self._free: List[int] = list(range(n_pages))  # already a heap
        self._refs: Dict[int, int] = {}               # page -> refcount
        self._shared: Dict[int, List[int]] = {}       # slot -> prefix pages
        self._owned: Dict[int, List[int]] = {}        # slot -> private pages
        #: chain -> pages, oldest-first (LRU order; move_to_end on touch)
        self._interned: "OrderedDict[Tuple[int, ...], List[int]]" = \
            OrderedDict()
        #: cumulative intern-entry evictions (capacity + pressure) — the
        #: engine snapshots deltas into its ``prefix_evictions`` counter
        self.evictions = 0
        #: free pages the engine's quarantine scrub has zeroed (content
        #: AND, on quantized pools, the scale sidecar) — tracked so
        #: :meth:`check` can assert the zero-scale invariant on them;
        #: membership ends at the page's next allocation
        self._scrubbed: set = set()

    # -- introspection ----------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use_count(self) -> int:
        """Referenced pages: slot-mapped or kept alive by the intern
        index. ``free + in_use == n_pages`` always."""
        return self.n_pages - len(self._free)

    @property
    def owned_count(self) -> int:
        """Private (write-target) pages across all slots — the pages the
        reservation ledger already paid for."""
        return sum(len(v) for v in self._owned.values())

    @property
    def reclaimable_count(self) -> int:
        """Referenced pages held ONLY by intern entries: dropping every
        entry would free exactly this many — the admission predicate's
        extra headroom on top of ``free_count``."""
        slot_held = set()
        for pages in self._shared.values():
            slot_held.update(pages)
        for pages in self._owned.values():
            slot_held.update(pages)
        return sum(1 for p in self._refs if p not in slot_held)

    @property
    def interned_count(self) -> int:
        """Entries currently in the intern index."""
        return len(self._interned)

    @property
    def occupancy(self) -> float:
        """Referenced fraction in [0, 1] — the kv_page_occupancy feed."""
        return self.in_use_count / self.n_pages

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` cache rows."""
        return -(-tokens // self.page_size)

    def slot_pages(self, slot: int) -> List[int]:
        """The pages currently mapped to ``slot`` (logical order:
        shared prefix first, then private)."""
        return list(self._shared.get(slot, ())) + \
            list(self._owned.get(slot, ()))

    def shared_pages(self, slot: int) -> List[int]:
        """Just the shared prefix pages of ``slot``."""
        return list(self._shared.get(slot, ()))

    # -- the prefix-intern index ------------------------------------------

    def match_prefix(self, chain: Sequence[int]) -> Tuple[List[int], int]:
        """Longest interned leading run of ``chain``: returns
        ``(pages, matched)`` where ``pages`` back tokens
        ``[0, matched * page_size)``. Touches the matched entry's LRU
        position. ``([], 0)`` on a miss (or when interning is off)."""
        best_key, best = None, 0
        for key in self._interned:
            n = 0
            for a, b in zip(key, chain):
                if a != b:
                    break
                n += 1
            if n > best:
                best_key, best = key, n
        if best_key is None:
            return [], 0
        self._interned.move_to_end(best_key)
        return list(self._interned[best_key][:best]), best

    def intern_prefix(self, chain: Sequence[int],
                      pages: Sequence[int]) -> bool:
        """Publish ``pages`` (one per chain entry, already referenced by
        their writer slot) as the immutable backing of ``chain``. Each
        page gains one reference held by the entry, so the prefix
        outlives the writer's retirement. A shorter entry this one
        extends (same leading pages) is upgraded away; at
        ``lru_capacity`` the LRU entry is evicted. Returns True when a
        new entry was created (False: duplicate, or interning off)."""
        if self.lru_capacity <= 0 or not chain:
            return False
        key = tuple(int(h) for h in chain)
        pages = list(pages)
        if len(pages) != len(key):
            raise PageError(
                f"intern chain has {len(key)} entries but {len(pages)} "
                f"pages — one full page per chain entry")
        if key in self._interned:
            self._interned.move_to_end(key)
            return False
        for p in pages:
            if self._refs.get(p, 0) < 1:
                raise PageError(
                    f"intern of unreferenced page {p} — prefixes are "
                    f"published from a LIVE slot's mapping")
        subsumed = [k for k in self._interned
                    if len(k) < len(key) and key[:len(k)] == k
                    and self._interned[k] == pages[:len(k)]]
        for k in subsumed:
            self._drop_entry(k)     # upgrade, not an eviction
        while len(self._interned) >= self.lru_capacity:
            self._drop_entry(next(iter(self._interned)))
            self.evictions += 1
        for p in pages:
            self._refs[p] += 1
        self._interned[key] = pages
        return True

    def _drop_entry(self, key: Tuple[int, ...]) -> int:
        """Remove one intern entry, freeing pages whose last reference
        it held; returns the number of pages freed."""
        freed = 0
        for p in self._interned.pop(key):
            if self._unref(p):
                freed += 1
        return freed

    def _unref(self, p: int) -> bool:
        """Drop one reference; freelists (and reports True) at zero."""
        r = self._refs[p] - 1
        if r:
            self._refs[p] = r
            return False
        del self._refs[p]
        heapq.heappush(self._free, p)
        return True

    def _take_free(self, k: int) -> Optional[List[int]]:
        """Pop ``k`` pages off the free heap, evicting intern entries
        (oldest-first, only ones that actually free pages) under
        pressure. None when the pool genuinely cannot supply them —
        all-or-nothing, no partial allocation."""
        while k > len(self._free):
            victim = None
            for key in self._interned:   # oldest-first
                if any(self._refs.get(p, 0) == 1
                       for p in self._interned[key]):
                    victim = key
                    break
            if victim is None:
                return None
            self._drop_entry(victim)
            self.evictions += 1
        pages = [heapq.heappop(self._free) for _ in range(k)]
        for p in pages:
            self._refs[p] = self._refs.get(p, 0) + 1
            self._scrubbed.discard(p)   # allocated: may be written again
        return pages

    # -- slot mapping -----------------------------------------------------

    def map_slot(self, slot: int, tokens: int,
                 shared: Optional[Sequence[int]] = None
                 ) -> Optional[List[int]]:
        """Map a fresh slot with enough pages for ``tokens`` rows.

        ``shared`` (from :meth:`match_prefix`) maps those pages as the
        slot's read-only prefix — they gain a reference instead of
        leaving the free heap — and only the remainder is allocated
        privately. Returns the full page list (logical order), or None
        when the pool cannot supply the private remainder even after
        evicting reclaimable intern entries — the caller defers or sheds
        rather than partially mapping (all-or-None holds WITH a hit: a
        hit whose private remainder cannot fit maps nothing). A slot may
        only be mapped once between releases.
        """
        if slot in self._owned:
            raise PageError(f"slot {slot} is already mapped")
        shared = list(shared) if shared else []
        need = self.pages_for(max(tokens, 1))
        if need > self.pages_per_slot:
            raise PageError(
                f"slot {slot} needs {need} pages > pages_per_slot "
                f"{self.pages_per_slot}")
        if len(shared) > need:
            raise PageError(
                f"slot {slot}: shared prefix ({len(shared)} pages) "
                f"exceeds the {need}-page mapping")
        for p in shared:
            if self._refs.get(p, 0) < 1:
                raise PageError(
                    f"shared page {p} is unreferenced — stale "
                    f"match_prefix result?")
        # pin the shared run FIRST so pressure eviction inside the
        # private allocation can never free the pages we are mapping
        for p in shared:
            self._refs[p] += 1
        fresh = self._take_free(need - len(shared))
        if fresh is None:
            for p in shared:
                self._unref(p)      # roll back: all-or-None
            return None
        self._shared[slot] = shared
        self._owned[slot] = fresh
        return shared + fresh

    def extend_slot(self, slot: int, tokens: int) -> Optional[List[int]]:
        """Grow ``slot`` to cover ``tokens`` rows (decode on-demand path).

        Returns the NEWLY mapped private pages (possibly empty), or None
        when the pool is exhausted even after evicting reclaimable
        intern entries — the slot keeps its existing pages and the
        caller decides whether to retire it.
        """
        if slot not in self._owned:
            raise PageError(f"extend of unmapped slot {slot}")
        have = len(self._shared.get(slot, ())) + len(self._owned[slot])
        need = self.pages_for(tokens)
        if need > self.pages_per_slot:
            raise PageError(
                f"slot {slot} needs {need} pages > pages_per_slot "
                f"{self.pages_per_slot}")
        grow = need - have
        if grow <= 0:
            return []
        fresh = self._take_free(grow)
        if fresh is None:
            return None
        self._owned[slot].extend(fresh)
        return fresh

    def note_scrubbed(self, pages: Sequence[int]) -> None:
        """Record that the engine zeroed these FREE pages (quarantine
        hygiene). On quantized pools the scrub also zeroes the scale
        sidecar, and :meth:`check` asserts that stays true until the
        page is allocated again."""
        for p in pages:
            if p in self._refs:
                raise PageError(
                    f"scrub of referenced page {p} — the scrub program "
                    f"must only touch pages whose last reference dropped")
            self._scrubbed.add(p)

    def release_slot(self, slot: int) -> List[int]:
        """Drop all of ``slot``'s references; returns the pages whose
        LAST reference this release dropped (now back on the free heap —
        the scrub path zeroes exactly these rows). Shared pages still
        held by co-tenant slots or the intern index stay mapped and are
        NOT in the returned list."""
        if slot not in self._owned:
            raise PageError(
                f"release of unmapped slot {slot} "
                f"(double release or foreign id; "
                f"mapped={sorted(self._owned)})")
        freed = []
        for p in self._shared.pop(slot, []) + self._owned.pop(slot):
            if self._unref(p):
                freed.append(p)
        return freed

    def reset(self) -> None:
        """Return EVERY page to the free heap AND clear the prefix-intern
        index + LRU — engine rebuild/close path, mirroring
        :meth:`SlotPool.reset`. A rebuilt engine must start from a full
        pool with an empty index (recovery never assumes residency)."""
        self._free = list(range(self.n_pages))
        self._refs.clear()
        self._shared.clear()
        self._owned.clear()
        self._interned.clear()
        self._scrubbed.clear()
        self.check()

    def check(self, k_scales=None, v_scales=None) -> None:
        """Assert refcount conservation; raises :class:`PageError`.

        Every page's refcount must equal its slot-list memberships plus
        intern-entry memberships; the free heap and the referenced set
        partition ``n_pages`` exactly; no slot maps a page twice or
        exceeds ``pages_per_slot``. With a quantized pool's scale
        sidecars (``k_scales``/``v_scales``, ``[n_pages, kv_heads]``
        arrays — pass one layer's), additionally asserts every page the
        scrub zeroed (:meth:`note_scrubbed`) still carries all-zero
        scales while free — the invariant that keeps a recycled page's
        rescale floor clean."""
        import numpy as _np
        for name, scales in (("k", k_scales), ("v", v_scales)):
            if scales is None:
                continue
            sc = _np.asarray(scales)
            if sc.shape[0] != self.n_pages:
                raise PageError(
                    f"{name}_scales has {sc.shape[0]} rows, pool has "
                    f"{self.n_pages} pages")
            stale = [p for p in sorted(self._scrubbed)
                     if p not in self._refs and sc[p].any()]
            if stale:
                raise PageError(
                    f"scrubbed free pages carry nonzero {name} scales: "
                    f"{stale[:8]} — scrub/reset must zero the sidecar")
        expect: Dict[int, int] = {}
        holders = list(self._shared.values()) + list(self._owned.values()) \
            + list(self._interned.values())
        for pages in holders:
            for p in pages:
                if not 0 <= p < self.n_pages:
                    raise PageError(f"foreign page id {p} "
                                    f"(pool has 0..{self.n_pages - 1})")
                expect[p] = expect.get(p, 0) + 1
        if expect != self._refs:
            bad = {p: (self._refs.get(p), expect.get(p))
                   for p in set(expect) | set(self._refs)
                   if self._refs.get(p) != expect.get(p)}
            raise PageError(
                f"refcount drift (page: (recorded, actual)): {bad}")
        free_set = set(self._free)
        if len(free_set) != len(self._free) or free_set & set(expect) or \
                len(self._free) + len(expect) != self.n_pages:
            raise PageError(
                f"page leak: {len(self._free)} free + {len(expect)} "
                f"referenced != n_pages {self.n_pages} (or a page is "
                f"both free and referenced)")
        for slot in set(self._shared) | set(self._owned):
            pages = self.slot_pages(slot)
            if len(set(pages)) != len(pages):
                raise PageError(f"slot {slot} maps a page twice: {pages}")
            if len(pages) > self.pages_per_slot:
                raise PageError(
                    f"slot {slot} maps {len(pages)} pages > "
                    f"pages_per_slot {self.pages_per_slot}")
