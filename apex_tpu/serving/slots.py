"""Fixed-capacity decode slot pool with free-list allocation.

Each slot is one row of the engine's batched KV cache
(``[max_slots, max_len]`` per layer): a request holds exactly one slot
from prefill to retirement, and the pool's invariant — every slot is
either free or owned by exactly one request — is what the scheduler
tests mean by "no slot leaks". Allocation always hands out the LOWEST
free slot id so runs are deterministic (the same arrival order always
produces the same slot assignment, and therefore the same decode batch
layout).
"""

from __future__ import annotations

import heapq
from typing import List, Optional

__all__ = ["SlotError", "SlotPool"]


class SlotError(RuntimeError):
    """A slot-pool invariant was violated (double release, foreign id)."""


class SlotPool:
    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._free: List[int] = list(range(capacity))  # already a heap
        self._active: set = set()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return len(self._active)

    @property
    def occupancy(self) -> float:
        """Active fraction in [0, 1] — the slot-occupancy histogram feed."""
        return len(self._active) / self.capacity

    def allocate(self) -> Optional[int]:
        """Lowest free slot id, or None when the pool is exhausted."""
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._active.add(slot)
        return slot

    def release(self, slot: int) -> None:
        if slot not in self._active:
            raise SlotError(
                f"release of slot {slot} which is not active "
                f"(double release or foreign id; active={sorted(self._active)})")
        self._active.remove(slot)
        heapq.heappush(self._free, slot)

    def reset(self) -> None:
        """Return EVERY slot to the free list — the supervisor's engine
        rebuild / ``close()`` path, where all in-flight occupants are
        being retired at once. Re-asserts the no-leak invariant after
        the rebuild; safe to call on an already-clean pool."""
        self._free = list(range(self.capacity))
        self._active.clear()
        self.check()

    def check(self) -> None:
        """Assert the no-leak invariant; raises :class:`SlotError`."""
        if len(self._free) + len(self._active) != self.capacity or \
                set(self._free) & self._active:
            raise SlotError(
                f"slot leak: {len(self._free)} free + "
                f"{len(self._active)} active != capacity {self.capacity}")
