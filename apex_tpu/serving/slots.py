"""Fixed-capacity decode slot pool with free-list allocation.

Each slot is one row of the engine's batched KV cache: a request holds
exactly one slot from prefill to retirement, and the pool's invariant —
every slot is either free or owned by exactly one request — is what the
scheduler tests mean by "no slot leaks". Allocation always hands out
the LOWEST free slot id so runs are deterministic (the same arrival
order always produces the same slot assignment, and therefore the same
decode batch layout).

Under the paged KV layout (``kv_layout="paged"``, docs/serving.md), a
slot row no longer reserves ``max_len`` cache memory; instead each slot
maps a variable number of fixed-size pages out of a shared
:class:`PagePool`, so HBM is committed to *actual* context length and
long-context mixes stop being bounded by ``max_slots × max_len``. The
PagePool mirrors SlotPool's discipline exactly — lowest-first free
heap, one-owner invariant, :meth:`PagePool.check` as the leak assert —
but allocation is per-slot *lists* of pages that grow on demand during
decode and are returned wholesale at retirement.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional

__all__ = ["SlotError", "SlotPool", "PageError", "PagePool"]


class SlotError(RuntimeError):
    """A slot-pool invariant was violated (double release, foreign id)."""


class PageError(RuntimeError):
    """A page-pool invariant was violated (leak, foreign page, double map)."""


class SlotPool:
    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._free: List[int] = list(range(capacity))  # already a heap
        self._active: set = set()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return len(self._active)

    @property
    def occupancy(self) -> float:
        """Active fraction in [0, 1] — the slot-occupancy histogram feed."""
        return len(self._active) / self.capacity

    def allocate(self) -> Optional[int]:
        """Lowest free slot id, or None when the pool is exhausted."""
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._active.add(slot)
        return slot

    def release(self, slot: int) -> None:
        if slot not in self._active:
            raise SlotError(
                f"release of slot {slot} which is not active "
                f"(double release or foreign id; active={sorted(self._active)})")
        self._active.remove(slot)
        heapq.heappush(self._free, slot)

    def reset(self) -> None:
        """Return EVERY slot to the free list — the supervisor's engine
        rebuild / ``close()`` path, where all in-flight occupants are
        being retired at once. Re-asserts the no-leak invariant after
        the rebuild; safe to call on an already-clean pool."""
        self._free = list(range(self.capacity))
        self._active.clear()
        self.check()

    def check(self) -> None:
        """Assert the no-leak invariant; raises :class:`SlotError`."""
        if len(self._free) + len(self._active) != self.capacity or \
                set(self._free) & self._active:
            raise SlotError(
                f"slot leak: {len(self._free)} free + "
                f"{len(self._active)} active != capacity {self.capacity}")


class PagePool:
    """Free-list allocator for the global KV page pool.

    Host-side bookkeeping only — the device arrays live in the engine.
    ``n_pages`` pool rows are handed out lowest-first as per-slot page
    lists; every page is either on the free heap or in exactly one
    slot's list (the page analogue of the slot no-leak invariant, and
    what "no page leaks" asserts in the tests). ``pages_per_slot``
    bounds one slot's list — it is the page-table width, i.e. the
    paged engine's ``max_len`` in pages.
    """

    def __init__(self, n_pages: int, page_size: int, pages_per_slot: int):
        if n_pages < 1 or page_size < 1 or pages_per_slot < 1:
            raise ValueError(
                f"n_pages/page_size/pages_per_slot must be >= 1, got "
                f"{n_pages}/{page_size}/{pages_per_slot}")
        self.n_pages = n_pages
        self.page_size = page_size
        self.pages_per_slot = pages_per_slot
        self._free: List[int] = list(range(n_pages))  # already a heap
        self._owned: Dict[int, List[int]] = {}        # slot -> mapped pages

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use_count(self) -> int:
        return self.n_pages - len(self._free)

    @property
    def occupancy(self) -> float:
        """Mapped fraction in [0, 1] — the kv_page_occupancy feed."""
        return self.in_use_count / self.n_pages

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` cache rows."""
        return -(-tokens // self.page_size)

    def slot_pages(self, slot: int) -> List[int]:
        """The pages currently mapped to ``slot`` (logical order)."""
        return list(self._owned.get(slot, ()))

    def map_slot(self, slot: int, tokens: int) -> Optional[List[int]]:
        """Map a fresh slot with enough pages for ``tokens`` rows.

        Returns the page list (logical order), or None when the pool
        cannot supply them — the caller sheds with ``pages_exhausted``
        rather than partially mapping. A slot may only be mapped once
        between releases.
        """
        if slot in self._owned:
            raise PageError(f"slot {slot} is already mapped")
        need = self.pages_for(max(tokens, 1))
        if need > self.pages_per_slot:
            raise PageError(
                f"slot {slot} needs {need} pages > pages_per_slot "
                f"{self.pages_per_slot}")
        if need > len(self._free):
            return None
        pages = [heapq.heappop(self._free) for _ in range(need)]
        self._owned[slot] = pages
        return pages

    def extend_slot(self, slot: int, tokens: int) -> Optional[List[int]]:
        """Grow ``slot`` to cover ``tokens`` rows (decode on-demand path).

        Returns the NEWLY mapped pages (possibly empty), or None when
        the pool is exhausted — the slot keeps its existing pages and
        the caller decides whether to retire it.
        """
        if slot not in self._owned:
            raise PageError(f"extend of unmapped slot {slot}")
        have = self._owned[slot]
        need = self.pages_for(tokens)
        if need > self.pages_per_slot:
            raise PageError(
                f"slot {slot} needs {need} pages > pages_per_slot "
                f"{self.pages_per_slot}")
        grow = need - len(have)
        if grow <= 0:
            return []
        if grow > len(self._free):
            return None
        fresh = [heapq.heappop(self._free) for _ in range(grow)]
        have.extend(fresh)
        return fresh

    def release_slot(self, slot: int) -> List[int]:
        """Return all of ``slot``'s pages to the free heap; returns the
        released page list (the scrub path zeroes exactly these rows)."""
        if slot not in self._owned:
            raise PageError(
                f"release of unmapped slot {slot} "
                f"(double release or foreign id; "
                f"mapped={sorted(self._owned)})")
        pages = self._owned.pop(slot)
        for p in pages:
            heapq.heappush(self._free, p)
        return pages

    def reset(self) -> None:
        """Return EVERY page to the free heap — engine rebuild/close
        path, mirroring :meth:`SlotPool.reset`."""
        self._free = list(range(self.n_pages))
        self._owned.clear()
        self.check()

    def check(self) -> None:
        """Assert the no-leak invariant; raises :class:`PageError`."""
        owned = [p for pages in self._owned.values() for p in pages]
        if len(self._free) + len(owned) != self.n_pages or \
                set(self._free) & set(owned) or \
                len(set(owned)) != len(owned):
            raise PageError(
                f"page leak: {len(self._free)} free + {len(owned)} owned "
                f"!= n_pages {self.n_pages} (or duplicate mapping)")
