"""Content-addressed prompt-prefix hashing for the prefix cache.

One hash algorithm, three consumers (docs/serving.md#prefix-cache):

- the **engine** hashes an admitted prompt's page-aligned prefix into a
  chain of per-page digests and asks the
  :class:`~apex_tpu.serving.slots.PagePool` intern index for the longest
  interned run;
- the **pool** keys its intern index by chain tuples;
- the fleet **router** hashes the same chain to score prefix affinity —
  a replica that recently served the same prefix probably still holds
  its pages interned, so routing the request there turns a would-be
  miss into a hit.

The chain is *cumulative*: entry ``i`` digests pages ``0..i``, so two
prompts share a leading chain run exactly when they share the leading
token pages — a single mismatched token anywhere in page ``j`` changes
every entry from ``j`` on. Hashes are salted with a model/config
fingerprint (:func:`prefix_salt`), never with sampling state: K/V for a
prompt depend only on the tokens and the weights, so a greedy and a
sampled request over the same prompt MUST share pages. blake2b keeps
collisions out of reach for any realistic fleet lifetime; everything
here is stdlib + host-side (no jax import).
"""

from __future__ import annotations

import hashlib
from typing import Sequence, Tuple

__all__ = ["prefix_hash_chain", "prefix_salt", "adapter_salt",
           "common_chain_len"]


def prefix_salt(config) -> str:
    """A model fingerprint that changes whenever cached K/V could: the
    architecture dims that shape the cache plus the parameter-defining
    seed is out of scope (one engine serves one weight set; a fleet
    serves replicas of the same weights). Sampling knobs are deliberately
    absent — K/V are sampling-invariant."""
    return (f"{getattr(config, 'num_layers', 0)}:"
            f"{getattr(config, 'hidden_size', 0)}:"
            f"{getattr(config, 'num_attention_heads', 0)}:"
            f"{getattr(config, 'kv_heads', 0)}:"
            f"{getattr(config, 'vocab_size', 0)}:"
            f"{getattr(config, 'position_embedding_type', '')}")


def adapter_salt(salt: str, adapter_id=None) -> str:
    """Fold a request's LoRA ``adapter_id`` into the chain salt. K/V are
    sampling-invariant but NOT adapter-invariant — the per-slot QKV delta
    writes adapter-specific K/V into the pages — so two tenants with
    identical prompts under different adapters must never share a chain
    (a naive model-only salt would alias their pages; the regression test
    in tests/test_prefix_cache.py demonstrates the bug). ``None`` (base
    traffic) keeps the plain model salt, so all base requests still
    share."""
    if adapter_id is None:
        return salt
    return f"{salt}|adapter:{adapter_id}"


def prefix_hash_chain(tokens: Sequence[int], page_size: int,
                      salt: str = "") -> Tuple[int, ...]:
    """Rolling per-page digest chain over ``tokens``.

    Returns one 64-bit int per FULL page: entry ``i`` is
    ``H(salt, tokens[0 : (i + 1) * page_size])`` computed incrementally
    (each entry chains the previous digest, so it covers the whole
    prefix, not just its own page). The trailing partial page is never
    hashed — only immutable page-aligned runs are internable.
    """
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    full = len(tokens) // page_size
    if full == 0:
        return ()
    chain = []
    h = hashlib.blake2b(salt.encode("utf-8"), digest_size=8)
    for i in range(full):
        page = tokens[i * page_size:(i + 1) * page_size]
        h.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                          for t in page))
        # fork the running state so the chain stays cumulative without
        # rehashing the prefix per entry
        chain.append(int.from_bytes(h.copy().digest(), "little"))
    return tuple(chain)


def common_chain_len(a: Sequence[int], b: Sequence[int]) -> int:
    """Length of the common leading run of two chains — the number of
    shared full pages (the router's affinity numerator)."""
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n
