"""Self-speculative drafting for the batched serving engine.

Decode is bandwidth-bound: one read of a slot's whole KV stream buys
ONE token. Speculative decoding amortizes that read — draft ``k - 1``
likely continuations cheaply, then verify all of them in ONE windowed
forward (the paged kernel's k-row append+attend window,
:mod:`apex_tpu.ops.decode_attention`), emitting every prefix token the
target model agrees with. This module is the DRAFT side: a model-free
n-gram proposer over the request's own token history (prompt +
generated so far) — "self-speculative", no draft model to load, no
extra weights resident. Repetitive streams (templated output, code,
the repeated-text loadtest scenario) draft well; incompressible streams
fall back to one token per step, never worse than plain decode.

Correctness does not depend on the draft at all: the engine samples the
TARGET model at every window position with the exact per-position key
the sequential path would use (``fold_in(PRNGKey(seed), position)``),
and accepts a drafted token only while the token FED at the next
window row equals what the target just emitted. With a deterministic
draft this acceptance rule reproduces the sequential engine's stream
token-for-token — greedy AND sampled — so "distribution-preserving"
holds exactly, not just in expectation (docs/serving.md#speculative-
decoding has the argument).
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = ["propose_draft"]

#: longest n-gram the proposer matches against the history
_MAX_ORDER = 3

#: how far back the proposer scans for a matching n-gram — bounds the
#: per-slot per-tick cost to O(n * order * tail) regardless of context
#: length (drafting runs on the host between device steps; it must stay
#: far cheaper than the decode step it feeds)
_TAIL = 128


def propose_draft(context: Sequence[int], n: int, *,
                  max_order: int = _MAX_ORDER) -> List[int]:
    """Predict the next ``n`` tokens of ``context`` by n-gram matching.

    For each position: find the MOST RECENT earlier occurrence of the
    longest current suffix (order ``max_order`` down to 1, within the
    last ``_TAIL`` tokens) and propose the token that followed it;
    with no match anywhere, repeat the last token (a cheap bet that is
    free when wrong — rejected drafts cost nothing beyond the window
    row they rode in). Proposals are appended to the working context so
    multi-token drafts extend their own predictions. Deterministic:
    same context -> same draft, which is what makes the engine's
    acceptance rule reproduce the sequential stream exactly.
    """
    if n <= 0:
        return []
    ctx = [int(t) for t in context[-(_TAIL + max_order):]]
    if not ctx:
        return [0] * n
    out: List[int] = []
    for _ in range(n):
        nxt = None
        lo = max(0, len(ctx) - _TAIL)
        for order in range(min(max_order, len(ctx) - 1), 0, -1):
            pat = ctx[-order:]
            # newest match first: recent repetition is the signal
            for i in range(len(ctx) - order - 1, lo - 1, -1):
                if ctx[i:i + order] == pat:
                    nxt = ctx[i + order]
                    break
            if nxt is not None:
                break
        if nxt is None:
            nxt = ctx[-1]
        out.append(nxt)
        ctx.append(nxt)
    return out
