"""apex_tpu.serving — continuous-batching inference over the KV-cache
decode path.

The request-level layer above :mod:`apex_tpu.models.generation`: where
``generate()`` is one lockstep prefill+decode batch, the
:class:`InferenceEngine` admits and retires requests **per decode step**
(Orca-style continuous batching) over a fixed-capacity slot pool and a
single jitted batched decode program that never retraces. FCFS
scheduling with bucketed prefill and backpressure lives in
:mod:`~apex_tpu.serving.scheduler`; request/result types in
:mod:`~apex_tpu.serving.request`. :class:`EngineSupervisor`
(:mod:`~apex_tpu.serving.supervisor`) is the resilience layer: engine
restarts with in-flight request recovery, slot quarantine, a circuit
breaker, and deadline-aware load shedding.
:mod:`~apex_tpu.serving.fleet` scales it out: :class:`ReplicaFleet`
routes traffic across N supervised replicas with least-loaded dispatch
and draining restarts, and :class:`ShardedEngine` runs the decode step
tensor-parallel over the device mesh. See docs/serving.md.
"""

from apex_tpu.lora import UnknownAdapterError
from apex_tpu.serving.engine import EngineConfig, InferenceEngine
from apex_tpu.serving.fleet import (
    FleetConfig,
    FleetUnavailableError,
    ReplicaFleet,
    Router,
    ShardedEngine,
)
from apex_tpu.serving.request import (
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_REASONS,
    FINISH_REJECTED,
    FINISH_TIMEOUT,
    Request,
    RequestResult,
    SamplingParams,
)
from apex_tpu.serving.scheduler import (
    DeadlineExpiredError,
    FCFSScheduler,
    QueueFullError,
    SchedulerConfig,
    bucket_for,
    prefill_buckets,
)
from apex_tpu.serving.slots import PageError, PagePool, SlotError, SlotPool
from apex_tpu.serving.supervisor import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    EngineSupervisor,
    EngineUnavailableError,
    SupervisorConfig,
)

__all__ = [
    "InferenceEngine",
    "EngineConfig",
    "EngineSupervisor",
    "SupervisorConfig",
    "EngineUnavailableError",
    "ReplicaFleet",
    "Router",
    "FleetConfig",
    "FleetUnavailableError",
    "ShardedEngine",
    "BREAKER_CLOSED",
    "BREAKER_OPEN",
    "BREAKER_HALF_OPEN",
    "Request",
    "RequestResult",
    "SamplingParams",
    "FCFSScheduler",
    "SchedulerConfig",
    "QueueFullError",
    "DeadlineExpiredError",
    "UnknownAdapterError",
    "bucket_for",
    "prefill_buckets",
    "SlotPool",
    "SlotError",
    "PagePool",
    "PageError",
    "FINISH_EOS",
    "FINISH_LENGTH",
    "FINISH_CANCELLED",
    "FINISH_TIMEOUT",
    "FINISH_REJECTED",
    "FINISH_ERROR",
    "FINISH_REASONS",
]
