"""apex_tpu.serving — continuous-batching inference over the KV-cache
decode path.

The request-level layer above :mod:`apex_tpu.models.generation`: where
``generate()`` is one lockstep prefill+decode batch, the
:class:`InferenceEngine` admits and retires requests **per decode step**
(Orca-style continuous batching) over a fixed-capacity slot pool and a
single jitted batched decode program that never retraces. FCFS
scheduling with bucketed prefill and backpressure lives in
:mod:`~apex_tpu.serving.scheduler`; request/result types in
:mod:`~apex_tpu.serving.request`. See docs/serving.md.
"""

from apex_tpu.serving.engine import EngineConfig, InferenceEngine
from apex_tpu.serving.request import (
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_REASONS,
    FINISH_REJECTED,
    FINISH_TIMEOUT,
    Request,
    RequestResult,
    SamplingParams,
)
from apex_tpu.serving.scheduler import (
    FCFSScheduler,
    QueueFullError,
    SchedulerConfig,
    bucket_for,
    prefill_buckets,
)
from apex_tpu.serving.slots import SlotError, SlotPool

__all__ = [
    "InferenceEngine",
    "EngineConfig",
    "Request",
    "RequestResult",
    "SamplingParams",
    "FCFSScheduler",
    "SchedulerConfig",
    "QueueFullError",
    "bucket_for",
    "prefill_buckets",
    "SlotPool",
    "SlotError",
    "FINISH_EOS",
    "FINISH_LENGTH",
    "FINISH_CANCELLED",
    "FINISH_TIMEOUT",
    "FINISH_REJECTED",
    "FINISH_REASONS",
]
