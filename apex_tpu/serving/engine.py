"""Continuous-batching inference engine over the cached decode path.

``generate()`` (apex_tpu/models/generation.py) is a single-shot batch
primitive: every caller pays one lockstep prefill+decode, and short
requests wait for the longest. :class:`InferenceEngine` turns those
primitives into a request-level serving loop — Orca-style continuous
(in-flight) batching: requests are admitted and retired **per decode
step**, not per batch, over one fixed-shape jitted decode program.

Architecture (docs/serving.md has the full walkthrough):

- **Slot pool**: a ``[max_slots, max_len]`` batched FLAT KV cache
  (``init_kv_caches(stacked=False, flat=True)``) whose rows are
  independent requests; :class:`~apex_tpu.serving.slots.SlotPool` does
  free-list allocation, eviction on EOS/length budget/cancel/timeout.
- **One decode program**: a single ``jax.jit`` step over ALL slots with
  per-slot position vectors (the vector ``cache_index`` capability of
  the flat cache path — attention masks each row to its own length, rope
  rotates each row at its own offset, and per-request sampling runs
  in-jit from per-slot temperature/top-k/seed arrays). Arrivals and
  retirements mutate host-side arrays only, so the decode step NEVER
  retraces — asserted by a
  :class:`~apex_tpu.analysis.retrace.RetraceWatchdog`, since the decode
  roofline (PAPERS: arXiv 2502.17728) is only reachable when every step
  is the same compiled program.
- **Bucketed prefill**: prompts prefill one-at-a-time, right-padded to
  power-of-two buckets, on the SAME 4D-list/flash path ``generate()``
  uses (then flattened and scattered into the slot row) — compile count
  is bounded by the bucket set and greedy outputs are token-exact
  against per-request ``generate()`` calls.
- **Scheduling**: FCFS bounded queue with a decode-starvation cap
  (:mod:`apex_tpu.serving.scheduler`); queue-full rejection, deadlines,
  and cancellation follow ``resilience``'s structured ``log_event``
  conventions, and every terminal request emits one ``kind="request"``
  JSONL record plus latency/occupancy histograms into an attached
  :class:`~apex_tpu.observability.MetricsRegistry` (rendered by
  ``python -m apex_tpu.monitor``).
- **Decode-output integrity**: the jitted decode step also returns a
  per-slot ``isfinite(logits)`` flag (one cheap in-jit reduction —
  resilience's off-critical-path watchdog idea applied per slot). A row
  with non-finite logits or an out-of-vocab token is **quarantined**:
  its request retires with ``finish_reason="error"``, its KV row is
  scrubbed and the slot released — co-tenant rows keep serving,
  unperturbed (rows are independent through the vmap'd flat-cache
  attention, so one poisoned row cannot contaminate the others).
  Tick-level failures (decode/prefill exceptions, hung ticks) and
  admission control under overload are the
  :class:`~apex_tpu.serving.supervisor.EngineSupervisor`'s job —
  docs/serving.md#robustness has the full fault model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.analysis.retrace import RetraceWatchdog
from apex_tpu.models.generation import (
    _cached_forward,
    cast_decode_params,
    decode_step,
    flatten_decode_caches,
    init_kv_caches,
    init_paged_kv_caches,
    preslice_layer_params,
)
from apex_tpu.observability import MetricsRegistry
from apex_tpu.observability.trace import (
    SPAN_PREEMPT,
    SPAN_QUARANTINE,
    SPAN_SPEC_VERIFY,
    emit_request_spans,
    emit_span,
)
from apex_tpu.ops.decode_attention import (
    paged_quant_fill,
    paged_quant_scatter,
)
from apex_tpu.serving.request import (
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_REJECTED,
    FINISH_TIMEOUT,
    PRIORITY_RANK,
    Request,
    RequestResult,
)
from apex_tpu.lora import UnknownAdapterError
from apex_tpu.serving import clock
from apex_tpu.serving.prefix import (
    adapter_salt,
    prefix_hash_chain,
    prefix_salt,
)
from apex_tpu.serving.scheduler import (
    DeadlineExpiredError,
    FCFSScheduler,
    QueueFullError,
    SchedulerConfig,
    bucket_for,
    prefill_buckets,
)
from apex_tpu.serving.slots import PagePool, SlotPool
from apex_tpu.serving.speculation import propose_draft
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["EngineConfig", "InferenceEngine"]

_LOG = get_logger(__name__)

#: declared up front so final counter snapshots carry every key even for
#: outcomes that never fired — the monitor report reconciles these
#: against the per-request records key-for-key
_COUNTERS = ("requests_submitted", "requests_eos", "requests_length",
             "requests_cancelled", "requests_timeout", "requests_rejected",
             "requests_error", "prefills", "decode_steps",
             "tokens_generated", "slots_quarantined",
             "requests_shed_pages",
             # multi-LoRA (docs/serving.md#multi-lora): submits whose
             # adapter_id the AdapterStore doesn't know, fast-failed at
             # submit() — reconciled against request_shed events with
             # reason="unknown_adapter"
             "requests_shed_adapter",
             # prefix cache (docs/serving.md#prefix-cache): hits + misses
             # == paged prefills when prefix_cache is on, so hit_rate is
             # derivable; pages_shared counts prefill pages NOT recomputed
             "prefix_hits", "prefix_misses", "prefix_pages_shared",
             "prefix_evictions",
             # speculative decoding (docs/serving.md#speculative-decoding):
             # proposed counts drafted positions beyond the forced first
             # feed; accepted counts the ones the target agreed with, so
             # accepted/proposed is the fleet-wide acceptance rate
             "draft_tokens_proposed", "draft_tokens_accepted",
             # chunked prefill (docs/serving.md#chunked-prefill): chunk
             # programs run under prefill_token_budget — reconciled
             # against the per-request prefill_chunks record field and
             # the prefill_tokens_per_tick histogram's observation sum
             "prefill_chunks",
             # priority preemption (docs/serving.md#priority-preemption-
             # and-quotas): running slots parked for a higher class (or a
             # brownout rung) — reconciled against request_preempted
             # events key-for-key; parks are not terminal, so this never
             # enters the finish-reason sum
             "requests_preempted")


@dataclass
class EngineConfig:
    """Engine sizing and robustness knobs.

    ``retrace_budget`` guards the one-compile decode invariant: after the
    warmup compile, that many decode retraces are tolerated before
    :class:`~apex_tpu.analysis.retrace.RetraceBudgetExceeded` aborts the
    engine (0 = any retrace is a bug; None = log only). ``donate_caches``
    donates the KV-cache buffers into the jitted steps so decode updates
    in place on TPU; ``None`` auto-disables it on the CPU backend (which
    cannot donate and would warn every compile).

    KV layout (docs/serving.md#paged-kv): ``kv_layout="paged"`` (the
    default) backs slots with a shared page pool — ``n_pages`` pages of
    ``page_size`` tokens per layer — so HBM is committed to actual
    context length and ``max_slots`` can exceed what dense rows would
    fit; decode runs the fused append+attend kernel. ``n_pages=None``
    sizes the pool to fully back every slot at ``max_len`` (same
    capacity as flat — no admission behavior change); size it below that
    to overcommit, and the engine sheds ``pages_exhausted`` when a
    request's worst case can never fit. ``kv_layout="flat"`` keeps the
    dense ``[max_slots, max_len]`` rows for bisection.

    Prefix cache (docs/serving.md#prefix-cache, paged layout only):
    ``prefix_cache=True`` interns each prompt's page-aligned prefix into
    the pool's content-addressed index, so a later prompt sharing that
    prefix maps the interned pages refcounted and prefills ONLY its
    suffix — token-exact, and admission reserves just the suffix +
    worst-case-new pages, so the hit rate directly raises effective
    capacity. ``prefix_lru_capacity`` bounds the index (entries; evicted
    LRU-first under page pressure). ``prefix_cache=False`` restores the
    PR 9 one-owner pool bit-for-bit.

    Decode-roofline knobs (paged layout only):
    ``kv_dtype="int8"`` (docs/serving.md#kv-quantization) stores the
    page pools int8 with per-(page, kv-head) scale sidecars — half the
    decode HBM stream, dequantized inline in the fused kernel;
    ``"bf16"`` (default) is the exact path and the bisection baseline.
    ``speculation=k`` (docs/serving.md#speculative-decoding, ``k >= 2``)
    turns each decode tick into a k-row self-speculative verify window:
    n-gram drafts ride the batched step and every accepted draft is one
    more token per KV-stream read. 0 disables (the PR 9 single-token
    step). Both knobs keep greedy streams token-exact against the
    defaults; speculation keeps SAMPLED streams exact too (the
    acceptance rule reproduces the sequential per-position sampling).

    Chunked prefill (docs/serving.md#chunked-prefill):
    ``prefill_token_budget=n`` bounds the prefill TOKENS one tick may
    run — a long prompt prefills as a sequence of bucketed chunk
    programs carried across ticks, interleaved with the batched decode
    step, so co-tenant TPOT never stalls for more than one chunk's
    compute. Internal chunk boundaries are page-aligned under the paged
    layout (so int8 scales and prefix interning stay bitwise what the
    monolithic fill produces) and outputs are token-exact, greedy and
    sampled. ``None`` (default) keeps the one-shot prefill path
    unchanged.
    """

    max_slots: int = 8
    max_len: int = 512
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    retrace_budget: Optional[int] = 0
    donate_caches: Optional[bool] = None
    kv_layout: str = "paged"
    page_size: int = 64
    n_pages: Optional[int] = None
    prefix_cache: bool = True
    prefix_lru_capacity: int = 32
    kv_dtype: str = "bf16"
    speculation: int = 0
    prefill_token_budget: Optional[int] = None

    def __post_init__(self):
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {self.max_slots}")
        if self.max_len < 2:
            raise ValueError(
                f"max_len must be >= 2 (one prompt + one generated token), "
                f"got {self.max_len}")
        if self.kv_layout not in ("flat", "paged"):
            raise ValueError(
                f"kv_layout must be 'flat' or 'paged', got "
                f"{self.kv_layout!r}")
        if self.page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {self.page_size}")
        if self.n_pages is not None and self.n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {self.n_pages}")
        if self.prefix_lru_capacity < 0:
            raise ValueError(
                f"prefix_lru_capacity must be >= 0, got "
                f"{self.prefix_lru_capacity}")
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be 'bf16' or 'int8', got "
                f"{self.kv_dtype!r}")
        if self.kv_dtype == "int8" and self.kv_layout != "paged":
            raise ValueError(
                "kv_dtype='int8' needs kv_layout='paged' — the scales "
                "are per-page sidecars")
        if self.speculation < 0 or self.speculation == 1:
            raise ValueError(
                f"speculation is 0 (off) or a verify window >= 2, got "
                f"{self.speculation}")
        if self.speculation and self.kv_layout != "paged":
            raise ValueError(
                "speculation needs kv_layout='paged' — the verify "
                "window rides the fused paged kernel")
        if self.prefill_token_budget is not None:
            if self.prefill_token_budget < 1:
                raise ValueError(
                    f"prefill_token_budget must be >= 1 (or None to "
                    f"disable chunking), got {self.prefill_token_budget}")
            if (self.kv_layout == "paged"
                    and self.prefill_token_budget < self.page_size):
                raise ValueError(
                    f"prefill_token_budget ({self.prefill_token_budget}) "
                    f"must be >= page_size ({self.page_size}) under the "
                    f"paged layout — internal chunk boundaries are "
                    f"page-aligned, so a smaller budget could never make "
                    f"progress on a multi-page prompt")

    @property
    def pages_per_slot(self) -> int:
        """Page-table width: pages covering one slot at ``max_len``."""
        return -(-self.max_len // self.page_size)


class _Active:
    """Host-side state of a request holding a slot."""

    __slots__ = ("request", "slot", "tokens", "last_token", "position",
                 "submit_ts", "prefill_start", "prefill_end",
                 "first_token_ts", "last_token_ts", "cancelled",
                 "reserved_pages", "adapter_ix",
                 "spec_proposed", "spec_accepted",
                 "prefill_pos", "prefill_chunks", "chunk_marks",
                 "page_row", "chain", "shared_used", "skip_first",
                 "finite_ok")

    def __init__(self, request: Request, slot: int, submit_ts: float):
        self.request = request
        self.slot = slot
        self.tokens: List[int] = []
        self.last_token = 0
        self.position = 0       # cache rows written for this slot
        self.reserved_pages = 0  # worst-case pages minus shared-prefix hit
        self.adapter_ix = 0     # bank row (null row when no adapter)
        self.submit_ts = submit_ts
        self.prefill_start = 0.0
        self.prefill_end = 0.0
        self.first_token_ts = 0.0   # when token #1 reached the host (TTFT)
        self.last_token_ts = 0.0    # latest token arrival (TPOT numerator)
        self.cancelled = False
        self.spec_proposed = 0   # draft positions offered over the lifetime
        self.spec_accepted = 0   # draft positions the target agreed with
        # chunked-prefill progress, carried across ticks as plain host
        # data (page ids + an absolute token offset — never jit-trace
        # state, the seam a dedicated prefill replica would ship)
        self.prefill_pos = 0     # prompt tokens whose K/V are written
        self.prefill_chunks = 0  # chunk programs run so far
        self.chunk_marks: List[float] = []  # interior chunk-end stamps
        self.page_row = None     # the slot's REAL page row while chunking
        self.chain = ()          # prefix hash chain (interned at the end)
        self.shared_used = 0     # prefix-hit pages mapped at admission
        self.skip_first = False  # fully page-aligned hit (COW seam)
        self.finite_ok = True    # AND of every chunk's isfinite flag


def _sample_tokens(logits, temps, topks, seeds, steps):
    """Per-row sampling over ``logits`` [n, V]: greedy where
    ``temps == 0``, else softmax at the row's temperature truncated to
    its top-k (``topks == V`` disables truncation), keyed by
    ``fold_in(PRNGKey(seed), step)`` so a request's stream depends only
    on its own (seed, positions) — never on batch co-tenants."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    v = logits.shape[-1]
    safe_t = jnp.where(temps > 0.0, temps, 1.0).astype(logits.dtype)
    scaled = logits / safe_t[:, None]
    # kth-largest per row via one sort (top_k varies per row, so the
    # static-k lax.top_k form generate() uses cannot batch here);
    # mask logits < kth — identical support to generate()'s truncation
    order = jnp.sort(scaled, axis=-1)                      # ascending
    kth = jnp.take_along_axis(order, (v - topks)[:, None], axis=-1)
    masked = jnp.where(scaled < kth, -jnp.inf, scaled)

    def draw(seed, step, row):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.categorical(key, row)

    sampled = jax.vmap(draw)(seeds, steps, masked).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def _select_adapters(lora, adapter_ix):
    """Gather per-slot LoRA factors from the stacked adapter bank: leaves
    ``[L, max_adapters + 1, ...]`` at bank rows ``adapter_ix`` (``[b]``)
    -> ``[L, b, ...]``, the layout the transformer's per-layer loop
    slices. ``None`` passes through — an engine without an AdapterStore
    compiles the identical no-delta program."""
    if lora is None:
        return None
    return jax.tree.map(lambda x: x[:, adapter_ix], lora)


class InferenceEngine:
    """Continuous-batching serving engine; see the module docstring.

    Drive it either with :meth:`serve` (submit a request list, tick to
    completion, collect results) or manually: :meth:`submit` +
    :meth:`tick` in a loop, harvesting :attr:`completed`.
    """

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 *, metrics: Optional[MetricsRegistry] = None,
                 faults=None, replica_id: Optional[int] = None,
                 adapters=None):
        self.model = model
        self.config = config or EngineConfig()
        #: optional AdapterStore (apex_tpu.lora) — multi-tenant serving:
        #: per-request adapter_id selects a bank row, the step programs
        #: gather per-slot factors in-jit (docs/serving.md#multi-lora).
        #: The bank is re-read every call, so host-side load/unload
        #: between ticks applies on the next step without a retrace.
        self.adapters = adapters
        #: fleet replica label stamped on every RequestResult / JSONL
        #: record this engine emits (None = single-engine deployment)
        self.replica_id = replica_id
        #: optional ServingFaultInjector (apex_tpu.testing_faults) — hook
        #: points are host-side on purpose: injected faults must never
        #: retrace the compiled decode step
        self._faults = faults
        self._closed = False
        c = model.config
        if (c.position_embedding_type == "learned"
                and self.config.max_len > c.max_position_embeddings):
            raise ValueError(
                f"max_len ({self.config.max_len}) exceeds the model's "
                f"max_position_embeddings ({c.max_position_embeddings})")
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.metrics.declare_counters(*_COUNTERS)
        if self.adapters is not None:
            # per-adapter submit counters, declared up front like the
            # fleet's replica{i}_dispatches so final snapshots carry every
            # key; the monitor reconciles them against adapter_request
            # events key-for-key
            self.metrics.declare_counters(
                *(f"adapter{ix}_requests"
                  for ix in range(self.adapters.max_adapters)))
        self.scheduler = FCFSScheduler(self.config.scheduler)
        self.slots = SlotPool(self.config.max_slots)
        self.buckets = prefill_buckets(self.config.max_len)
        self.completed: Dict[int, RequestResult] = {}
        #: request ids in admission (prefill) order — the FCFS audit trail
        self.admission_log: List[int] = []
        self._active: Dict[int, _Active] = {}      # slot -> state
        #: slots mid-chunked-prefill, in admission order (insertion-
        #: ordered dict) — excluded from _active so the batched decode
        #: step never sees them; the slot's real page row lives on the
        #: rec until the final chunk lands (see _begin_chunked_prefill)
        self._prefilling: Dict[int, _Active] = {}
        #: preempted (parked) requests as (request, generated_tokens,
        #: submit_ts) — host-side token cursors with slot and pages
        #: released; the supervisor drains them via take_parked() into
        #: restart-style continuations that resume TOKEN-EXACT (sampling
        #: keys on absolute position, docs/serving.md#priority-
        #: preemption-and-quotas)
        self._parked: List = []
        #: set True by a caller that drains take_parked() every tick
        #: (the EngineSupervisor). Without a consumer the engine never
        #: preempts on its own — a parked request would have nowhere to
        #: resume. park_class() is exempt: an explicit call owns the
        #: drain responsibility.
        self.resume_consumer = False
        self._chunk_tokens_tick = 0   # prefill tokens run this tick
        self._vocab = c.vocab_size

        # serving precision: generate()'s own one-time pre-cast +
        # per-layer param pre-slice, materialized ONCE at engine build
        if c.compute_dtype != jnp.float32:
            params = cast_decode_params(params, c.compute_dtype)
        self._params = preslice_layer_params(params, c.num_layers)
        if self.config.kv_layout == "paged":
            pps = self.config.pages_per_slot
            n_pages = (self.config.n_pages if self.config.n_pages is not None
                       else self.config.max_slots * pps)
            self.pages: Optional[PagePool] = PagePool(
                n_pages, self.config.page_size, pps,
                lru_capacity=(self.config.prefix_lru_capacity
                              if self.config.prefix_cache else 0))
            #: salt for the prompt-prefix hash chains — keyed by the
            #: model fingerprint (K/V are sampling-invariant), with each
            #: request's adapter_id folded in at hash time: adapter
            #: deltas write adapter-specific K/V, so tenants must never
            #: alias pages across adapters (see prefix.adapter_salt)
            self._prefix_salt = prefix_salt(c)
            self._evictions_seen = 0
            self._quantized = self.config.kv_dtype == "int8"
            self._caches = init_paged_kv_caches(
                model, n_pages, self.config.page_size,
                quantized=self._quantized)
            # HBM bytes one decode step streams per mapped page (K + V
            # across all layers, plus the f32 scale sidecars when
            # quantized) — the kv_bytes_per_step gauge's unit, computed
            # from the GLOBAL head count so the number means the same
            # thing sharded and unsharded
            f_dim = c.kv_heads * c.head_dim
            item = 1 if self._quantized else jnp.dtype(
                c.compute_dtype).itemsize
            self._page_read_bytes = 2 * c.num_layers * (
                self.config.page_size * f_dim * item
                + (c.kv_heads * 4 if self._quantized else 0))
            # host page table; n_pages is the unmapped sentinel (reads
            # clamp+mask, scatters drop — see ops/decode_attention.py)
            self._page_table_h = np.full(
                (self.config.max_slots, pps), n_pages, np.int32)
            #: worst-case pages promised to admitted requests — admission
            #: only lets a request in when its full total_len reservation
            #: fits, so decode-time extends can NEVER exhaust the pool
            #: (no mid-flight eviction policy needed; see _admit)
            self._reserved_pages = 0
        else:
            self.pages = None
            self._quantized = False
            self._caches = init_kv_caches(
                model, self.config.max_slots, self.config.max_len,
                stacked=False, flat=True)

        n = self.config.max_slots
        self._tokens_h = np.zeros(n, np.int32)
        self._positions_h = np.zeros(n, np.int32)
        self._temps_h = np.zeros(n, np.float32)
        self._topks_h = np.full(n, self._vocab, np.int32)
        self._seeds_h = np.zeros(n, np.int32)
        #: per-slot adapter bank row; idle/base slots point at the
        #: all-zeros null row, so their delta is an exact zero
        self._null_adapter = (0 if self.adapters is None
                              else self.adapters.null_index)
        self._adapter_ix_h = np.full(n, self._null_adapter, np.int32)
        #: speculation host state: per-slot verify window (row 0 is the
        #: token being fed — the sequential step's _tokens_h — rows 1..
        #: the n-gram draft, padded by repeating the last real feed) and
        #: its valid length
        self._spec = self.config.speculation
        if self._spec:
            self._window_h = np.zeros((n, self._spec), np.int32)
            self._wlen_h = np.ones(n, np.int32)

        donate = self.config.donate_caches
        if donate is None:
            donate = jax.default_backend() != "cpu"

        decode_fn, prefill_fn, suffix_fn, chunk_fn, scrub_fn, reset_fn = \
            self._build_step_fns(donate)
        self._decode_fn = RetraceWatchdog(
            decode_fn,
            budget=self.config.retrace_budget, expected_compiles=1,
            name="serving_decode", metrics=self.metrics)
        # one jit whose compile count is bounded by the bucket set (each
        # distinct padded prompt shape is one entry); budget=None — bucket
        # compiles are expected, the TEST asserts compiles <= buckets
        self._prefill_fn = RetraceWatchdog(
            prefill_fn, budget=None, expected_compiles=len(self.buckets),
            name="serving_prefill", metrics=self.metrics)
        # suffix prefill (prefix-cache hits) buckets exactly like full
        # prefill, so its compile count has the same bound; under
        # chunked prefill it doubles as the paged CHUNK program (the
        # chunk offset is a traced scalar, so chunking adds no shapes)
        self._suffix_fn = None if suffix_fn is None else RetraceWatchdog(
            suffix_fn, budget=None, expected_compiles=len(self.buckets),
            name="serving_suffix_prefill", metrics=self.metrics)
        # flat-layout chunk program (paged chunks ride _suffix_fn) —
        # bucketed like prefill, so the same compile bound holds
        self._chunk_fn = None if chunk_fn is None else RetraceWatchdog(
            chunk_fn, budget=None, expected_compiles=len(self.buckets),
            name="serving_chunk_prefill", metrics=self.metrics)
        self._scrub_fn = scrub_fn
        self._reset_scales_fn = reset_fn

    # -- step programs (overridable: ShardedEngine wraps these bodies in
    # -- shard_map over the device mesh) ----------------------------------

    def _decode_body(self, params, caches, tokens, positions, temps,
                     topks, seeds, adapter_ix, lora):
        logits, caches = decode_step(self.model, params, caches, tokens,
                                     positions,
                                     lora=_select_adapters(lora, adapter_ix))
        nxt = _sample_tokens(logits, temps, topks, seeds, positions + 1)
        # per-slot integrity flag: one cheap in-jit reduction so the
        # host can quarantine a poisoned row without fetching logits
        finite = jnp.all(jnp.isfinite(logits), axis=-1)
        return nxt, finite, caches

    def _scrub_body(self, caches, slot):
        # zero one slot's KV rows across every layer — quarantine
        # hygiene, so a poisoned row's NaNs can never reach a future
        # occupant even through a masked-weight * NaN-value product
        return [(k.at[slot].set(0.0), v.at[slot].set(0.0))
                for k, v in caches]

    def _prefill_body(self, params, caches, prompt, slot, prompt_len,
                      temp, topk, seed, adapter_ix, lora):
        # the EXACT prefill generate() runs (4D per-layer list -> the
        # cache_index==0 causal-flash fast path), at the bucket-padded
        # length; pad rows are causally invisible to real rows and
        # their K/V land beyond the row's live length, so they are
        # never read back
        model = self.model
        small = init_kv_caches(model, 1, prompt.shape[1], stacked=False)
        logits, small = _cached_forward(model, params, small, prompt, 0,
                                        last_index=prompt_len - 1,
                                        lora=_select_adapters(lora,
                                                              adapter_ix))
        flat = flatten_decode_caches(small, model.config.num_layers)
        new = [
            (jax.lax.dynamic_update_slice(bk, fk, (slot, 0, 0)),
             jax.lax.dynamic_update_slice(bv, fv, (slot, 0, 0)))
            for (bk, bv), (fk, fv) in zip(caches, flat)]
        first = _sample_tokens(logits[0], temp[None], topk[None],
                               seed[None], prompt_len[None])
        return first[0], new

    def _paged_decode_body(self, params, caches, page_table, tokens,
                           positions, temps, topks, seeds, adapter_ix,
                           lora):
        # same decode step over the PAGED pool: one fused append+attend
        # per layer (apex_tpu.ops.decode_attention) instead of the flat
        # row scatter + masked read; with the pool donated the appends
        # are in-place row writes, so per step the KV traffic is one
        # read of the mapped stream plus one row
        logits, caches = decode_step(self.model, params, caches, tokens,
                                     positions, paged_state=page_table,
                                     lora=_select_adapters(lora, adapter_ix))
        nxt = _sample_tokens(logits, temps, topks, seeds, positions + 1)
        finite = jnp.all(jnp.isfinite(logits), axis=-1)
        return nxt, finite, caches

    def _spec_decode_body(self, params, caches, page_table, windows,
                          positions, temps, topks, seeds, adapter_ix,
                          lora):
        # speculative decode: each slot feeds a k-token verify window
        # (row 0 = the sequential step's token, rows 1.. the draft) in
        # ONE forward — one read of the mapped KV stream buys up to k
        # target samples. Sampling is per-position with the SAME
        # fold_in(seed, position) keys the sequential step would use,
        # and every _sample_tokens op is row-independent, so row j of
        # the [n, k] output is bitwise what a sequential step at
        # position + j would emit given the same fed tokens — the host
        # acceptance loop then consumes exactly the prefix the
        # sequential engine would have produced.
        n, k = windows.shape
        logits, caches = _cached_forward(
            self.model, params, caches, windows, positions,
            paged_state=page_table,
            lora=_select_adapters(lora, adapter_ix))      # [k, n, V]
        lf = logits.transpose(1, 0, 2).reshape(n * k, -1)
        steps = (positions[:, None] + 1 + jnp.arange(k)[None, :]).reshape(-1)
        nxt = _sample_tokens(lf, jnp.repeat(temps, k), jnp.repeat(topks, k),
                             jnp.repeat(seeds, k), steps)
        finite = jnp.all(jnp.isfinite(logits), axis=-1).T  # [n, k]
        return nxt.reshape(n, k), finite, caches

    def _paged_scrub_body(self, caches, page_row):
        # zero exactly the quarantined slot's mapped pages across every
        # layer (``page_row`` is its fixed-width table row; sentinel
        # entries drop) — same NaN-hygiene contract as the flat scrub,
        # but foreign slots' pages are never touched. Quantized pools
        # zero the scale sidecar too, so a recycled page starts from a
        # clean rescale baseline (slots.PagePool.check asserts this).
        if self._quantized:
            return [((k.at[page_row].set(0, mode="drop"),
                      ks.at[page_row].set(0.0, mode="drop")),
                     (v.at[page_row].set(0, mode="drop"),
                      vs.at[page_row].set(0.0, mode="drop")))
                    for (k, ks), (v, vs) in caches]
        return [(k.at[page_row].set(0.0, mode="drop"),
                 v.at[page_row].set(0.0, mode="drop"))
                for k, v in caches]

    def _reset_scales_body(self, caches, page_row):
        # zero ONLY the scale sidecar for freshly allocated pages (the
        # int8 payload is overwritten before it can be read, but a
        # stale scale from the previous tenant would poison the
        # scatter-max rescale floor). No-op program for bf16 pools.
        return [((k, ks.at[page_row].set(0.0, mode="drop")),
                 (v, vs.at[page_row].set(0.0, mode="drop")))
                for (k, ks), (v, vs) in caches]

    def _paged_prefill_body(self, params, caches, page_row, prompt,
                            prompt_len, temp, topk, seed, adapter_ix,
                            lora):
        # identical prefill compute to the flat body (same 4D small-cache
        # forward, so greedy outputs stay token-exact); only the landing
        # differs — the flattened rows scatter into this slot's freshly
        # mapped pages. Chunks past the mapped count (bucket padding)
        # carry the sentinel and drop; garbage rows inside the last
        # mapped page are causally masked by the row's position forever.
        model = self.model
        small = init_kv_caches(model, 1, prompt.shape[1], stacked=False)
        logits, small = _cached_forward(model, params, small, prompt, 0,
                                        last_index=prompt_len - 1,
                                        lora=_select_adapters(lora,
                                                              adapter_ix))
        flat = flatten_decode_caches(small, model.config.num_layers)
        ps = self.config.page_size
        bucket = prompt.shape[1]
        n_chunks = -(-bucket // ps)
        pad = n_chunks * ps - bucket
        dest = page_row[:n_chunks]
        new = []
        for cache, (fk, fv) in zip(caches, flat):
            fk1 = jnp.pad(fk[0], ((0, pad), (0, 0)))
            fv1 = jnp.pad(fv[0], ((0, pad), (0, 0)))
            if self._quantized:
                # whole-page overwrite: the chunk IS the page content,
                # so each page's scale comes straight from its own amax
                # (pad rows are zeros and cannot inflate it)
                (bk, bks), (bv, bvs) = cache
                new.append(
                    (paged_quant_fill(bk, bks,
                                      fk1.reshape(n_chunks, ps, -1), dest),
                     paged_quant_fill(bv, bvs,
                                      fv1.reshape(n_chunks, ps, -1), dest)))
                continue
            bk, bv = cache
            new.append(
                (bk.at[dest].set(fk1.reshape(n_chunks, ps, -1)
                                 .astype(bk.dtype), mode="drop"),
                 bv.at[dest].set(fv1.reshape(n_chunks, ps, -1)
                                 .astype(bv.dtype), mode="drop")))
        first = _sample_tokens(logits[0], temp[None], topk[None],
                               seed[None], prompt_len[None])
        # finite flag gates publishing these pages to the prefix-intern
        # index: a poisoned prefill must never become a shared prefix
        return first[0], jnp.all(jnp.isfinite(logits)), new

    def _suffix_prefill_body(self, params, caches, page_row, suffix,
                             start, suffix_len, prompt_len, temp, topk,
                             seed, skip_first, adapter_ix, lora):
        """Prefill ONLY the suffix of a prefix-cache hit.

        The slot's page table already maps the shared prefix pages for
        tokens ``[0, start)``; this body gathers those rows into a
        small 4D cache, runs the suffix forward at ``cache_index=start``
        (offset-causal mask + rope at the absolute offset — the same
        mid-cache path the flat engine's vectorized decode uses), and
        scatters the suffix K/V into the slot's PRIVATE pages row by
        row. Shared pages are never written: when ``skip_first`` is set
        (a fully page-aligned hit, whose one-token "suffix" is a
        recompute of the prompt's LAST token purely to produce first-
        token logits), the recomputed row's scatter is masked so the
        boundary page keeps its original bitwise K/V — the copy-on-write
        seam with the copy elided, since the row is already resident.
        """
        model = self.model
        ps = self.config.page_size
        pps = self.config.pages_per_slot
        n_pages = self.pages.n_pages
        bucket = suffix.shape[1]
        s0 = pps * ps
        # static length s0 + bucket keeps the suffix update in-bounds for
        # any traced start (no dynamic_update_slice clamping)
        small = init_kv_caches(model, 1, s0 + bucket, stacked=False)
        valid_page = page_row < n_pages
        clamped = jnp.clip(page_row, 0, n_pages - 1)
        filled = []
        for cache, (sk, sv) in zip(caches, small):
            h, d = sk.shape[1], sk.shape[3]

            def place(pool, sm, scales=None):
                g = pool[clamped]                       # [pps, ps, h*d]
                if scales is not None:
                    # dequantize the shared-prefix rows with their pages'
                    # sidecar scales before they enter the fp forward
                    sc = jnp.repeat(scales[clamped], d, axis=-1)
                    g = g.astype(jnp.float32) * sc[:, None, :]
                # sentinel rows must read as EXACT zeros (a clamped
                # gather could otherwise import a co-tenant's transient
                # NaN into causally masked positions: 0-weight * NaN
                # is still NaN)
                g = jnp.where(valid_page[:, None, None], g, 0.0)
                g = g.reshape(s0, h, d).transpose(1, 0, 2)[None]
                return sm.at[:, :, :s0, :].set(g.astype(sm.dtype))

            if self._quantized:
                (bk, bks), (bv, bvs) = cache
                filled.append((place(bk, sk, bks), place(bv, sv, bvs)))
            else:
                bk, bv = cache
                filled.append((place(bk, sk), place(bv, sv)))
        logits, filled = _cached_forward(model, params, filled, suffix,
                                         start, last_index=suffix_len - 1,
                                         lora=_select_adapters(lora,
                                                               adapter_ix))
        # scatter the suffix K/V into the slot's pages, one row per
        # suffix position (rows can straddle page boundaries, so the
        # whole-page chunk scatter of the miss path does not apply)
        idx = jnp.arange(bucket)
        pos = start + idx
        dest_page = page_row[jnp.clip(pos // ps, 0, pps - 1)]
        dest_off = pos % ps
        valid = (idx < suffix_len) & ~(skip_first & (idx == 0))
        dest_page = jnp.where(valid, dest_page, n_pages)  # drop pads
        new = []
        for cache, (fk, fv) in zip(caches, filled):
            h, d = fk.shape[1], fk.shape[3]

            def rows(f):
                r = jax.lax.dynamic_slice_in_dim(f, start, bucket, axis=2)
                return r[0].transpose(1, 0, 2).reshape(bucket, h * d)

            if self._quantized:
                # suffix rows straddle pages, so they go through the
                # rescale-on-append scatter (sentinel dests drop; the
                # shared boundary page's scale only grows monotonically,
                # which every co-tenant's dequant view tolerates)
                (bk, bks), (bv, bvs) = cache
                new.append(
                    (paged_quant_scatter(bk, bks, rows(fk), dest_page,
                                         dest_off),
                     paged_quant_scatter(bv, bvs, rows(fv), dest_page,
                                         dest_off)))
            else:
                bk, bv = cache
                new.append(
                    (bk.at[dest_page, dest_off].set(
                        rows(fk).astype(bk.dtype), mode="drop"),
                     bv.at[dest_page, dest_off].set(
                         rows(fv).astype(bv.dtype), mode="drop")))
        first = _sample_tokens(logits[0], temp[None], topk[None],
                               seed[None], prompt_len[None])
        return first[0], jnp.all(jnp.isfinite(logits)), new

    def _flat_chunk_body(self, params, caches, slot, chunk, start,
                         chunk_len, prompt_len, temp, topk, seed,
                         adapter_ix, lora):
        """Prefill ONE bucketed chunk of a prompt into a flat slot row.

        The flat analogue of the suffix body: gather the slot's dense
        row (tokens ``[0, start)`` are live, later rows garbage the
        offset-causal mask never attends) into a small 4D cache, run
        the chunk forward at ``cache_index=start`` — rope and sampling
        keyed to the ABSOLUTE position, so the final chunk's sample is
        bitwise the monolithic prefill's first token — and scatter the
        chunk's K/V rows back (pad rows drop)."""
        model = self.model
        max_len = self.config.max_len
        bucket = chunk.shape[1]
        # static length max_len + bucket keeps the chunk update
        # in-bounds for any traced start
        small = init_kv_caches(model, 1, max_len + bucket, stacked=False)
        filled = []
        for (bk, bv), (sk, sv) in zip(caches, small):
            h, d = sk.shape[1], sk.shape[3]
            f = bk.shape[-1]

            def place(big, sm):
                g = jax.lax.dynamic_slice(big, (slot, 0, 0),
                                          (1, max_len, f))[0]
                g = g.reshape(max_len, h, d).transpose(1, 0, 2)[None]
                return sm.at[:, :, :max_len, :].set(g.astype(sm.dtype))

            filled.append((place(bk, sk), place(bv, sv)))
        logits, filled = _cached_forward(model, params, filled, chunk,
                                         start, last_index=chunk_len - 1,
                                         lora=_select_adapters(lora,
                                                               adapter_ix))
        idx = jnp.arange(bucket)
        # pad rows (idx >= chunk_len) target row max_len — out of bounds
        # for the dense row, so the drop-mode scatter discards them
        dest = jnp.where(idx < chunk_len, start + idx, max_len)
        new = []
        for (bk, bv), (fk, fv) in zip(caches, filled):
            h, d = fk.shape[1], fk.shape[3]

            def rows(f4):
                r = jax.lax.dynamic_slice_in_dim(f4, start, bucket, axis=2)
                return r[0].transpose(1, 0, 2).reshape(bucket, h * d)

            new.append(
                (bk.at[slot, dest].set(rows(fk).astype(bk.dtype),
                                       mode="drop"),
                 bv.at[slot, dest].set(rows(fv).astype(bv.dtype),
                                       mode="drop")))
        first = _sample_tokens(logits[0], temp[None], topk[None],
                               seed[None], prompt_len[None])
        return first[0], jnp.all(jnp.isfinite(logits)), new

    def _build_step_fns(self, donate: bool):
        """Compile the device programs:
        ``(decode, prefill, suffix_prefill, chunk_prefill, scrub,
        reset_scales)`` — ``suffix_prefill`` is None under the flat
        layout (no pages, no prefix cache), ``chunk_prefill`` is None
        under the paged layout (paged chunks reuse the suffix program —
        the chunk offset is a traced scalar), and ``reset_scales`` is
        None unless the pool is quantized. The base engine jits the
        bodies directly (single-chip);
        :class:`~apex_tpu.serving.fleet.ShardedEngine` overrides this to
        wrap each body in ``shard_map`` over the tensor axis first. The
        bodies are picked by ``kv_layout`` — both layouts keep the
        caches as argument 1 so donation and the watchdogs are shared.
        With ``speculation`` on, the decode program is the windowed
        verify body (same arity: the [n] token vector becomes the
        [n, k] window matrix)."""
        donate_args = (1,) if donate else ()
        if self.pages is not None:
            decode_body = (self._spec_decode_body if self._spec
                           else self._paged_decode_body)
            return (jax.jit(decode_body, donate_argnums=donate_args),
                    jax.jit(self._paged_prefill_body,
                            donate_argnums=donate_args),
                    jax.jit(self._suffix_prefill_body,
                            donate_argnums=donate_args),
                    None,
                    jax.jit(self._paged_scrub_body,
                            donate_argnums=(0,) if donate else ()),
                    jax.jit(self._reset_scales_body,
                            donate_argnums=(0,) if donate else ())
                    if self._quantized else None)
        return (jax.jit(self._decode_body, donate_argnums=donate_args),
                jax.jit(self._prefill_body, donate_argnums=donate_args),
                None,
                jax.jit(self._flat_chunk_body, donate_argnums=donate_args),
                jax.jit(self._scrub_body,
                        donate_argnums=(0,) if donate else ()),
                None)

    @property
    def _bank(self):
        """Current adapter bank (None without an AdapterStore) — read
        fresh per step call so hot load/unload lands next tick."""
        return None if self.adapters is None else self.adapters.bank

    def _adapter_index(self, adapter_id, *, strict: bool) -> int:
        """Resolve an ``adapter_id`` to its bank row. ``strict`` raises
        :class:`UnknownAdapterError` (submit validation); non-strict
        falls back to the null row — the prefill/decode path for a
        request whose adapter was unloaded after admission, which
        degrades to base-model output instead of crashing the batch."""
        if self.adapters is None:
            if adapter_id is not None and strict:
                raise UnknownAdapterError(
                    f"adapter {adapter_id!r}: engine has no AdapterStore")
            return self._null_adapter
        try:
            return self.adapters.index_of(adapter_id)
        except UnknownAdapterError:
            if strict:
                raise
            return self._null_adapter

    # -- introspection ----------------------------------------------------

    @property
    def decode_retraces(self) -> int:
        """Decode-step recompiles beyond the warmup — must stay 0."""
        return self._decode_fn.retraces

    @property
    def prefill_compiles(self) -> int:
        """Distinct prefill shapes compiled — bounded by ``len(buckets)``."""
        return self._prefill_fn.compiles

    @property
    def chunk_compiles(self) -> int:
        """Distinct chunk-program shapes compiled under chunked prefill
        — bounded by ``len(buckets)`` (on the paged layout the chunk
        program IS the suffix program, so this counts its shapes)."""
        if self.pages is not None:
            return 0 if self._suffix_fn is None else \
                self._suffix_fn.compiles
        return 0 if self._chunk_fn is None else self._chunk_fn.compiles

    @property
    def decode_compiles(self) -> int:
        """Decode-step compilations (warmup included) — the supervisor
        exempts compile ticks from its hung-tick wall-clock budget."""
        return self._decode_fn.compiles

    @property
    def active_count(self) -> int:
        return self.slots.active_count

    @property
    def queued_count(self) -> int:
        return self.scheduler.depth

    @property
    def queued_tokens(self) -> int:
        """Prompt tokens waiting in the queue — the token-aware load
        signal the supervisor's shed/cost estimates fold in (a backlog
        of long prompts is more work than its depth suggests)."""
        return self.scheduler.queued_tokens

    @property
    def parked_count(self) -> int:
        """Preempted requests awaiting resume — non-terminal work the
        supervisor's idle checks must count."""
        return len(self._parked)

    def take_parked(self) -> List:
        """Drain the parked (preempted) requests as ``(request,
        generated_tokens, submit_ts)`` tuples — the supervisor turns each
        into a restart-style continuation (original prompt + generated
        prefix, remaining budget, same request/trace ids and deadline
        clock) and resubmits it when capacity allows."""
        parked, self._parked = self._parked, []
        return parked

    def queued_tokens_by_class(self) -> Dict[str, int]:
        """Queued prompt tokens per priority class (scheduler
        passthrough) — the supervisor's per-class shed pricing input."""
        return self.scheduler.queued_tokens_by_class()

    def queued_depth_by_class(self) -> Dict[str, int]:
        """Queue depth per priority class (scheduler passthrough)."""
        return self.scheduler.depth_by_class()

    def set_admission_floor(self, priority: Optional[str]) -> None:
        """Scheduler passthrough: pause dispatch of classes below
        ``priority`` (the brownout ladder's admission rungs)."""
        self.scheduler.set_admission_floor(priority)

    def inflight(self) -> List:
        """Snapshot of active (admitted, non-terminal) requests as
        ``(request, generated_tokens, submit_ts)`` tuples in slot order —
        what the supervisor re-prefills after an engine restart.
        Mid-chunked-prefill requests are included with NO tokens: a
        restart re-prefills them from the prompt through the same admit
        path (their chunk progress died with the engine's pages).
        Parked (preempted) requests are included WITH their tokens: a
        restart resumes them exactly like the supervisor's ordinary
        take_parked() drain would have."""
        recs = [(rec.request, list(rec.tokens), rec.submit_ts)
                for _, rec in sorted(self._active.items())]
        recs += [(rec.request, [], rec.submit_ts)
                 for rec in self._prefilling.values()]
        recs += [(request, list(tokens), submit_ts)
                 for request, tokens, submit_ts in self._parked]
        return recs

    # -- request lifecycle ------------------------------------------------

    def submit(self, request: Request, *, resubmission: bool = False) -> int:
        """Enqueue; returns the request id. Raises
        :class:`~apex_tpu.serving.scheduler.QueueFullError` when the
        bounded queue is full, and
        :class:`~apex_tpu.serving.scheduler.DeadlineExpiredError` when
        the request's deadline already elapsed (stale ``arrival_ts``) —
        both rejections are also recorded: counter, ``request_rejected``
        event (with a ``reason``), and a terminal ``kind="request"``
        record with ``finish_reason="rejected"``.

        ``resubmission=True`` is the supervisor's restart-continuation
        path: the request was already counted at its ORIGINAL submit, so
        ``requests_submitted`` is not incremented again (one arrival ==
        one count == one terminal record)."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if request.request_id in self.completed:
            raise ValueError(
                f"request id {request.request_id} already completed")
        if request.total_len > self.config.max_len:
            raise ValueError(
                f"prompt ({request.prompt_len}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the engine's max_len "
                f"({self.config.max_len})")
        now = clock.now()
        if not resubmission:
            self.metrics.inc("requests_submitted")
        aid = request.sampling.adapter_id
        try:
            # restart continuations (resubmission) were validated at their
            # ORIGINAL submit; if the adapter vanished since, they degrade
            # to the null row (base output) instead of failing the restart
            ix = self._adapter_index(aid, strict=not resubmission)
        except UnknownAdapterError:
            # fast-fail BEFORE the queue: an unknown/unloaded adapter_id
            # can never produce the tenant's output, so it sheds with its
            # own counter + request_shed reason (the supervisor-shed
            # convention) and a terminal rejected record
            self.metrics.inc("requests_shed_adapter")
            log_event(_LOG, "request_shed",
                      request_id=request.request_id,
                      reason="unknown_adapter", adapter_id=aid)
            self.metrics.event("request_shed",
                               request_id=request.request_id,
                               reason="unknown_adapter", adapter_id=aid)
            self._finish(request, [], FINISH_REJECTED, submit_ts=now,
                         now=now, detail="unknown_adapter")
            raise
        if aid is not None and not resubmission:
            # per-adapter arrival ledger (monitor reconciles the counter
            # against these events key-for-key)
            self.metrics.inc(f"adapter{ix}_requests")
            self.metrics.event("adapter_request",
                               request_id=request.request_id,
                               adapter_id=aid, adapter_ix=ix)
        try:
            self.scheduler.submit(request, now)
        except QueueFullError:
            self._finish(request, [], FINISH_REJECTED, submit_ts=now,
                         now=now, detail="queue_full")
            raise
        except DeadlineExpiredError:
            self._finish(request, [], FINISH_REJECTED, submit_ts=now,
                         now=now, detail="deadline_expired")
            raise
        return request.request_id

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request; returns True when found.
        A queued request terminates immediately; an in-flight one is
        evicted at the start of the next tick, keeping its partial
        tokens in the result."""
        queued = self.scheduler.cancel(request_id)
        if queued is not None:
            request, submit_ts = queued
            self._finish(request, [], FINISH_CANCELLED, submit_ts=submit_ts,
                         now=clock.now())
            return True
        for i, (request, tokens, submit_ts) in enumerate(self._parked):
            if request.request_id == request_id:
                # a parked request holds no slot or pages — it terminates
                # immediately, keeping the tokens generated before the park
                del self._parked[i]
                self._finish(request, tokens, FINISH_CANCELLED,
                             submit_ts=submit_ts, now=clock.now())
                return True
        for rec in (*self._active.values(), *self._prefilling.values()):
            if rec.request.request_id == request_id:
                rec.cancelled = True
                return True
        return False

    def tick(self) -> List[RequestResult]:
        """One scheduler iteration: expire deadlines, evict cancellations,
        admit+prefill FCFS (decode-starvation capped), then one batched
        decode step over all active slots. Returns the requests that
        reached a terminal state during this tick."""
        if self._closed:
            raise RuntimeError("engine is closed")
        finished: List[RequestResult] = []
        now = clock.now()
        self._expire(now, finished)
        self._evict_cancelled(finished)
        self._maybe_preempt(now)
        self._chunk_tokens_tick = 0
        if self.config.prefill_token_budget is None:
            self._admit(finished)
        else:
            self._chunked_admit(finished)
        if self._chunk_tokens_tick:
            # one observation per tick with prefill activity — the
            # histogram's sum is the total chunked prefill tokens, its
            # max must never exceed prefill_token_budget
            self.metrics.observe("prefill_tokens_per_tick",
                                 self._chunk_tokens_tick)
        self._decode_tick(finished)
        self.metrics.observe("slot_occupancy", self.slots.occupancy)
        if self.pages is not None:
            self.metrics.set_gauge("kv_pages_in_use",
                                   self.pages.in_use_count)
            self.metrics.set_gauge("kv_pages_free", self.pages.free_count)
            self.metrics.observe("kv_page_occupancy", self.pages.occupancy)
            delta = self.pages.evictions - self._evictions_seen
            if delta:
                self.metrics.inc("prefix_evictions", delta)
                self._evictions_seen = self.pages.evictions
        return finished

    def serve(self, requests: Sequence[Request], *,
              on_tick: Optional[Callable[["InferenceEngine", int], None]]
              = None, max_ticks: Optional[int] = None
              ) -> List[RequestResult]:
        """Serve ``requests`` to completion: submits lazily as the bounded
        queue drains (backpressure without rejections), ticks until idle,
        and returns results in input order. ``on_tick(engine, i)`` runs
        after each tick — the hook fault-injection and tests use to
        cancel/submit mid-flight."""
        pending = list(requests)
        ids = [r.request_id for r in pending]
        ticks = 0
        while pending or self.scheduler.depth or self._active \
                or self._prefilling:
            while pending and \
                    self.scheduler.depth < self.config.scheduler.max_queue:
                self.submit(pending.pop(0))
            before = (len(pending), self.scheduler.depth,
                      len(self._active), len(self._prefilling))
            self.tick()
            ticks += 1
            if on_tick is not None:
                on_tick(self, ticks)
            if max_ticks is not None and ticks >= max_ticks:
                break
            if (before == (len(pending), self.scheduler.depth,
                           len(self._active), len(self._prefilling))
                    and not self._active and not self._prefilling
                    and self.scheduler.depth):
                raise RuntimeError(
                    "serve() made no progress: queued requests exist but "
                    "none are admissible (admission_hook deferring "
                    "forever?)")
        return [self.completed[i] for i in ids if i in self.completed]

    def close(self) -> None:
        """Release every slot and flush the metrics registry (final
        counter snapshot — what the monitor report reconciles against
        the request records). Idempotent: a second ``close()`` is a
        no-op, so exception paths can close unconditionally."""
        if self._closed:
            return
        self._closed = True
        self._active.clear()
        self._prefilling.clear()
        self._parked.clear()
        self.slots.reset()
        if self.pages is not None:
            # the page free list resets WITH the slot pool — a rebuild
            # that reused this registry must start from a full pool
            self.pages.reset()
            self._reserved_pages = 0
            self._page_table_h[:] = self.pages.n_pages
        self.metrics.flush()

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False

    # -- tick phases ------------------------------------------------------

    def _expire(self, now: float, finished: List[RequestResult]) -> None:
        for request, submit_ts in self.scheduler.expire(now):
            finished.append(self._finish(
                request, [], FINISH_TIMEOUT, submit_ts=submit_ts, now=now))
        if self._parked:
            # a park never stops the deadline clock — parked requests
            # expire exactly like queued ones, keeping their partial
            # tokens in the result
            kept = []
            for request, tokens, submit_ts in self._parked:
                d = request.deadline_s
                if d is not None and now - submit_ts > d:
                    finished.append(self._finish(
                        request, tokens, FINISH_TIMEOUT,
                        submit_ts=submit_ts, now=now))
                else:
                    kept.append((request, tokens, submit_ts))
            self._parked = kept
        for slot in sorted(self._active):
            rec = self._active[slot]
            d = rec.request.deadline_s
            if d is not None and now - rec.submit_ts > d:
                finished.append(self._retire(rec, FINISH_TIMEOUT, now))
        for slot in list(self._prefilling):
            rec = self._prefilling[slot]
            d = rec.request.deadline_s
            if d is not None and now - rec.submit_ts > d:
                finished.append(self._abandon_prefill(
                    rec, FINISH_TIMEOUT, now))

    def _evict_cancelled(self, finished: List[RequestResult]) -> None:
        for slot in sorted(self._active):
            rec = self._active[slot]
            if rec.cancelled:
                finished.append(self._retire(
                    rec, FINISH_CANCELLED, clock.now()))
        for slot in list(self._prefilling):
            rec = self._prefilling[slot]
            if rec.cancelled:
                finished.append(self._abandon_prefill(
                    rec, FINISH_CANCELLED, clock.now()))

    def _plan_prefix(self, request: Request):
        """Match ``request``'s page-aligned prompt prefix against the
        intern index: ``(chain, shared_pages, skip_first)``. The chain is
        always computed (the miss path interns it); ``shared_pages`` is
        the longest currently-interned leading run (empty on a miss or
        with ``prefix_cache=False``). ``skip_first`` marks the fully
        page-aligned hit, whose suffix prefill is a single recompute of
        the prompt's last token with its K/V scatter masked (the COW
        seam — the boundary row already lives, bitwise, in the last
        shared page). A match is trimmed when its suffix bucket would
        overrun ``max_len`` (only possible for non-power-of-two page
        sizes) so the static bucket set keeps holding."""
        ps = self.config.page_size
        # fold the request's adapter identity into the salt: adapter
        # deltas make K/V adapter-specific, so same-prompt tenants under
        # different adapters must never share a chain (base traffic,
        # adapter_id=None, keeps the plain model salt and still shares)
        salt = adapter_salt(self._prefix_salt, request.sampling.adapter_id)
        chain = prefix_hash_chain(request.prompt, ps, salt)
        if not self.config.prefix_cache or not chain:
            return chain, [], False
        pages, matched = self.pages.match_prefix(chain)
        max_len = self.config.max_len
        while matched:
            start = (request.prompt_len - 1
                     if matched * ps == request.prompt_len
                     else matched * ps)
            if start + bucket_for(request.prompt_len - start,
                                  max_len) <= max_len:
                break
            matched -= 1
        if matched == 0:
            return chain, [], False
        return chain, pages[:matched], \
            matched * ps == request.prompt_len

    def _make_page_predicate(self):
        """Pages-aware admission predicate (None under the flat layout):
        a request enters only when its WORST-CASE page need (total_len,
        minus the shared-prefix pages a cache hit maps refcounted) fits
        alongside every other admitted request's outstanding reservation
        — so decode-time on-demand extends can never exhaust the pool.
        ``reclaimable`` pages (held only by the intern index) count as
        capacity since allocation evicts entries under pressure, but
        this request's own shared pages are subtracted from that pot
        first: mapping PINS them, so they stop being evictable. A head
        that can never fit (need > n_pages) is shed as
        ``pages_exhausted``; one that merely must wait defers (FCFS
        head-blocking). The ``planned`` tallies accumulate across the
        pops of ONE call — chunked admission builds a fresh predicate
        per single-head pop because it maps pages between pops."""
        if self.pages is None:
            return None
        planned = 0          # private pages promised this tick
        planned_shared = 0   # reclaimable pages pinned this tick

        def predicate(request):
            nonlocal planned, planned_shared
            need = self.pages.pages_for(request.total_len)
            if need > self.pages.n_pages:
                return "shed"
            _, shared_pages, _ = self._plan_prefix(request)
            shared = len(shared_pages)
            pool = self.pages
            avail = (pool.free_count
                     + max(0, pool.reclaimable_count
                           - planned_shared - shared)
                     - (self._reserved_pages - pool.owned_count)
                     - planned)
            if need - shared <= avail:
                planned += need - shared
                planned_shared += shared
                return "admit"
            return "defer"

        return predicate

    def _maybe_preempt(self, now: float) -> None:
        """Park ONE lowest-class running slot when a strictly-higher-class
        queued head is blocked on slots or pages (the tentpole's
        preemption rule). Runs before admission so the freed slot/pages
        can admit the head in the same tick; one park per tick converges
        without thrashing (the parked continuation re-queues in its own
        class lane, where strict priority keeps it behind the traffic
        that displaced it). The head's TRUE class decides — a batch head
        aged up to standard rank may dispatch ahead of standard, but it
        never preempts anyone."""
        if not self.resume_consumer or not self._active:
            return
        head = self.scheduler.head(now=now)
        if head is None:
            return
        head_rank = PRIORITY_RANK[head[0].sampling.priority]
        blocked = self.slots.free_count == 0
        if not blocked and self.pages is not None:
            pred = self._make_page_predicate()
            blocked = pred(head[0]) == "defer"
        if not blocked:
            return
        victim, victim_key = None, None
        for slot in sorted(self._active):
            rec = self._active[slot]
            rank = PRIORITY_RANK[rec.request.sampling.priority]
            if rank <= head_rank:
                continue
            # lowest class first; among peers the one with the least
            # generated work (cheapest re-prefill), ids breaking ties
            key = (rank, -len(rec.tokens), rec.request.request_id)
            if victim_key is None or key > victim_key:
                victim, victim_key = rec, key
        if victim is not None:
            self._park(victim, now, cause="priority")

    def _park(self, rec: _Active, now: float, *, cause: str) -> None:
        """Preempt one ACTIVE slot: release the slot and its pages
        (shared prefix pages outlive it, refcounted — exactly the
        `_retire` release sequence) but emit NO terminal record and NO
        phase spans — a park is not an outcome. The host-side cursor
        (request, generated tokens, submit_ts) moves to the parked list
        for the supervisor's continuation path; a zero-width ``preempt``
        mark span annotates the timeline under the request's original
        trace_id."""
        slot = rec.slot
        del self._active[slot]
        self.slots.release(slot)
        if self.pages is not None:
            self.pages.release_slot(slot)
            self._reserved_pages -= rec.reserved_pages
            self._page_table_h[slot, :] = self.pages.n_pages
        self._clear_slot(slot)
        self._parked.append((rec.request, list(rec.tokens), rec.submit_ts))
        self.metrics.inc("requests_preempted")
        log_event(_LOG, "request_preempted",
                  request_id=rec.request.request_id, cause=cause,
                  priority=rec.request.sampling.priority,
                  tokens_parked=len(rec.tokens))
        self.metrics.event("request_preempted",
                           request_id=rec.request.request_id, cause=cause,
                           priority=rec.request.sampling.priority,
                           tokens_parked=len(rec.tokens))
        emit_span(self.metrics, SPAN_PREEMPT,
                  trace_id=rec.request.trace_id,
                  request_id=rec.request.request_id,
                  start_s=now, end_s=now, wall=clock.wall(),
                  replica_id=self.replica_id, detail=cause,
                  tokens_parked=len(rec.tokens),
                  priority=rec.request.sampling.priority)

    def park_class(self, priority: str, *, cause: str = "brownout") -> int:
        """Park EVERY active slot of ``priority`` (the brownout ladder's
        "preempt batch slots" rung); returns the number parked. The
        caller owns the take_parked() drain. Mid-chunked-prefill slots
        are not parked — their progress lives in half-filled pages, not
        a host cursor; the admission floor already stops new ones."""
        now = clock.now()
        victims = [self._active[s] for s in sorted(self._active)
                   if self._active[s].request.sampling.priority == priority]
        for rec in victims:
            self._park(rec, now, cause=cause)
        return len(victims)

    def _admit(self, finished: List[RequestResult]) -> None:
        shed: List = []
        now = clock.now()
        batch = self.scheduler.pop_admissible(
            self.slots.free_count, decoding=bool(self._active),
            predicate=self._make_page_predicate(), shed=shed, now=now)
        for request, submit_ts in shed:
            finished.append(self._shed_pages(request, submit_ts, now))
        for request, submit_ts in batch:
            slot = self.slots.allocate()
            assert slot is not None  # pop_admissible respects free_count
            self._prefill_into(request, slot, submit_ts, finished)

    def _chunked_admit(self, finished: List[RequestResult]) -> None:
        """Token-budgeted mixed tick (docs/serving.md#chunked-prefill):
        continue in-flight chunked prefills in admission order, then
        admit new heads while budget remains, each running its first
        chunk(s) in the same tick. ``max_prefills_per_tick`` still caps
        NEW admissions per tick while requests are decoding; the token
        budget bounds the total prefill compute of the whole tick, so a
        long prompt can never stall co-tenant decode for more than one
        chunk's worth."""
        budget = self.config.prefill_token_budget
        spent = 0
        for slot in list(self._prefilling):
            if spent >= budget:
                break
            ran = self._run_chunk(self._prefilling[slot], budget - spent,
                                  finished)
            if ran == 0:
                break           # remaining budget below one page
            spent += ran
        admitted = 0
        limit = self.slots.free_count
        if self._active:
            limit = min(limit,
                        self.config.scheduler.max_prefills_per_tick)
        while spent < budget and admitted < limit and self.scheduler.depth:
            shed: List = []
            now = clock.now()
            batch = self.scheduler.pop_admissible(
                1, decoding=False, predicate=self._make_page_predicate(),
                shed=shed, now=now)
            for request, submit_ts in shed:
                finished.append(self._shed_pages(request, submit_ts, now))
            if not batch:
                break           # head deferred (pages) or queue drained
            request, submit_ts = batch[0]
            slot = self.slots.allocate()
            assert slot is not None
            rec = self._begin_chunked_prefill(request, slot, submit_ts)
            if rec is None:
                break           # intern-eviction race: requeued at front
            admitted += 1
            ran = self._run_chunk(rec, budget - spent, finished)
            if ran == 0:
                break           # admitted; first chunk waits for budget
            spent += ran

    def _shed_pages(self, request: Request, submit_ts: float,
                    now: float) -> RequestResult:
        """Reject a request whose worst-case page reservation exceeds the
        whole pool — its own shed counter + ``request_shed`` reason, the
        supervisor-shed convention, instead of a prefill-time failure."""
        need = self.pages.pages_for(request.total_len)
        self.metrics.inc("requests_shed_pages")
        log_event(_LOG, "request_shed", request_id=request.request_id,
                  reason="pages_exhausted", pages_needed=need,
                  n_pages=self.pages.n_pages)
        self.metrics.event("request_shed", request_id=request.request_id,
                           reason="pages_exhausted", pages_needed=need,
                           n_pages=self.pages.n_pages)
        return self._finish(request, [], FINISH_REJECTED,
                            submit_ts=submit_ts, now=now,
                            detail="pages_exhausted")

    def _prefill_into(self, request: Request, slot: int, submit_ts: float,
                      finished: List[RequestResult]) -> None:
        rec = _Active(request, slot, submit_ts)
        rec.prefill_start = clock.now()
        sp = request.sampling
        # resolve the adapter row NOW (non-strict: an id unloaded while
        # queued degrades to the null row — base output — rather than
        # crashing admission; submit() already validated it existed)
        rec.adapter_ix = self._adapter_index(sp.adapter_id, strict=False)
        aix = jnp.asarray([rec.adapter_ix], jnp.int32)
        bank = self._bank
        topk = jnp.int32(sp.top_k if sp.top_k is not None else self._vocab)
        chain, shared_pages, skip_first = (), [], False
        shared_used = 0
        if self.pages is not None:
            # re-match the prefix NOW (the predicate's match may have
            # been reshaped by a later head's intern eviction), commit
            # the worst-case reservation minus the shared pages, then
            # physically map only the prompt's pages (decode extends on
            # demand)
            chain, shared_pages, skip_first = self._plan_prefix(request)
            shared_used = len(shared_pages)
            need = self.pages.pages_for(request.total_len) - shared_used
            mapped = self.pages.map_slot(slot, request.prompt_len,
                                         shared=shared_pages or None)
            if mapped is None:
                self.slots.release(slot)
                if self.config.prefix_cache:
                    # an intern eviction between the admission predicate
                    # and this map changed what's reclaimable — FCFS
                    # honest, the request retries from the FRONT of the
                    # queue on a later tick (co-tenant retirements will
                    # unpin pages)
                    self.scheduler.requeue_front(request, submit_ts)
                    return
                raise RuntimeError(
                    f"page pool exhausted at prefill despite admission "
                    f"reservation (slot {slot}, "
                    f"free={self.pages.free_count}) — reservation "
                    f"accounting is broken")
            rec.reserved_pages = need
            self._reserved_pages += need
            row = self._page_table_h[slot]
            row[:] = self.pages.n_pages
            row[:len(mapped)] = mapped
            # freshly mapped PRIVATE pages may be recycled (e.g. from a
            # pressure-evicted intern run) with stale scales; zero them
            # so the rescale-on-append floor starts clean. Shared pages
            # keep their scales — that's their dequant key.
            self._reset_fresh_scales(mapped[shared_used:])
        try:
            if self._faults is not None:
                self._faults.before_prefill()
            finite = True
            if self.pages is not None and shared_used:
                # prefix-cache hit: prefill ONLY the suffix (bucketed
                # like a full prefill). start is the first token NOT
                # covered by shared pages — or, fully covered, the
                # prompt's last token recomputed for its logits only
                ps = self.config.page_size
                start = (request.prompt_len - 1 if skip_first
                         else shared_used * ps)
                suffix_len = request.prompt_len - start
                bucket = bucket_for(suffix_len, self.config.max_len)
                suffix = np.zeros((1, bucket), np.int32)
                suffix[0, :suffix_len] = request.prompt[start:]
                first, finite, self._caches = self._suffix_fn(
                    self._params, self._caches,
                    jnp.asarray(self._page_table_h[slot]),
                    jnp.asarray(suffix), jnp.int32(start),
                    jnp.int32(suffix_len), jnp.int32(request.prompt_len),
                    jnp.float32(sp.temperature), topk,
                    jnp.int32(sp.seed), jnp.bool_(skip_first), aix, bank)
            elif self.pages is not None:
                bucket = bucket_for(request.prompt_len, self.config.max_len)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :request.prompt_len] = request.prompt
                first, finite, self._caches = self._prefill_fn(
                    self._params, self._caches,
                    jnp.asarray(self._page_table_h[slot]),
                    jnp.asarray(padded), jnp.int32(request.prompt_len),
                    jnp.float32(sp.temperature), topk,
                    jnp.int32(sp.seed), aix, bank)
            else:
                bucket = bucket_for(request.prompt_len, self.config.max_len)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :request.prompt_len] = request.prompt
                first, self._caches = self._prefill_fn(
                    self._params, self._caches, jnp.asarray(padded),
                    jnp.int32(slot), jnp.int32(request.prompt_len),
                    jnp.float32(sp.temperature), topk,
                    jnp.int32(sp.seed), aix, bank)
            first = int(np.asarray(first))
        except Exception:
            # keep the pool invariants even as the failure propagates:
            # the slot never held committed state (nothing scattered, or
            # the scatter's result was discarded with the raised call)
            self.slots.release(slot)
            if self.pages is not None:
                self.pages.release_slot(slot)
                self._reserved_pages -= rec.reserved_pages
                self._page_table_h[slot, :] = self.pages.n_pages
            raise
        if self.pages is not None and self.config.prefix_cache:
            if shared_used:
                self.metrics.inc("prefix_hits")
                self.metrics.inc("prefix_pages_shared", shared_used)
            else:
                self.metrics.inc("prefix_misses")
            # publish the prompt's full pages (shared run + freshly
            # prefilled privates) so later prompts hit; gated on finite
            # logits — a poisoned prefill must never be shared. On an
            # exact repeat this is a no-op; a longer prompt upgrades the
            # subsumed shorter entry.
            if chain and bool(np.asarray(finite)):
                self.pages.intern_prefix(
                    chain,
                    [int(p) for p in self._page_table_h[slot][:len(chain)]])
        rec.prefill_end = clock.now()
        rec.tokens.append(first)
        rec.last_token = first
        # token #1 lands with the prefill result — TTFT is submit -> here
        rec.first_token_ts = rec.last_token_ts = rec.prefill_end
        rec.position = request.prompt_len
        self._active[slot] = rec
        self.admission_log.append(request.request_id)
        self.metrics.inc("prefills")
        self.metrics.inc("tokens_generated")
        self._sync_slot(rec)
        done = self._finish_reason(rec, first)
        if done is not None:
            finished.append(self._retire(rec, done, clock.now()))

    def _begin_chunked_prefill(self, request: Request, slot: int,
                               submit_ts: float) -> Optional[_Active]:
        """Admission half of a chunked prefill: allocate the slot,
        commit the page reservation and map the prompt's pages (shared
        prefix refcounted, exactly like the monolithic path) — but run
        NO compute yet. The slot's real page row lives on the rec while
        chunks land; the GLOBAL table row stays all-sentinel, so the
        batched decode step treats the slot exactly like an idle one
        (gathers mask, appends drop) and mid-prefill slots are excluded
        from decode with no program or shape change. Returns None when
        an intern-eviction race requeued the request (FCFS front)."""
        rec = _Active(request, slot, submit_ts)
        rec.prefill_start = clock.now()
        rec.adapter_ix = self._adapter_index(request.sampling.adapter_id,
                                             strict=False)
        if self.pages is not None:
            chain, shared_pages, skip_first = self._plan_prefix(request)
            shared_used = len(shared_pages)
            need = self.pages.pages_for(request.total_len) - shared_used
            mapped = self.pages.map_slot(slot, request.prompt_len,
                                         shared=shared_pages or None)
            if mapped is None:
                self.slots.release(slot)
                if self.config.prefix_cache:
                    self.scheduler.requeue_front(request, submit_ts)
                    return None
                raise RuntimeError(
                    f"page pool exhausted at prefill despite admission "
                    f"reservation (slot {slot}, "
                    f"free={self.pages.free_count}) — reservation "
                    f"accounting is broken")
            rec.reserved_pages = need
            self._reserved_pages += need
            row = np.full(self.config.pages_per_slot, self.pages.n_pages,
                          np.int32)
            row[:len(mapped)] = mapped
            rec.page_row = row
            rec.chain = chain
            rec.shared_used = shared_used
            rec.skip_first = skip_first
            # shared prefix rows are already resident: chunking starts
            # at the first uncovered token (page-aligned), or — fully
            # covered — at the last-token recompute (the COW seam)
            rec.prefill_pos = (request.prompt_len - 1 if skip_first
                               else shared_used * self.config.page_size)
            self._reset_fresh_scales(mapped[shared_used:])
        else:
            # park the position at the last row: the flat decode step
            # appends unconditionally at _positions_h[slot], and row
            # max_len-1 is never live (a request's final sampled token
            # is never fed back), so co-tenant decode garbage cannot
            # clobber already-prefilled chunk rows
            self._positions_h[slot] = self.config.max_len - 1
        self._prefilling[slot] = rec
        self.admission_log.append(request.request_id)
        return rec

    def _run_chunk(self, rec: _Active, budget_left: int,
                   finished: List[RequestResult]) -> int:
        """Run ONE maximal prefill chunk for ``rec`` within
        ``budget_left`` tokens; returns the tokens consumed (0 = no
        progress possible this tick). Paged chunks reuse the suffix
        program (the slot's pages ARE the carried state); flat chunks
        run the dedicated chunk body. The final chunk's sample — keyed
        at step ``prompt_len`` from the prompt's last-token logits —
        is the request's first token, bitwise what the monolithic
        prefill emits; intermediate chunks' samples are discarded."""
        request = rec.request
        remaining = request.prompt_len - rec.prefill_pos
        chunk_len = min(remaining, budget_left)
        if chunk_len < remaining and self.pages is not None:
            # internal chunk boundaries stay page-aligned: every fresh
            # page is then written whole in ONE scatter onto a zeroed
            # scale, so int8 page contents (and the interned prefix
            # pages) are bitwise what the monolithic fill produces
            ps = self.config.page_size
            chunk_len = ((rec.prefill_pos + chunk_len) // ps) * ps \
                - rec.prefill_pos
        if chunk_len <= 0:
            return 0
        sp = request.sampling
        start = rec.prefill_pos
        bucket = bucket_for(chunk_len, self.config.max_len)
        chunk = np.zeros((1, bucket), np.int32)
        chunk[0, :chunk_len] = request.prompt[start:start + chunk_len]
        aix = jnp.asarray([rec.adapter_ix], jnp.int32)
        topk = jnp.int32(sp.top_k if sp.top_k is not None else self._vocab)
        try:
            if self._faults is not None:
                self._faults.before_prefill()
            if self.pages is not None:
                first, finite, self._caches = self._suffix_fn(
                    self._params, self._caches, jnp.asarray(rec.page_row),
                    jnp.asarray(chunk), jnp.int32(start),
                    jnp.int32(chunk_len), jnp.int32(request.prompt_len),
                    jnp.float32(sp.temperature), topk, jnp.int32(sp.seed),
                    jnp.bool_(rec.skip_first and rec.prefill_chunks == 0),
                    aix, self._bank)
            else:
                first, finite, self._caches = self._chunk_fn(
                    self._params, self._caches, jnp.int32(rec.slot),
                    jnp.asarray(chunk), jnp.int32(start),
                    jnp.int32(chunk_len), jnp.int32(request.prompt_len),
                    jnp.float32(sp.temperature), topk, jnp.int32(sp.seed),
                    aix, self._bank)
            rec.finite_ok = rec.finite_ok and bool(np.asarray(finite))
            first = int(np.asarray(first))
        except Exception:
            # same failure contract as the monolithic prefill: the slot
            # never held committed state — release everything as the
            # exception propagates; the supervisor's restart re-prefills
            # the request from its prompt through the same admit path
            del self._prefilling[rec.slot]
            self.slots.release(rec.slot)
            if self.pages is not None:
                self.pages.release_slot(rec.slot)
                self._reserved_pages -= rec.reserved_pages
                self._page_table_h[rec.slot, :] = self.pages.n_pages
            self._clear_slot(rec.slot)
            raise
        rec.prefill_pos += chunk_len
        rec.prefill_chunks += 1
        self.metrics.inc("prefill_chunks")
        self._chunk_tokens_tick += chunk_len
        if rec.prefill_pos < request.prompt_len:
            rec.chunk_marks.append(clock.now())
        else:
            self._complete_chunked_prefill(rec, first, finished)
        return chunk_len

    def _complete_chunked_prefill(self, rec: _Active, first: int,
                                  finished: List[RequestResult]) -> None:
        """Final chunk landed: publish the page row to the global table
        (the batched decode step sees — and appends to — the slot from
        the next step on), intern the prefix, and promote the rec to
        the active set with its first token."""
        request = rec.request
        slot = rec.slot
        del self._prefilling[slot]
        if self.pages is not None:
            self._page_table_h[slot] = rec.page_row
            if self.config.prefix_cache:
                # hit/miss accounting lands at COMPLETION so hits +
                # misses stays == prefills even when a mid-prefill
                # request times out or is cancelled
                if rec.shared_used:
                    self.metrics.inc("prefix_hits")
                    self.metrics.inc("prefix_pages_shared",
                                     rec.shared_used)
                else:
                    self.metrics.inc("prefix_misses")
                if rec.chain and rec.finite_ok:
                    self.pages.intern_prefix(
                        rec.chain,
                        [int(p) for p in rec.page_row[:len(rec.chain)]])
        rec.prefill_end = clock.now()
        rec.tokens.append(first)
        rec.last_token = first
        # token #1 is emitted by THIS tick's final chunk — TTFT stamps
        # here, not at prefill admission
        rec.first_token_ts = rec.last_token_ts = rec.prefill_end
        rec.position = request.prompt_len
        self._active[slot] = rec
        self.metrics.inc("prefills")
        self.metrics.inc("tokens_generated")
        self._sync_slot(rec)
        done = self._finish_reason(rec, first)
        if done is not None:
            finished.append(self._retire(rec, done, clock.now()))

    def _abandon_prefill(self, rec: _Active, reason: str,
                         now: float) -> RequestResult:
        """Retire a request whose chunked prefill never completed
        (deadline/cancel): release the slot and its pages. Partially
        written rows need no scrub unless a chunk went non-finite —
        finite garbage is causally invisible to any future occupant,
        exactly like bucket-padding rows."""
        del self._prefilling[rec.slot]
        self.slots.release(rec.slot)
        if self.pages is not None:
            freed = self.pages.release_slot(rec.slot)
            self._reserved_pages -= rec.reserved_pages
            self._page_table_h[rec.slot, :] = self.pages.n_pages
            if not rec.finite_ok and freed:
                row = np.full(self.config.pages_per_slot,
                              self.pages.n_pages, np.int32)
                row[:len(freed)] = freed
                self._caches = self._scrub_fn(self._caches,
                                              jnp.asarray(row))
                self.pages.note_scrubbed(freed)
        self._clear_slot(rec.slot)
        return self._finish(
            rec.request, [], reason, submit_ts=rec.submit_ts, now=now,
            prefill_start=rec.prefill_start, prefill_end=now,
            prefill_segments=tuple(rec.chunk_marks),
            prefill_chunks=rec.prefill_chunks or None)

    def _reset_fresh_scales(self, pages) -> None:
        """Zero the scale sidecar for freshly allocated ``pages``
        (quantized pools only) — one fixed-width sentinel-padded row
        through a dedicated program, so it never adds a compile shape."""
        if not self._quantized or len(pages) == 0:
            return
        row = np.full(self.config.pages_per_slot, self.pages.n_pages,
                      np.int32)
        row[:len(pages)] = pages
        self._caches = self._reset_scales_fn(self._caches,
                                             jnp.asarray(row))

    def _build_windows(self) -> None:
        """Fill the per-slot verify windows for the next speculative
        step: row 0 is the token the sequential engine would feed
        (``last_token``), rows ``1..wl-1`` the n-gram draft over the
        slot's own history, rows past ``wl`` repeat the last real feed
        (causally invisible padding that cannot inflate an int8 page
        scale). ``wl`` is clipped so a nearly-finished request cannot
        overrun its ``max_new_tokens`` page reservation."""
        k = self._spec
        for slot in sorted(self._active):
            rec = self._active[slot]
            wl = max(1, min(
                k, rec.request.max_new_tokens - len(rec.tokens)))
            draft = propose_draft(
                list(rec.request.prompt) + rec.tokens, wl - 1)
            window = [rec.last_token] + draft
            window += [window[-1]] * (k - wl)
            self._window_h[slot] = window
            self._wlen_h[slot] = wl

    def _decode_tick(self, finished: List[RequestResult]) -> None:
        if self._spec and self._active:
            self._build_windows()
        if self.pages is not None:
            self._extend_pages(finished)
        if not self._active:
            return
        if self._faults is not None:
            self._faults.before_decode()
        if self.pages is not None:
            # roofline gauge: bytes of KV stream one decode step reads
            # (mapped pages of every active slot, dtype- and sidecar-
            # aware) — THE denominator speculation and int8 shrink
            self.metrics.set_gauge(
                "kv_bytes_per_step",
                sum(len(self.pages.slot_pages(s)) for s in self._active)
                * self._page_read_bytes)
            fed = (jnp.asarray(self._window_h) if self._spec
                   else jnp.asarray(self._tokens_h))
            nxt, finite, self._caches = self._decode_fn(
                self._params, self._caches,
                jnp.asarray(self._page_table_h),
                fed, jnp.asarray(self._positions_h),
                jnp.asarray(self._temps_h), jnp.asarray(self._topks_h),
                jnp.asarray(self._seeds_h),
                jnp.asarray(self._adapter_ix_h), self._bank)
        else:
            nxt, finite, self._caches = self._decode_fn(
                self._params, self._caches,
                jnp.asarray(self._tokens_h), jnp.asarray(self._positions_h),
                jnp.asarray(self._temps_h), jnp.asarray(self._topks_h),
                jnp.asarray(self._seeds_h),
                jnp.asarray(self._adapter_ix_h), self._bank)
        nxt = np.asarray(nxt)
        finite = np.asarray(finite)
        if self._faults is not None:
            nxt, finite = self._faults.corrupt_decode(nxt, finite)
        self.metrics.inc("decode_steps")
        self.metrics.observe("decode_batch_size", len(self._active))
        now = clock.now()
        if self._spec:
            self._accept_windows(nxt, finite, now, finished)
            return
        for slot in sorted(self._active):
            rec = self._active[slot]
            token = int(nxt[slot])
            # integrity check, off the critical path: non-finite logits
            # or an out-of-vocab token mean THIS row is poisoned —
            # quarantine it alone, co-tenant rows keep their clean step
            if not bool(finite[slot]) or not 0 <= token < self._vocab:
                cause = ("nonfinite_logits" if not bool(finite[slot])
                         else "out_of_vocab_token")
                finished.append(self._quarantine(rec, cause, now))
                continue
            rec.position += 1            # last_token's K/V are now cached
            rec.tokens.append(token)
            rec.last_token = token
            rec.last_token_ts = now
            self.metrics.inc("tokens_generated")
            self._sync_slot(rec)
            done = self._finish_reason(rec, token)
            if done is not None:
                finished.append(self._retire(rec, done, now))

    def _accept_windows(self, nxt, finite, now: float,
                        finished: List[RequestResult]) -> None:
        """Consume each slot's verified window: walk positions left to
        right, keep the target's sample at row ``j`` only while the
        token FED at row ``j`` was itself the target's previous output
        — the first disagreement invalidates everything to its right
        (those rows attended to a token the sequential engine would
        never have fed; their K/V rows are garbage the next window
        overwrites). Row 0 is always the sequential feed, so every
        step emits >= 1 token; a window is never slower than plain
        decode, only cheaper per token when drafts land."""
        for slot in sorted(self._active):
            rec = self._active[slot]
            wl = int(self._wlen_h[slot])
            consumed = 0
            quarantined = done = None
            for j in range(wl):
                token = int(nxt[slot, j])
                if not bool(finite[slot, j]) or \
                        not 0 <= token < self._vocab:
                    quarantined = ("nonfinite_logits"
                                   if not bool(finite[slot, j])
                                   else "out_of_vocab_token")
                    break
                rec.position += 1     # row j's fed K/V are now cached
                rec.tokens.append(token)
                rec.last_token = token
                rec.last_token_ts = now
                consumed += 1
                self.metrics.inc("tokens_generated")
                done = self._finish_reason(rec, token)
                if done is not None:
                    break
                if j + 1 >= wl or int(self._window_h[slot, j + 1]) != token:
                    break             # draft diverged from the target
            # rows 1..wl-1 were drafted; the drafts the walk consumed
            # BEYOND the mandatory row-0 token are the accepted ones
            proposed = wl - 1
            accepted = max(0, consumed - 1)
            if proposed:
                self.metrics.inc("draft_tokens_proposed", proposed)
                self.metrics.inc("draft_tokens_accepted", accepted)
                self.metrics.observe("spec_accept_rate",
                                     accepted / proposed)
                rec.spec_proposed += proposed
                rec.spec_accepted += accepted
            if quarantined is not None:
                # poisoned at any window row: quarantine the slot even
                # if clean tokens landed first — its KV is suspect
                finished.append(self._quarantine(rec, quarantined, now))
                continue
            self._sync_slot(rec)
            if done is not None:
                finished.append(self._retire(rec, done, now))

    def _extend_pages(self, finished: List[RequestResult]) -> None:
        """On-demand page growth before the decode step: every active
        slot must have the page backing row ``position`` mapped (the
        fused kernel appends there). Admission reserved each request's
        worst case, so the extend cannot fail — the defensive branch
        retires the slot as an error rather than corrupting a foreign
        page, and counts the shed so the monitor surfaces it."""
        now = clock.now()
        for slot in sorted(self._active):
            rec = self._active[slot]
            # a speculative step appends K/V for the whole verify
            # window (positions position..position+wl-1); wl is clipped
            # to the request's max_new_tokens, so the target stays
            # within the admission reservation
            grow = int(self._wlen_h[slot]) if self._spec else 1
            fresh = self.pages.extend_slot(slot, rec.position + grow)
            if fresh is None:
                self.metrics.inc("requests_shed_pages")
                log_event(_LOG, "request_shed",
                          request_id=rec.request.request_id,
                          reason="pages_exhausted", mid_flight=True)
                self.metrics.event("request_shed",
                                   request_id=rec.request.request_id,
                                   reason="pages_exhausted",
                                   mid_flight=True)
                finished.append(self._retire(rec, FINISH_ERROR, now))
                continue
            if fresh:
                row = self._page_table_h[slot]
                pages = self.pages.slot_pages(slot)
                row[len(pages) - len(fresh):len(pages)] = fresh
                self._reset_fresh_scales(fresh)

    # -- retirement & bookkeeping ----------------------------------------

    def _quarantine(self, rec: _Active, cause: str,
                    now: float) -> RequestResult:
        """Retire ONE poisoned slot and keep the batch serving: scrub the
        row's KV (NaNs must not outlive the occupant — a masked attention
        weight times a NaN value is still NaN), release the slot, and
        finish the request with ``finish_reason="error"`` — co-tenants
        are untouched and the decode program never retraces.

        Under the paged layout only the pages this release actually
        FREES are scrubbed (``_retire(scrub=True)``): shared prefix
        pages still referenced by co-tenant slots or the intern index
        hold exclusively pre-intern prefill data (interned pages are
        never written again — decode appends land past the prompt's full
        pages, and interning is gated on finite prefill logits), so they
        are clean by construction and co-tenants keep token-exact
        streams; they are zeroed when their LAST reference drops."""
        slot = rec.slot
        if self.pages is None:
            self._caches = self._scrub_fn(self._caches, jnp.int32(slot))
        self.metrics.inc("slots_quarantined")
        log_event(_LOG, "slot_quarantined", slot=slot,
                  request_id=rec.request.request_id, cause=cause)
        self.metrics.event("slot_quarantined", slot=slot,
                           request_id=rec.request.request_id, cause=cause)
        # mark span (zero-width): annotates the timeline with the scrub —
        # excluded from the phase-span conservation sum
        emit_span(self.metrics, SPAN_QUARANTINE,
                  trace_id=rec.request.trace_id,
                  request_id=rec.request.request_id,
                  start_s=now, end_s=now, wall=clock.wall(),
                  replica_id=self.replica_id, detail=cause)
        return self._retire(rec, FINISH_ERROR, now, scrub=True)

    def _finish_reason(self, rec: _Active, token: int) -> Optional[str]:
        if rec.request.eos_token is not None and \
                token == rec.request.eos_token:
            return FINISH_EOS
        if len(rec.tokens) >= rec.request.max_new_tokens:
            return FINISH_LENGTH
        return None

    def _sync_slot(self, rec: _Active) -> None:
        sp = rec.request.sampling
        i = rec.slot
        self._tokens_h[i] = rec.last_token
        self._positions_h[i] = rec.position
        self._temps_h[i] = sp.temperature
        self._topks_h[i] = sp.top_k if sp.top_k is not None else self._vocab
        self._seeds_h[i] = sp.seed
        self._adapter_ix_h[i] = rec.adapter_ix

    def _clear_slot(self, slot: int) -> None:
        self._tokens_h[slot] = 0
        self._positions_h[slot] = 0
        self._temps_h[slot] = 0.0
        self._topks_h[slot] = self._vocab
        self._seeds_h[slot] = 0
        self._adapter_ix_h[slot] = self._null_adapter
        if self._spec:
            self._window_h[slot] = 0
            self._wlen_h[slot] = 1

    def _retire(self, rec: _Active, reason: str, now: float, *,
                scrub: bool = False) -> RequestResult:
        del self._active[rec.slot]
        self.slots.release(rec.slot)
        if self.pages is not None:
            # release returns only the pages whose LAST reference this
            # drop removed — shared prefix pages outlive the slot
            freed = self.pages.release_slot(rec.slot)
            self._reserved_pages -= rec.reserved_pages
            self._page_table_h[rec.slot, :] = self.pages.n_pages
            if scrub and freed:
                # fixed-width row (sentinel-padded) through the same
                # scrub program — no new compile shapes
                row = np.full(self.config.pages_per_slot,
                              self.pages.n_pages, np.int32)
                row[:len(freed)] = freed
                self._caches = self._scrub_fn(self._caches,
                                              jnp.asarray(row))
                # PagePool.check() can now assert these free pages hold
                # zero scales until their next allocation
                self.pages.note_scrubbed(freed)
        self._clear_slot(rec.slot)
        if rec.spec_proposed:
            # mark span over the decode stretch the verify windows rode:
            # lifetime speculation totals, for the --trace timeline
            emit_span(self.metrics, SPAN_SPEC_VERIFY,
                      trace_id=rec.request.trace_id,
                      request_id=rec.request.request_id,
                      start_s=rec.prefill_end, end_s=now,
                      wall=clock.wall(), replica_id=self.replica_id,
                      proposed=rec.spec_proposed,
                      accepted=rec.spec_accepted)
        return self._finish(
            rec.request, rec.tokens, reason, submit_ts=rec.submit_ts,
            now=now, prefill_start=rec.prefill_start,
            prefill_end=rec.prefill_end,
            first_token_ts=rec.first_token_ts,
            last_token_ts=rec.last_token_ts,
            prefill_segments=tuple(rec.chunk_marks),
            prefill_chunks=rec.prefill_chunks or None)

    def _finish(self, request: Request, tokens: List[int], reason: str, *,
                submit_ts: float, now: float, prefill_start: float = 0.0,
                prefill_end: float = 0.0, first_token_ts: float = 0.0,
                last_token_ts: float = 0.0,
                prefill_segments: Sequence[float] = (),
                prefill_chunks: Optional[int] = None,
                detail: Optional[str] = None) -> RequestResult:
        if prefill_start:
            queue_s = prefill_start - submit_ts
            prefill_s = prefill_end - prefill_start
            decode_s = now - prefill_end
        else:                       # never left the queue
            queue_s, prefill_s, decode_s = now - submit_ts, 0.0, 0.0
        # SLO primitives, from the engine's own token timestamps: TTFT is
        # submit -> first token on the host; TPOT is the mean inter-token
        # interval (needs >= 2 tokens to define an interval)
        ttft_s = (first_token_ts - submit_ts
                  if tokens and first_token_ts else None)
        tpot_s = ((last_token_ts - first_token_ts) / (len(tokens) - 1)
                  if len(tokens) >= 2 and first_token_ts else None)
        result = RequestResult(
            request_id=request.request_id, prompt_len=request.prompt_len,
            tokens=list(tokens), finish_reason=reason, queue_s=queue_s,
            prefill_s=prefill_s, decode_s=decode_s,
            total_s=now - submit_ts, ttft_s=ttft_s, tpot_s=tpot_s,
            replica_id=self.replica_id,
            adapter_id=request.sampling.adapter_id,
            trace_id=request.trace_id,
            prefill_chunks=prefill_chunks,
            priority=request.sampling.priority)
        self.completed[request.request_id] = result
        self.metrics.inc(f"requests_{reason}")
        # the span timeline, stamped at the SAME terminal choke point and
        # from the SAME timestamps as the queue/prefill/decode
        # decomposition above — so span-sum == total_s by construction,
        # and restarts stay exactly-once (a dead incarnation emits
        # neither a record nor spans)
        emit_request_spans(
            self.metrics, trace_id=request.trace_id,
            request_id=request.request_id, submit_ts=submit_ts, now=now,
            wall=clock.wall(), prefill_start=prefill_start,
            prefill_end=prefill_end, replica_id=self.replica_id,
            prefill_segments=prefill_segments, detail=detail)
        for name, value in (("request_queue_s", result.queue_s),
                            ("request_prefill_s", result.prefill_s),
                            ("request_decode_s", result.decode_s),
                            ("request_total_s", result.total_s)):
            self.metrics.observe(name, value)
        tps = result.tokens_per_s
        if tps is not None:
            self.metrics.observe("request_tokens_per_s", tps)
        if result.ttft_s is not None:
            self.metrics.observe("request_ttft_s", result.ttft_s)
        if result.tpot_s is not None:
            self.metrics.observe("request_tpot_s", result.tpot_s)
        self.metrics.emit_record(result.record(wall=clock.wall()))
        if reason in (FINISH_REJECTED, FINISH_TIMEOUT, FINISH_CANCELLED,
                      FINISH_ERROR):
            extra = {"reason": detail} if detail else {}
            log_event(_LOG, f"request_{reason}",
                      request_id=request.request_id,
                      prompt_len=request.prompt_len,
                      new_tokens=result.new_tokens,
                      total_s=result.total_s, **extra)
            self.metrics.event(f"request_{reason}",
                               request_id=request.request_id,
                               new_tokens=result.new_tokens, **extra)
        return result
