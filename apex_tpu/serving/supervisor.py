"""Engine supervision: restart-with-recovery and overload admission.

:class:`~apex_tpu.serving.engine.InferenceEngine` owns device state and
assumes every jitted step returns; production traffic does not oblige —
a decode exception, a hung collective, or a poisoned slot must be
routine, not fatal (TorchTitan makes fault tolerance a first-class
pillar of LLM infrastructure; PR 1's resilience driver did the same for
training). :class:`EngineSupervisor` is the serving-side survive leg:

- **Tick-level fault recovery**: every ``tick()`` runs under a
  try/except plus a wall-clock budget (``hung_tick_s``). On failure the
  supervisor rebuilds the engine from scratch — fresh slot pool, fresh
  KV caches, fresh jit wrappers — and **re-prefills every in-flight
  request from its prompt plus the tokens already generated**. Because
  sampling keys on the absolute position (``fold_in(seed, position)``)
  and greedy decoding is prefix-deterministic, a resumed request's
  stream is TOKEN-EXACT across the restart, for greedy and sampled
  requests alike. Recovery is budgeted per request
  (``max_restarts_per_request``); over-budget requests retire with
  ``finish_reason="error"`` — admitted work is never silently lost.
- **Circuit breaker**: ``breaker_threshold`` consecutive tick failures
  open the breaker; while open, ``submit()`` fails fast with
  :class:`EngineUnavailableError` instead of queuing doomed work. After
  ``breaker_cooldown_s`` the breaker goes half-open; the next clean tick
  closes it, the next failure re-opens it with a fresh cooldown.
- **Deadline-aware load shedding**: the supervisor tracks an EWMA of
  observed per-request service time; a deadline request whose projected
  queue wait (``queue_depth × ewma``) already exceeds its remaining
  budget is shed at submit — layered on the scheduler's
  ``QueueFullError`` backpressure and expired-deadline fast-fail.

Every retry / quarantine / breaker transition / shed is wired into the
shared :class:`~apex_tpu.observability.MetricsRegistry` (counters AND
``kind="event"`` incident records) and each terminal outcome emits one
``kind="request"`` row, so ``python -m apex_tpu.monitor`` reconciles the
incident timeline against the counters key-for-key — the serving
counterpart of the trainer's telemetry contract. The registry is owned
by the supervisor and survives engine rebuilds.

One metrics invariant to lean on: every arrival increments
``requests_submitted`` exactly once (restart continuations resubmit
with ``resubmission=True``) and produces exactly one terminal
``kind="request"`` record plus one ``requests_<reason>`` increment —
whether it finishes in the engine, is shed at admission, or is retired
by the supervisor itself.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from apex_tpu.observability import MetricsRegistry
from apex_tpu.serving import clock
from apex_tpu.observability.trace import (
    SPAN_DECODE,
    SPAN_RESUME,
    SPAN_SHED,
    emit_span,
)
from apex_tpu.serving.engine import EngineConfig, InferenceEngine
from apex_tpu.serving.request import (
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_REJECTED,
    FINISH_TIMEOUT,
    PRIORITY_RANK,
    Request,
    RequestResult,
)
from apex_tpu.serving.scheduler import DeadlineExpiredError, QueueFullError
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["EngineUnavailableError", "SupervisorConfig", "EngineSupervisor",
           "BREAKER_CLOSED", "BREAKER_OPEN", "BREAKER_HALF_OPEN"]

_LOG = get_logger(__name__)

#: circuit-breaker states (EngineSupervisor.breaker_state)
BREAKER_CLOSED = "closed"        # normal admission
BREAKER_OPEN = "open"            # submit() fails fast, cooldown running
BREAKER_HALF_OPEN = "half_open"  # probing: next tick decides

#: declared up front so the final snapshot carries every key even for
#: incident types that never fired — the monitor's serving-incidents
#: section reconciles these against the event stream key-for-key
_SUP_COUNTERS = ("engine_restarts", "tick_failures", "requests_recovered",
                 "breaker_opens", "breaker_half_opens", "breaker_closes",
                 "requests_shed_breaker", "requests_shed_deadline",
                 "requests_resumed")


class EngineUnavailableError(RuntimeError):
    """Admission control rejected the submit: the circuit breaker is
    open, or the projected queue wait already exceeds the request's
    deadline. The request IS recorded terminally
    (``finish_reason="rejected"``) — fail fast, never silently drop."""


@dataclass
class SupervisorConfig:
    """Recovery and admission-control knobs (docs/serving.md#robustness).

    ``hung_tick_s`` is a wall-clock budget per engine tick: a tick that
    takes longer is treated as a tick failure (its committed tokens are
    kept — recovery re-prefills from prompt + tokens, so a slow-but-
    completed tick loses nothing). ``None`` disables the check.
    ``max_engine_restarts`` bounds TOTAL rebuild work per supervisor
    lifetime — past it every surviving request retires with an error
    instead of looping a persistently-broken engine forever.
    """

    max_restarts_per_request: int = 2
    max_engine_restarts: int = 32
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 0.25
    hung_tick_s: Optional[float] = None
    shed_deadlines: bool = True
    #: EWMA weight for the observed per-request service time that feeds
    #: the deadline shed estimate
    service_time_alpha: float = 0.3

    def __post_init__(self):
        if self.max_restarts_per_request < 0:
            raise ValueError(
                f"max_restarts_per_request must be >= 0, got "
                f"{self.max_restarts_per_request}")
        if self.max_engine_restarts < 1:
            raise ValueError(
                f"max_engine_restarts must be >= 1, got "
                f"{self.max_engine_restarts}")
        if self.breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got "
                f"{self.breaker_threshold}")
        if self.breaker_cooldown_s <= 0:
            raise ValueError(
                f"breaker_cooldown_s must be positive, got "
                f"{self.breaker_cooldown_s}")
        if self.hung_tick_s is not None and self.hung_tick_s <= 0:
            raise ValueError(
                f"hung_tick_s must be positive, got {self.hung_tick_s}")
        if not 0.0 < self.service_time_alpha <= 1.0:
            raise ValueError(
                f"service_time_alpha must be in (0, 1], got "
                f"{self.service_time_alpha}")


class _Tracked:
    """Supervisor-side state of one admitted-and-not-yet-terminal
    request — the source of truth that survives engine rebuilds."""

    __slots__ = ("request", "first_submit_ts", "prefix", "restarts",
                 "order")

    def __init__(self, request: Request, submit_ts: float, order: int):
        self.request = request
        self.first_submit_ts = submit_ts
        self.prefix: List[int] = []   # tokens recovered from dead engines
        self.restarts = 0
        self.order = order            # original arrival order (FCFS)


class EngineSupervisor:
    """Crash-only wrapper around :class:`InferenceEngine`; see the
    module docstring. API mirrors the engine: :meth:`submit` /
    :meth:`cancel` / :meth:`tick` / :meth:`serve` / :meth:`close`, plus
    context-manager support; results land in :attr:`completed` with the
    ORIGINAL prompt lengths and the full recovered token streams."""

    def __init__(self, model, params,
                 config: Optional[EngineConfig] = None, *,
                 supervisor: Optional[SupervisorConfig] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 faults=None, replica_id: Optional[int] = None,
                 service_s: Optional[float] = None,
                 engine_factory=None, adapters=None):
        self._model = model
        self._params = params
        #: LoRA :class:`~apex_tpu.lora.AdapterStore`, handed to every
        #: engine incarnation — the store (and its device bank) is
        #: SUPERVISOR state, so loaded adapters survive engine rebuilds
        #: and restart continuations keep their per-tenant deltas
        self._adapters = adapters
        self.config = config or EngineConfig()
        self.supervisor = supervisor or SupervisorConfig()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.metrics.declare_counters(*_SUP_COUNTERS)
        self._faults = faults
        #: fleet replica label, stamped on every result/record this
        #: supervisor (or its engines) emits; None = standalone
        self.replica_id = replica_id
        self.completed: Dict[int, RequestResult] = {}
        self._tracked: Dict[int, _Tracked] = {}
        #: restart continuations waiting for queue room in the new engine
        self._backlog: List[Request] = []
        #: backlog ids that are PREEMPTION resumes (not restart
        #: recoveries) — tagged so the successful resubmit emits the
        #: ``requests_resumed`` counter / zero-width resume mark span
        self._resuming: set = set()
        self._order = 0
        self._closed = False
        self.restarts = 0
        self.breaker_state = BREAKER_CLOSED
        self._breaker_opened_ts = 0.0
        self._consecutive_failures = 0
        # the deadline-shedding EWMA is SUPERVISOR state: it survives
        # engine rebuilds, and a fleet replica rebuild seeds the fresh
        # supervisor with the old one's estimate (``service_s=``) so the
        # first post-restart submits are not admitted blind
        self._service_s: Optional[float] = service_s
        # token-aware companion EWMAs (same alpha): per-token prefill
        # cost and typical prompt length, so the shed projection and the
        # fleet Router can price a backlog of LONG prompts above the
        # same depth of short ones (docs/serving.md#chunked-prefill).
        # None until the first completion measures them.
        self._prefill_s_per_token: Optional[float] = None
        self._avg_prompt_tokens: Optional[float] = None
        #: custom engine constructor, ``(model, params, config, *,
        #: metrics, faults, replica_id) -> InferenceEngine`` — how a
        #: fleet runs :class:`~apex_tpu.serving.fleet.ShardedEngine`
        #: replicas under the same supervision
        self._engine_factory = engine_factory or InferenceEngine
        self.engine = self._build_engine()

    def _build_engine(self) -> InferenceEngine:
        kwargs = dict(metrics=self.metrics, faults=self._faults,
                      replica_id=self.replica_id)
        if self._adapters is not None:
            # only forwarded when set, so custom engine factories that
            # predate multi-LoRA keep their narrower signature
            kwargs["adapters"] = self._adapters
        eng = self._engine_factory(self._model, self._params, self.config,
                                   **kwargs)
        # this supervisor drains take_parked() every tick, so the engine
        # may preempt: a parked request is guaranteed a resume path
        try:
            eng.resume_consumer = True
        except AttributeError:
            pass   # custom factories that predate preemption
        return eng

    # -- introspection ----------------------------------------------------

    @property
    def active_count(self) -> int:
        return self.engine.active_count

    @property
    def queued_count(self) -> int:
        return self.engine.queued_count + len(self._backlog)

    @property
    def inflight_count(self) -> int:
        """Admitted-or-queued requests not yet terminal."""
        return len(self._tracked)

    @property
    def inflight_ids(self) -> List[int]:
        """Ids of admitted-or-queued requests not yet terminal — what a
        driver must cancel to drain the supervisor early (the loadtest
        wall-budget abort path)."""
        return sorted(self._tracked)

    @property
    def service_estimate_s(self) -> Optional[float]:
        """The deadline-shedding EWMA of observed per-request service
        time (None until the first completion) — also the fleet router's
        per-replica load weight, and the value carried into a rebuilt
        replica so it never restarts blind."""
        return self._service_s

    @property
    def queued_prompt_tokens(self) -> int:
        """Total prompt tokens waiting in line (engine queue + restart
        backlog) — the token-denominated companion to
        :attr:`queued_count`."""
        return (self.engine.queued_tokens
                + sum(r.prompt_len for r in self._backlog))

    @property
    def queued_token_excess_s(self) -> float:
        """Extra prefill seconds the queued PROMPT TOKENS represent
        beyond what ``depth x EWMA(service_s)`` already prices in.

        ``depth x service_s`` assumes every queued request costs the
        observed average; a backlog of unusually long prompts breaks
        that (the first open failure mode ISSUE 15's router satellite
        names). This is the bounded, additive correction: the queued
        tokens in EXCESS of ``depth x EWMA(prompt_tokens)``, at the
        observed per-token prefill rate. Non-negative by construction
        (a backlog of SHORT prompts never discounts the estimate below
        the depth-based one), and 0.0 until both token EWMAs have been
        measured — so uniform traffic, fresh supervisors, and every
        pre-existing test see exactly the old behavior."""
        if self._prefill_s_per_token is None \
                or self._avg_prompt_tokens is None:
            return 0.0
        waiting = self.engine.queued_count + len(self._backlog)
        excess = self.queued_prompt_tokens - waiting * self._avg_prompt_tokens
        return max(0.0, excess) * self._prefill_s_per_token

    def _queued_ahead(self, priority: str):
        """``(depth, token_excess_s)`` of the queued work that would
        dispatch AT OR BEFORE ``priority`` under strict-priority order —
        the class-aware inputs to the deadline-shed projection, so an
        interactive submit is not priced against a deep batch backlog
        that would never run ahead of it. Falls back to the all-class
        totals for engines that predate priority lanes."""
        rank = PRIORITY_RANK.get(priority)
        depth_by = getattr(self.engine, "queued_depth_by_class", None)
        tokens_by = getattr(self.engine, "queued_tokens_by_class", None)
        if rank is None or depth_by is None or tokens_by is None:
            return (self.engine.queued_count + len(self._backlog),
                    self.queued_token_excess_s)
        waiting = sum(n for p, n in depth_by().items()
                      if PRIORITY_RANK[p] <= rank)
        tokens = sum(n for p, n in tokens_by().items()
                     if PRIORITY_RANK[p] <= rank)
        for r in self._backlog:
            if PRIORITY_RANK.get(r.sampling.priority, 0) <= rank:
                waiting += 1
                tokens += r.prompt_len
        if self._prefill_s_per_token is None \
                or self._avg_prompt_tokens is None:
            return waiting, 0.0
        excess = tokens - waiting * self._avg_prompt_tokens
        return waiting, max(0.0, excess) * self._prefill_s_per_token

    def queued_token_excess_s_for(self, priority: str) -> float:
        """Class-aware :attr:`queued_token_excess_s`: only the queued
        tokens of same-or-higher classes count (ISSUE 20 satellite —
        a batch backlog must not inflate the shed estimate for an
        interactive submit)."""
        return self._queued_ahead(priority)[1]

    # -- priority control (brownout ladder / fleet passthroughs) ----------

    def set_admission_floor(self, priority: Optional[str]) -> None:
        """Pause dispatch of classes below ``priority`` (engine/scheduler
        passthrough); ``None`` restores all classes."""
        fn = getattr(self.engine, "set_admission_floor", None)
        if fn is not None:
            fn(priority)

    def preempt_class(self, priority: str, *, cause: str = "brownout") -> int:
        """Park every active slot of ``priority`` and immediately queue
        their resume continuations (the brownout ladder's "preempt batch
        slots" rung). Returns the number parked."""
        fn = getattr(self.engine, "park_class", None)
        if fn is None:
            return 0
        n = fn(priority, cause=cause)
        if n:
            self._drain_parked(clock.now())
            self._drain_backlog()
        return n

    # -- admission --------------------------------------------------------

    def submit(self, request: Request, *, resubmission: bool = False) -> int:
        """Admit one request through the overload gates: circuit breaker
        first, then the deadline-aware shed estimate, then the engine's
        own queue bound and expired-deadline fast-fail. Raises
        :class:`EngineUnavailableError` /
        :class:`~apex_tpu.serving.scheduler.QueueFullError` /
        :class:`~apex_tpu.serving.scheduler.DeadlineExpiredError`; every
        rejection is recorded terminally.

        ``resubmission=True`` is the fleet's migration path (a request
        handed over from a draining peer): it was already counted at its
        ORIGINAL submit, so ``requests_submitted`` is not incremented
        again — one arrival == one count == one terminal record, however
        many replicas the request visited."""
        if self._closed:
            raise RuntimeError("supervisor is closed")
        now = clock.now()
        self._poll_breaker(now)
        if self.breaker_state == BREAKER_OPEN:
            self._shed(request, "breaker", now, resubmission=resubmission)
        if (self.supervisor.shed_deadlines
                and request.deadline_s is not None
                and self._service_s is not None):
            # projected wait before this request even starts: everything
            # in line that would dispatch at-or-before its class, at the
            # observed per-request service rate, plus the token-aware
            # surcharge for unusually long prompts (0.0 until measured)
            waiting, excess_s = self._queued_ahead(
                request.sampling.priority)
            projected = waiting * self._service_s + excess_s
            start = request.arrival_ts if request.arrival_ts is not None \
                else now
            remaining = request.deadline_s - (now - start)
            if projected > remaining:
                self._shed(request, "deadline", now,
                           resubmission=resubmission,
                           projected_s=projected, remaining_s=remaining)
        tr = _Tracked(request, now, self._order)
        self._order += 1
        self._tracked[request.request_id] = tr
        try:
            self.engine.submit(request, resubmission=resubmission)
        except Exception:
            # QueueFull/DeadlineExpired were recorded terminally by the
            # engine and harvest below; validation errors recorded
            # nothing — either way the request must not stay tracked
            self._harvest(now)
            self._tracked.pop(request.request_id, None)
            raise
        return request.request_id

    def _shed(self, request: Request, why: str, now: float, *,
              resubmission: bool = False, **fields) -> None:
        """Reject at admission: terminal ``rejected`` record + counters +
        ``request_shed`` incident event, then raise."""
        if not resubmission:
            self.metrics.inc("requests_submitted")
        self.metrics.inc(f"requests_shed_{why}")
        self.metrics.inc(f"requests_{FINISH_REJECTED}")
        start = request.arrival_ts if request.arrival_ts is not None \
            else now
        result = RequestResult(
            request_id=request.request_id, prompt_len=request.prompt_len,
            tokens=[], finish_reason=FINISH_REJECTED,
            queue_s=now - start, total_s=now - start,
            replica_id=self.replica_id,
            adapter_id=request.sampling.adapter_id,
            trace_id=request.trace_id,
            priority=request.sampling.priority)
        self.completed[request.request_id] = result
        wall = clock.wall()
        # one shed phase span covering the request's whole (rejected)
        # lifetime — span-sum == total_s for admission sheds too
        emit_span(self.metrics, SPAN_SHED, trace_id=request.trace_id,
                  request_id=request.request_id, start_s=start,
                  end_s=now, wall=wall, replica_id=self.replica_id,
                  detail=why)
        self.metrics.emit_record(result.record(wall=wall))
        log_event(_LOG, "request_shed", request_id=request.request_id,
                  reason=why, **fields)
        self.metrics.event("request_shed", request_id=request.request_id,
                           reason=why, **fields)
        raise EngineUnavailableError(
            f"request {request.request_id} shed at admission "
            f"({why}): "
            + ("circuit breaker is open — engine is failing; retry after "
               f"{self.supervisor.breaker_cooldown_s}s"
               if why == "breaker" else
               f"projected queue wait {fields.get('projected_s', 0.0):.3f}s "
               f"exceeds remaining deadline "
               f"{fields.get('remaining_s', 0.0):.3f}s"))

    def cancel(self, request_id: int) -> bool:
        """Cancel a queued, in-flight, or restart-pending request."""
        now = clock.now()
        for i, cont in enumerate(self._backlog):
            if cont.request_id == request_id:
                del self._backlog[i]
                self._resuming.discard(request_id)
                tr = self._tracked.pop(request_id)
                self._retire_supervised(tr, FINISH_CANCELLED, now)
                return True
        found = self.engine.cancel(request_id)
        if found:
            self._harvest(now)   # queued cancels are terminal immediately
        return found

    # -- the supervised tick ----------------------------------------------

    def tick(self) -> List[RequestResult]:
        """One engine tick under supervision. Failures (exception or
        hung-tick budget) trigger a restart with in-flight recovery; the
        return value lists requests that reached a terminal state in the
        SUPERVISOR's view during this call."""
        if self._closed:
            raise RuntimeError("supervisor is closed")
        before = set(self.completed)
        now = clock.now()
        self._poll_breaker(now)
        self._drain_backlog()
        compiles = self.engine.prefill_compiles + self.engine.decode_compiles
        t0 = clock.now()
        failure: Optional[str] = None
        try:
            self.engine.tick()
        except Exception as exc:  # tick faults are recoverable by design
            failure = f"{type(exc).__name__}: {exc}"
        else:
            hung = self.supervisor.hung_tick_s
            elapsed = clock.now() - t0
            # warmup ticks are exempt: a bounded, expected XLA compile
            # (fresh engine, new prefill bucket) is not a hang
            compiled = (self.engine.prefill_compiles
                        + self.engine.decode_compiles) > compiles
            if hung is not None and elapsed > hung and not compiled:
                failure = (f"hung tick: {elapsed:.3f}s > "
                           f"budget {hung:.3f}s")
        if failure is not None:
            self._on_tick_failure(failure)
        else:
            self._consecutive_failures = 0
            if self.breaker_state == BREAKER_HALF_OPEN:
                self._breaker_to(BREAKER_CLOSED)
            after = clock.now()
            self._harvest(after)
            # preempted slots parked this tick become resume
            # continuations NOW — re-queued in their own class lane so
            # strict priority keeps them behind the displacing traffic
            self._drain_parked(after)
            self._drain_backlog()
        return [self.completed[rid] for rid in sorted(
            set(self.completed) - before)]

    def serve(self, requests: Sequence[Request], *,
              on_tick: Optional[Callable[["EngineSupervisor", int], None]]
              = None, max_ticks: Optional[int] = None
              ) -> List[RequestResult]:
        """Serve ``requests`` to completion under supervision. Requests
        rejected by admission control (breaker open, shed, queue full)
        are terminal immediately and appear in the returned results with
        ``finish_reason="rejected"`` — every submitted request reaches a
        terminal state, faults or not."""
        pending = list(requests)
        ids = [r.request_id for r in pending]
        ticks = 0
        while pending or self._tracked:
            while pending and (self.engine.queued_count
                               < self.config.scheduler.max_queue):
                req = pending.pop(0)
                try:
                    self.submit(req)
                except (EngineUnavailableError, QueueFullError,
                        DeadlineExpiredError):
                    pass     # already recorded terminally
            self.tick()
            ticks += 1
            if on_tick is not None:
                on_tick(self, ticks)
            if max_ticks is not None and ticks >= max_ticks:
                break
        return [self.completed[i] for i in ids if i in self.completed]

    # -- failure handling -------------------------------------------------

    def _on_tick_failure(self, failure: str) -> None:
        self.metrics.inc("tick_failures")
        self._consecutive_failures += 1
        log_event(_LOG, "tick_failure", failure=failure,
                  consecutive=self._consecutive_failures)
        self.metrics.event("tick_failure", failure=failure,
                           consecutive=self._consecutive_failures)
        if self.breaker_state == BREAKER_HALF_OPEN:
            self._breaker_to(BREAKER_OPEN)     # failed probe: re-open
        elif (self.breaker_state == BREAKER_CLOSED
              and self._consecutive_failures
              >= self.supervisor.breaker_threshold):
            self._breaker_to(BREAKER_OPEN)
        self._restart(failure)

    def _restart(self, failure: str) -> None:
        """Rebuild the engine and recover its admitted work: terminal
        results survive as-is, queued requests requeue for free, and
        every in-flight request re-prefills from prompt + generated
        tokens (bounded by its retry budget)."""
        now = clock.now()
        old = self.engine
        self._harvest(now)       # anything terminal before the fault
        queued = {r.request_id for r, _ in old.scheduler.snapshot()}
        inflight = {req.request_id: toks
                    for req, toks, _ in old.inflight()}
        self.restarts += 1
        self.metrics.inc("engine_restarts")
        log_event(_LOG, "engine_restart", failure=failure,
                  restart=self.restarts, inflight=len(inflight),
                  queued=len(queued))
        self.metrics.event("engine_restart", failure=failure,
                           restart=self.restarts, inflight=len(inflight),
                           queued=len(queued))
        self.engine = self._build_engine()
        self._backlog = []
        # a pending resume swept into the rebuild becomes a plain
        # restart continuation — the resume mark fires at most once
        self._resuming.clear()
        exhausted = self.restarts > self.supervisor.max_engine_restarts
        for rid in sorted(self._tracked,
                          key=lambda r: self._tracked[r].order):
            tr = self._tracked[rid]
            tr.prefix += inflight.get(rid, [])
            began = rid not in queued   # left the queue => lost real work
            if began:
                tr.restarts += 1
            if exhausted or \
                    tr.restarts > self.supervisor.max_restarts_per_request:
                self._retire_supervised(tr, FINISH_ERROR, now,
                                        detail="retry_budget_exhausted")
                continue
            cont = self._continuation(tr, now)
            if cont is None:
                continue        # retired inside _continuation
            if began:
                self.metrics.inc("requests_recovered")
                log_event(_LOG, "request_recovered", request_id=rid,
                          restart=tr.restarts,
                          tokens_resumed=len(tr.prefix))
                self.metrics.event("request_recovered", request_id=rid,
                                   restart=tr.restarts,
                                   tokens_resumed=len(tr.prefix))
            self._backlog.append(cont)
        self._drain_backlog()

    def _continuation(self, tr: _Tracked, now: float) -> Optional[Request]:
        """Build the re-prefill request: prompt + recovered tokens, the
        remaining token budget, the ORIGINAL deadline clock. Returns
        None (after retiring the request) when nothing remains to do."""
        req = tr.request
        remaining = req.max_new_tokens - len(tr.prefix)
        if remaining <= 0:      # fully generated just as the engine died
            self._retire_supervised(tr, FINISH_LENGTH, now)
            return None
        start = req.arrival_ts if req.arrival_ts is not None \
            else tr.first_submit_ts
        if req.deadline_s is not None and now - start > req.deadline_s:
            self._retire_supervised(tr, FINISH_TIMEOUT, now)
            return None
        return Request(
            prompt=list(req.prompt) + tr.prefix,
            max_new_tokens=remaining, sampling=req.sampling,
            eos_token=req.eos_token, deadline_s=req.deadline_s,
            request_id=req.request_id, arrival_ts=start,
            trace_id=req.trace_id)

    def _drain_parked(self, now: float) -> None:
        """Turn preempted (parked) requests into restart-style resume
        continuations: fold the generated tokens into the tracked
        prefix, rebuild the request with the remaining budget and the
        ORIGINAL ids/deadline clock, and queue it for resubmission.
        Preemption is not a failure: restart budgets are NOT charged
        and ``requests_recovered`` does not fire — the resume has its
        own counter/event pair, emitted at successful resubmit."""
        take = getattr(self.engine, "take_parked", None)
        if take is None:
            return
        for request, tokens, _submit_ts in take():
            tr = self._tracked.get(request.request_id)
            if tr is None:
                continue   # cancelled/retired while parked
            tr.prefix += tokens
            cont = self._continuation(tr, now)
            if cont is None:
                continue   # retired (length/timeout) inside
            self._resuming.add(request.request_id)
            self._backlog.append(cont)

    def _drain_backlog(self) -> None:
        while self._backlog and (self.engine.queued_count
                                 < self.config.scheduler.max_queue):
            cont = self._backlog.pop(0)
            rid = cont.request_id
            resuming = rid in self._resuming
            self._resuming.discard(rid)
            try:
                self.engine.submit(cont, resubmission=True)
            except (QueueFullError, DeadlineExpiredError):
                # terminal in the engine (recorded there) — harvest below
                self._harvest(clock.now())
            else:
                if resuming:
                    now = clock.now()
                    tr = self._tracked.get(rid)
                    carried = len(tr.prefix) if tr is not None else 0
                    self.metrics.inc("requests_resumed")
                    log_event(_LOG, "request_resumed", request_id=rid,
                              tokens_carried=carried)
                    self.metrics.event("request_resumed", request_id=rid,
                                       tokens_carried=carried)
                    # zero-width mark on the request's ORIGINAL trace —
                    # excluded from phase conservation (MARK_SPANS), the
                    # bookend of the park's ``preempt`` mark
                    emit_span(self.metrics, SPAN_RESUME,
                              trace_id=cont.trace_id, request_id=rid,
                              start_s=now, end_s=now, wall=clock.wall(),
                              replica_id=self.replica_id,
                              tokens_carried=carried)

    def _retire_supervised(self, tr: _Tracked, reason: str, now: float,
                           detail: Optional[str] = None) -> RequestResult:
        """Terminal retirement by the supervisor itself (over-budget,
        expired mid-restart, cancelled from the backlog): one counter
        increment, one ``kind="request"`` record, one event — same
        contract as an engine-side finish."""
        rid = tr.request.request_id
        self._tracked.pop(rid, None)
        result = RequestResult(
            request_id=rid, prompt_len=tr.request.prompt_len,
            tokens=list(tr.prefix), finish_reason=reason,
            total_s=now - tr.first_submit_ts, replica_id=self.replica_id,
            adapter_id=tr.request.sampling.adapter_id,
            trace_id=tr.request.trace_id,
            priority=tr.request.sampling.priority)
        self.completed[rid] = result
        self.metrics.inc(f"requests_{reason}")
        wall = clock.wall()
        # the engine incarnation that held this request died without
        # finishing it, so the supervisor owns the timeline: one coarse
        # phase span over the whole supervised lifetime (``decode`` when
        # generation actually completed, else ``shed``)
        emit_span(self.metrics,
                  SPAN_DECODE if reason in (FINISH_EOS, FINISH_LENGTH)
                  else SPAN_SHED,
                  trace_id=tr.request.trace_id, request_id=rid,
                  start_s=tr.first_submit_ts, end_s=now, wall=wall,
                  replica_id=self.replica_id, detail=detail)
        self.metrics.emit_record(result.record(wall=wall))
        extra = {"reason": detail} if detail else {}
        log_event(_LOG, f"request_{reason}", request_id=rid,
                  new_tokens=result.new_tokens, **extra)
        self.metrics.event(f"request_{reason}", request_id=rid,
                           new_tokens=result.new_tokens, **extra)
        return result

    # -- circuit breaker --------------------------------------------------

    def _poll_breaker(self, now: float) -> None:
        if self.breaker_state == BREAKER_OPEN and \
                now - self._breaker_opened_ts \
                >= self.supervisor.breaker_cooldown_s:
            self._breaker_to(BREAKER_HALF_OPEN)

    def _breaker_to(self, state: str) -> None:
        prev = self.breaker_state
        self.breaker_state = state
        if state == BREAKER_OPEN:
            self._breaker_opened_ts = clock.now()
            counter, event = "breaker_opens", "breaker_open"
        elif state == BREAKER_HALF_OPEN:
            counter, event = "breaker_half_opens", "breaker_half_open"
        else:
            counter, event = "breaker_closes", "breaker_closed"
        self.metrics.inc(counter)
        log_event(_LOG, event, previous=prev,
                  consecutive_failures=self._consecutive_failures)
        self.metrics.event(event, previous=prev,
                           consecutive_failures=self._consecutive_failures)

    # -- harvesting -------------------------------------------------------

    def _harvest(self, now: float) -> None:
        """Move the engine's newly-terminal results into the supervisor's
        view, stitching restarted requests back together: recovered
        prefix + continuation tokens, the ORIGINAL prompt length, and a
        total latency measured from the first submit."""
        done = [rid for rid in self._tracked
                if rid in self.engine.completed]
        for rid in sorted(done, key=lambda r: self._tracked[r].order):
            tr = self._tracked.pop(rid)
            res = self.engine.completed[rid]
            if tr.prefix or tr.restarts:
                # ttft_s only survives when no token predates this engine
                # incarnation (the original first-token timestamp died
                # with the crashed engine); tpot_s — the decode cadence —
                # stays meaningful for the continuation stream
                res = RequestResult(
                    request_id=rid, prompt_len=tr.request.prompt_len,
                    tokens=tr.prefix + res.tokens,
                    finish_reason=res.finish_reason,
                    queue_s=res.queue_s, prefill_s=res.prefill_s,
                    decode_s=res.decode_s,
                    total_s=now - tr.first_submit_ts,
                    ttft_s=None if tr.prefix else res.ttft_s,
                    tpot_s=res.tpot_s, replica_id=res.replica_id,
                    adapter_id=tr.request.sampling.adapter_id,
                    trace_id=tr.request.trace_id,
                    prefill_chunks=res.prefill_chunks,
                    priority=tr.request.sampling.priority)
            self.completed[rid] = res
            service = res.prefill_s + res.decode_s
            if service > 0 and res.finish_reason in (FINISH_EOS,
                                                     FINISH_LENGTH):
                a = self.supervisor.service_time_alpha
                self._service_s = (
                    service if self._service_s is None
                    else a * service + (1.0 - a) * self._service_s)
                # token-aware companions: per-token prefill cost and
                # typical prompt length, feeding queued_token_excess_s.
                # Under chunked prefill, prefill_s includes interleaved
                # co-tenant decode wall time — a conservative (over-)
                # estimate, which is the right bias for shedding.
                if res.prefill_s > 0 and res.prompt_len > 0:
                    rate = res.prefill_s / res.prompt_len
                    self._prefill_s_per_token = (
                        rate if self._prefill_s_per_token is None
                        else a * rate + (1.0 - a) * self._prefill_s_per_token)
                    self._avg_prompt_tokens = (
                        float(res.prompt_len)
                        if self._avg_prompt_tokens is None
                        else a * res.prompt_len
                        + (1.0 - a) * self._avg_prompt_tokens)

    # -- migration (the fleet's draining-restart path) --------------------

    def detach_for_migration(self) -> List:
        """Hand every non-terminal request over to the caller as
        ``(continuation, recovered_tokens)`` pairs, in arrival order —
        the fleet's draining-restart path: a peer replica re-prefills
        each continuation (prompt + tokens already generated) TOKEN-EXACT,
        exactly like this supervisor's own restart recovery.

        A request with nothing left to do (budget fully generated,
        deadline already expired) is retired terminally here instead of
        being handed over. After this call the supervisor tracks nothing;
        the caller is expected to :meth:`close` and rebuild it. Migration
        is not a failure: per-request restart budgets are NOT charged."""
        now = clock.now()
        self._harvest(now)
        inflight = {req.request_id: toks
                    for req, toks, _ in self.engine.inflight()}
        out: List = []
        for rid in sorted(self._tracked,
                          key=lambda r: self._tracked[r].order):
            tr = self._tracked[rid]
            tr.prefix += inflight.get(rid, [])
            cont = self._continuation(tr, now)
            if cont is None:
                continue        # retired (length/timeout) terminally
            self._tracked.pop(rid)
            out.append((cont, list(tr.prefix)))
        self._backlog = []
        self._resuming.clear()
        return out

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Close the underlying engine (releases slots, flushes the
        registry). Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.engine.close()

    def __enter__(self) -> "EngineSupervisor":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False
