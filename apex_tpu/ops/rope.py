"""Fused rotary positional embedding.

Capability parity with ``fused_rotary_positional_embedding``
(``csrc/megatron/fused_rotary_positional_embedding.cpp:223-243``): plain,
cached sin/cos, THD (packed variable-length), and 2D-image variants, each with
an exact custom VJP (rotate by -θ), mirroring the functional wrappers in
``apex/transformer/functional/fused_rope.py:19-303``.

RoPE is pure elementwise math; under XLA it fuses into the surrounding
matmuls' prologue, so a handwritten Pallas kernel adds nothing — the fusion
the CUDA build needed a kernel for is the compiler's default here.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def _rotate_half(t: jax.Array) -> jax.Array:
    half = t.shape[-1] // 2
    t1, t2 = t[..., :half], t[..., half:]
    return jnp.concatenate([-t2, t1], axis=-1)


def _apply(t: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    rot_dim = cos.shape[-1]
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    out = t_rot * cos + _rotate_half(t_rot) * sin
    if t_pass.shape[-1]:
        out = jnp.concatenate([out, t_pass], axis=-1)
    return out.astype(t.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_rope(t: jax.Array, freqs: jax.Array) -> jax.Array:
    """Apply RoPE. ``t``: (s, b, h, d); ``freqs``: (s, 1, 1, d_rot)
    (reference: ``fused_rope.py:19-98``)."""
    f = freqs.astype(jnp.float32)
    return _apply(t, jnp.cos(f), jnp.sin(f))


def _rope_fwd(t, freqs):
    return fused_rope(t, freqs), freqs


def _rope_bwd(freqs, g):
    f = freqs.astype(jnp.float32)
    # inverse rotation: cos(θ) unchanged, sin(−θ) = −sin(θ)
    return _apply(g, jnp.cos(f), -jnp.sin(f)), None


fused_rope.defvjp(_rope_fwd, _rope_bwd)


def fused_rope_cached(t: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Cached-sin/cos variant (reference: ``fused_rope.py:99-178``)."""
    return _rope_cached(t, cos, sin)


@jax.custom_vjp
def _rope_cached(t, cos, sin):
    return _apply(t, cos.astype(jnp.float32), sin.astype(jnp.float32))


def _rc_fwd(t, cos, sin):
    return _rope_cached(t, cos, sin), (cos, sin)


def _rc_bwd(res, g):
    cos, sin = res
    return _apply(g, cos.astype(jnp.float32), -sin.astype(jnp.float32)), None, None


_rope_cached.defvjp(_rc_fwd, _rc_bwd)


def fused_rope_thd(t: jax.Array, cu_seqlens: jax.Array, freqs: jax.Array) -> jax.Array:
    """Packed variable-length (THD) variant (reference: ``fused_rope.py:179-246``).

    ``t``: (total_tokens, h, d); ``cu_seqlens``: (batch+1,) cumulative lengths;
    ``freqs``: (max_seq, 1, 1, d_rot). Each token uses the frequency of its
    position within its own sequence.
    """
    total = t.shape[0]
    token_idx = jnp.arange(total)
    # position within sequence: idx - cu_seqlens[seq_id]
    seq_id = jnp.searchsorted(cu_seqlens, token_idx, side="right") - 1
    pos = token_idx - cu_seqlens[seq_id]
    f = freqs[pos, 0, 0, :].astype(jnp.float32)  # (total, d_rot)
    cos = jnp.cos(f)[:, None, :]
    sin = jnp.sin(f)[:, None, :]
    return _rope_cached(t, cos, sin)


def fused_rope_2d(t: jax.Array, img_h: int, img_w: int,
                  freqs_h: jax.Array, freqs_w: jax.Array) -> jax.Array:
    """2D image variant (reference: ``fused_rope.py:247-303``).

    ``t``: (b, img_h*img_w, h, d); first half of d rotated by row frequencies,
    second half by column frequencies.
    """
    d = t.shape[-1]
    half = d // 2
    fh = jnp.broadcast_to(freqs_h[:img_h, 0, 0, :], (img_h, half))
    fw = jnp.broadcast_to(freqs_w[:img_w, 0, 0, :], (img_w, half))
    fh2 = jnp.repeat(fh[:, None, :], img_w, axis=1).reshape(img_h * img_w, half)
    fw2 = jnp.repeat(fw[None, :, :], img_h, axis=0).reshape(img_h * img_w, half)
    f = jnp.concatenate([fh2, fw2], axis=-1)[None, :, None, :].astype(jnp.float32)
    return _rope_cached(t, jnp.cos(f), jnp.sin(f))
