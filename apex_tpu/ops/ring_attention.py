"""Context-parallel attention: ring + Ulysses (all-to-all).

The reference has **no** context parallelism — its only long-context
mechanism is Megatron SP (sequence sharded between TP ranks outside matmuls,
SURVEY.md §5) and its attention kernels cap at 16k tokens
(``csrc/megatron/scaled_masked_softmax.h:460``). These two ops are the
TPU-native long-context story that closes that gap:

- :func:`ring_attention` — blockwise attention with online-softmax
  accumulation: every rank keeps its query chunk, K/V chunks rotate around
  the ``context`` mesh axis one ``ppermute`` hop per step (ICI-neighbor
  traffic only), log-sum-exp state merges chunk by chunk. Peak memory per
  rank is O(s_local^2) logits for one chunk pair; no rank ever materializes
  the full sequence.
- :func:`ulysses_attention` — DeepSpeed-Ulysses-style all-to-all: exchange
  sequence sharding for head sharding, run the fused flash kernel on the
  full sequence with ``heads/cp`` local heads, all-to-all back. Two
  collectives total; better for moderate sequence lengths where the full-seq
  flash kernel wins.

Both degrade to plain :func:`flash_attention` outside ``shard_map`` (context
world size 1). Backward comes from autodiff: the VJP of the ``ppermute``
ring is the reverse rotation, giving the standard ring-attention backward
(dK/dV accumulate as the cotangents counter-rotate).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.ops.attention import flash_attention
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound

__all__ = ["ring_attention", "ulysses_attention"]

_NEG_INF = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    axis_name: str = CONTEXT_AXIS,
) -> jax.Array:
    """Exact attention over a context-sharded sequence.

    Args:
      q, k, v: ``[batch, heads, s_local, head_dim]`` — this rank's contiguous
        sequence chunk; global sequence is the rank-order concatenation over
        ``axis_name``.
      causal: global causal mask (rank ``i``'s queries see chunks ``j < i``
        fully, chunk ``i`` triangularly, chunks ``j > i`` not at all — the
        skipped work is real: fully-masked chunks cost one masked matmul,
        and XLA's scheduler overlaps the ppermute with compute).
    """
    if not axis_bound(axis_name):
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale)
    cp = lax.axis_size(axis_name)
    if cp == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale)
    rank = lax.axis_index(axis_name)
    scale = float(softmax_scale if softmax_scale is not None
                  else 1.0 / np.sqrt(q.shape[-1]))
    b, h, sc, d = q.shape
    q32 = q.astype(jnp.float32)
    perm = [(r, (r + 1) % cp) for r in range(cp)]

    rows = jnp.arange(sc)

    def accumulate(m, l, acc, kc, vc, j):
        """Fold chunk ``j`` (owner rank of the currently-held K/V) into the
        running online-softmax state."""
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, kc.astype(jnp.float32)) * scale
        if causal:
            allowed = jnp.where(
                rank == j, rows[:, None] >= rows[None, :],
                jnp.broadcast_to(rank > j, (sc, sc)))
            s = jnp.where(allowed[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        return m_new, l, acc

    def step(carry, t):
        # rotate first, then fold: cp-1 ppermute pairs total (the own chunk
        # is folded before the scan, so no discarded final rotation)
        kc, vc, m, l, acc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        m, l, acc = accumulate(m, l, acc, kc, vc, (rank - t) % cp)
        return (kc, vc, m, l, acc), None

    m0 = jnp.full((b, h, sc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sc), jnp.float32)
    acc0 = jnp.zeros((b, h, sc, d), jnp.float32)
    m0, l0, acc0 = jax.checkpoint(accumulate)(m0, l0, acc0, k, v, rank)
    (_, _, _, l, acc), _ = lax.scan(
        jax.checkpoint(step), (k, v, m0, l0, acc0), jnp.arange(1, cp))
    return (acc / l[..., None]).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    kv_lengths: Optional[jax.Array] = None,
    axis_name: str = CONTEXT_AXIS,
) -> jax.Array:
    """All-to-all sequence parallelism: trade the sequence shard for a head
    shard, run flash attention over the full sequence, trade back.

    Requires ``heads % cp == 0``. Layouts as :func:`ring_attention`.
    """
    if not axis_bound(axis_name):
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale,
                               kv_lengths=kv_lengths)
    cp = lax.axis_size(axis_name)
    if cp == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale,
                               kv_lengths=kv_lengths)
    if q.shape[1] % cp:
        raise ValueError(
            f"ulysses_attention needs heads ({q.shape[1]}) divisible by the "
            f"context-parallel size ({cp}); use ring_attention otherwise")

    def seq_to_heads(x):
        # [b, h, s/cp, d] -> [b, h/cp, s, d]; concat order over ranks is
        # rank-major, preserving the global sequence order
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = flash_attention(qh, kh, vh, causal=causal,
                          softmax_scale=softmax_scale, kv_lengths=kv_lengths)
    # [b, h/cp, s, d] -> [b, h, s/cp, d]
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
