"""Context-parallel attention: flash ring + Ulysses (all-to-all).

The reference has **no** context parallelism — its only long-context
mechanism is Megatron SP (sequence sharded between TP ranks outside matmuls,
SURVEY.md §5) and its attention kernels cap at 16k tokens
(``csrc/megatron/scaled_masked_softmax.h:460``). These two ops are the
TPU-native long-context story that closes that gap:

- :func:`ring_attention` — every rank keeps its query chunk; K/V chunks
  rotate around the ``context`` mesh axis one ``ppermute`` hop per step
  (ICI-neighbor traffic only). Each hop runs the **Pallas flash kernel** on
  the (q chunk, kv chunk) pair with global-position masking
  (:func:`apex_tpu.ops.attention.flash_chunk_fwd`), and per-hop results
  merge by log-sum-exp weights — O(block) memory per hop, bf16 MXU matmuls,
  never an O(s_local²) logit tensor. Under a causal mask, chunks entirely
  in the future are skipped *inside* the kernel grid (every k-block masked
  -> ``pl.when`` short-circuits), so the causal ring does ~half work like
  single-chip flash. ``kv_lengths`` (global valid lengths) and causal
  ``sliding_window`` are exact across chunk boundaries.
- :func:`ulysses_attention` — DeepSpeed-Ulysses-style all-to-all: exchange
  sequence sharding for head sharding, run the fused flash kernel on the
  full sequence with ``heads/cp`` local heads, all-to-all back. Two
  collectives total; better for moderate sequence lengths where the full-seq
  flash kernel wins.

The ring backward is explicit (``jax.custom_vjp``), the standard
ring-attention reverse pass: a second rotation where every rank applies the
flash backward kernel per chunk pair with the *global* ``lse``/``delta``
residuals; dK/dV partial sums ride the rotating carry and arrive home after
a full circle. Both functions degrade to plain :func:`flash_attention`
outside ``shard_map`` (context world size 1).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.ops._support import pallas_interpret
from apex_tpu.ops.attention import (
    _LSE_PAD,
    flash_attention,
    flash_chunk_bwd,
    flash_chunk_fwd,
)
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import axis_bound, axis_size

__all__ = ["ring_attention", "ulysses_attention"]

# rows whose lse reaches this are fully-masked sentinels (the flash kernels
# write _LSE_PAD for them; real lse values are nowhere near it)
_PAD_THRESH = _LSE_PAD / 10


def _merge(o_a, lse_a, o_b, lse_b):
    """Combine two normalized partial attentions by log-sum-exp weights.
    fp32 ``o`` accumulators; ``_LSE_PAD`` rows (no visible keys) carry
    weight zero."""
    la = jnp.where(lse_a > _PAD_THRESH, -jnp.inf, lse_a)
    lb = jnp.where(lse_b > _PAD_THRESH, -jnp.inf, lse_b)
    lnew = jnp.logaddexp(la, lb)
    wa = jnp.where(jnp.isneginf(la), 0.0, jnp.exp(la - lnew))
    wb = jnp.where(jnp.isneginf(lb), 0.0, jnp.exp(lb - lnew))
    o = wa[..., None] * o_a + wb[..., None] * o_b.astype(jnp.float32)
    return o, lnew


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring(q, k, v, kv_lengths, causal, window, scale, axis_name):
    o, _ = _ring_fwd_impl(q, k, v, kv_lengths, causal, window, scale,
                          axis_name)
    return o


def _ring_fwd_impl(q, k, v, kv_lengths, causal, window, scale, axis_name):
    cp = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    sc = q.shape[2]
    q_start = rank * sc

    def chunk(kc, vc, j):
        return flash_chunk_fwd(
            q, kc, vc, q_start=q_start, k_start=j * sc, causal=causal,
            window=window, kv_lengths=kv_lengths, softmax_scale=scale)

    o0, lse0 = chunk(k, v, rank)

    def hop(carry, t):
        kc, vc, o, lse = carry
        kc, vc = _rotate((kc, vc), axis_name, cp)
        j = (rank - t) % cp
        o_j, lse_j = chunk(kc, vc, j)
        o, lse = _merge(o, lse, o_j, lse_j)
        return (kc, vc, o, lse), None

    init = (k, v, o0.astype(jnp.float32),
            jnp.where(lse0 > _PAD_THRESH, -jnp.inf, lse0))
    if pallas_interpret():
        # interpret-mode emulation (CPU tests): an interpret pallas_call
        # inside a scan body trips XLA's SPMD partitioner (a PartitionId
        # reaches it through the scan); cp is static, so unroll — compile
        # time/temp memory only matter on the scan path real HW takes
        carry = init
        for t in range(1, cp):
            carry, _ = hop(carry, t)
        _, _, o, lse = carry
    else:
        (_, _, o, lse), _ = lax.scan(hop, init, jnp.arange(1, cp))
    return o.astype(q.dtype), lse


def _rotate(tree, axis_name, cp):
    perm = [(r, (r + 1) % cp) for r in range(cp)]
    return jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm), tree)


def _ring_vjp_fwd(q, k, v, kv_lengths, causal, window, scale, axis_name):
    o, lse = _ring_fwd_impl(q, k, v, kv_lengths, causal, window, scale,
                            axis_name)
    return o, (q, k, v, kv_lengths, o, lse)


def _ring_vjp_bwd(causal, window, scale, axis_name, res, do):
    q, k, v, kv_lengths, o, lse = res
    cp = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    sc = q.shape[2]
    q_start = rank * sc
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # the chunk backward kernel expects the flash pad sentinel for rows
    # with no visible keys (merged lse keeps them at -inf)
    lse_b = jnp.where(jnp.isneginf(lse), _LSE_PAD, lse)

    def chunk_bwd(kc, vc, j):
        return flash_chunk_bwd(
            q, kc, vc, do, lse_b, delta, q_start=q_start, k_start=j * sc,
            causal=causal, window=window, kv_lengths=kv_lengths,
            softmax_scale=scale)

    def hop(carry, t):
        kc, vc, dk, dv, dq = carry
        dq_j, dk_j, dv_j = chunk_bwd(kc, vc, (rank - t) % cp)
        dq = dq + dq_j.astype(jnp.float32)
        dk = dk + dk_j.astype(jnp.float32)
        dv = dv + dv_j.astype(jnp.float32)
        # dK/dV partials travel WITH their chunk; after cp total rotations
        # each accumulator is back at its owner
        kc, vc, dk, dv = _rotate((kc, vc, dk, dv), axis_name, cp)
        return (kc, vc, dk, dv, dq), None

    init = (k, v, jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32), jnp.zeros(q.shape, jnp.float32))
    if pallas_interpret():
        # unrolled under interpret-mode emulation — see _ring_fwd_impl
        carry = init
        for t in range(cp - 1):
            carry, _ = hop(carry, t)
        kc, vc, dk, dv, dq = carry
    else:
        (kc, vc, dk, dv, dq), _ = lax.scan(hop, init, jnp.arange(cp - 1))
    # final chunk: accumulate, then rotate ONLY the accumulators home — the
    # K/V chunks' last rotation would be discarded traffic
    dq_j, dk_j, dv_j = chunk_bwd(kc, vc, (rank - (cp - 1)) % cp)
    dq = dq + dq_j.astype(jnp.float32)
    dk, dv = _rotate((dk + dk_j.astype(jnp.float32),
                      dv + dv_j.astype(jnp.float32)), axis_name, cp)
    dkvl = (None if kv_lengths is None
            else np.zeros(kv_lengths.shape, dtype=jax.dtypes.float0))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dkvl)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    kv_lengths: Optional[jax.Array] = None,
    sliding_window: Optional[int] = None,
    axis_name: str = CONTEXT_AXIS,
) -> jax.Array:
    """Exact attention over a context-sharded sequence.

    Args:
      q, k, v: ``[batch, heads, s_local, head_dim]`` — this rank's
        contiguous sequence chunk; the global sequence is the rank-order
        concatenation over ``axis_name``. ``kv_heads`` may divide ``heads``
        (GQA/MQA): the smaller K/V chunks are what rotates.
      causal: global causal mask. Rank ``i``'s queries see chunks ``j < i``
        fully, chunk ``i`` triangularly, chunks ``j > i`` not at all — and
        the skipped work is skipped *inside* the flash kernel (masked
        k-blocks never issue their matmuls).
      kv_lengths: optional int32 ``[batch]`` — GLOBAL valid key lengths
        (pad-free varlen across the whole sharded sequence).
      sliding_window: causal local attention; the window is exact across
        chunk boundaries (far-past chunks cost only grid overhead).
    """
    if sliding_window is not None and not causal:
        raise ValueError("sliding_window requires causal attention")
    if not axis_bound(axis_name) or axis_size(axis_name) == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale,
                               kv_lengths=kv_lengths,
                               sliding_window=sliding_window)
    scale = float(softmax_scale if softmax_scale is not None
                  else 1.0 / np.sqrt(q.shape[-1]))
    return _ring(q, k, v, kv_lengths, causal, sliding_window, scale,
                 axis_name)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    kv_lengths: Optional[jax.Array] = None,
    sliding_window: Optional[int] = None,
    axis_name: str = CONTEXT_AXIS,
) -> jax.Array:
    """All-to-all sequence parallelism: trade the sequence shard for a head
    shard, run flash attention over the full sequence, trade back.

    Requires ``heads % cp == 0``. Layouts as :func:`ring_attention`;
    ``kv_lengths``/``sliding_window`` apply to the full gathered sequence.
    """
    if not axis_bound(axis_name) or axis_size(axis_name) == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale,
                               kv_lengths=kv_lengths,
                               sliding_window=sliding_window)
    cp = axis_size(axis_name)
    if q.shape[1] % cp:
        raise ValueError(
            f"ulysses_attention needs heads ({q.shape[1]}) divisible by the "
            f"context-parallel size ({cp}); use ring_attention otherwise")

    def seq_to_heads(x):
        # [b, h, s/cp, d] -> [b, h/cp, s, d]; concat order over ranks is
        # rank-major, preserving the global sequence order
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = flash_attention(qh, kh, vh, causal=causal,
                          softmax_scale=softmax_scale, kv_lengths=kv_lengths,
                          sliding_window=sliding_window)
    # [b, h/cp, s, d] -> [b, h, s/cp, d]
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
