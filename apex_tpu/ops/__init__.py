"""Pallas TPU kernels + XLA fallbacks (the ``csrc/`` capability layer)."""

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)
from apex_tpu.ops.softmax import (
    scaled_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
    generic_scaled_masked_softmax,
)
from apex_tpu.ops.cross_entropy import (
    softmax_cross_entropy_loss,
    SoftmaxCrossEntropyLoss,
)
from apex_tpu.ops.attention import (
    flash_attention,
    flash_attention_packed,
    packed_attention_supported,
)
from apex_tpu.ops.ring_attention import ring_attention, ulysses_attention
from apex_tpu.ops.decode_attention import (
    fused_paged_decode_attention,
    paged_pages_for,
)
from apex_tpu.ops.rope import (
    fused_rope,
    fused_rope_cached,
    fused_rope_thd,
    fused_rope_2d,
)

__all__ = [
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "fused_rms_norm",
    "fused_rms_norm_affine",
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
    "softmax_cross_entropy_loss",
    "SoftmaxCrossEntropyLoss",
    "fused_rope",
    "fused_rope_cached",
    "fused_rope_thd",
    "fused_rope_2d",
    "flash_attention",
    "flash_attention_packed",
    "packed_attention_supported",
    "ring_attention",
    "ulysses_attention",
    "fused_paged_decode_attention",
    "paged_pages_for",
]
