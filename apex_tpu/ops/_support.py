"""Kernel dispatch support.

The reference gates CUDA kernels on availability predicates (e.g.
``FusedScaleMaskSoftmax.is_kernel_available``, ``apex/transformer/functional/
fused_softmax.py:222-248``) and falls back to eager torch. Here the analog:
Pallas TPU kernels when running on TPU, pure-``jnp`` fallbacks elsewhere
(interpret mode is available for kernel debugging via
``APEX_TPU_FORCE_PALLAS=interpret``).
"""

from __future__ import annotations

import functools
import os

import jax


@functools.lru_cache(maxsize=None)
def pallas_mode() -> str:
    """Return 'tpu' (compiled pallas), 'interpret', or 'off'."""
    forced = os.environ.get("APEX_TPU_FORCE_PALLAS", "").lower()
    if forced in ("interpret", "tpu", "off"):
        return forced
    try:
        backend = jax.default_backend()
    except Exception:
        return "off"
    return "tpu" if backend == "tpu" else "off"


def use_pallas() -> bool:
    return pallas_mode() != "off"


def pallas_interpret() -> bool:
    return pallas_mode() == "interpret"


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across jax versions (0.4.x spells it
    ``TPUCompilerParams``; the fields used here are identical)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def round_up(x: int, multiple: int) -> int:
    return ((x + multiple - 1) // multiple) * multiple


def cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def min_sublane(dtype) -> int:
    """Minimum second-to-last tile dim for a dtype on TPU."""
    import jax.numpy as jnp

    if dtype in (jnp.bfloat16, jnp.float16):
        return 16
    if dtype in (jnp.int8, jnp.uint8):
        return 32
    return 8


def block_rows(h_pad: int, dtype, *, vmem_budget: int = 4 * 1024 * 1024,
               cap: int = 256) -> int:
    """Row-block size for row-wise kernels (layer norm, softmax): as many
    rows as a ``vmem_budget``-byte fp32 block allows, capped at ``cap``,
    rounded to the dtype's sublane. Cap tuning (v5e, round 4): an
    interleaved same-process A/B on the BERT step measured 256 vs 512 at
    77.8 vs 78.4 ms — equal within noise (an apparent +5% for 512 across
    separate processes was tunnel variance); 1024 exceeds Mosaic's 16 MB
    scoped-vmem stack in the LN backward (18.9 MB of live fp32
    intermediates at (1024, 768)). 256 stays."""
    sub = min_sublane(dtype)
    bm = max(sub, min(cap, vmem_budget // (h_pad * 4)))
    return round_up(bm, sub)
