"""Fused softmax cross-entropy with label smoothing.

Capability parity with the contrib xentropy extension
(``apex/contrib/xentropy/softmax_xentropy.py:6-30``,
``contrib/csrc/xentropy/xentropy_kernel.cu``): the forward saves only the
row-wise log-sum-exp instead of materializing the softmax, and the backward
recomputes probabilities — the "inplace backward" memory saving, expressed as
custom-VJP residual choice instead of tensor mutation.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                               smoothing: float = 0.0,
                               ignore_index: int = -100) -> jax.Array:
    """Per-example loss. ``logits``: (N, V) any float dtype; ``labels``: (N,) int."""
    loss, _ = _fwd_math(logits, labels, smoothing, ignore_index)
    return loss


def _fwd_math(logits, labels, smoothing, ignore_index):
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1)) + m[..., 0]
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    picked = jnp.take_along_axis(lf, safe_labels[:, None], axis=-1)[:, 0]
    nll = lse - picked
    if smoothing > 0.0:
        # uniform-smoothing loss: smoothing * mean over classes of -log p
        nll = (1.0 - smoothing) * nll + smoothing * (lse - jnp.mean(lf, axis=-1))
    loss = jnp.where(valid, nll, 0.0)
    return loss, lse


def _vjp_fwd(logits, labels, smoothing, ignore_index):
    loss, lse = _fwd_math(logits, labels, smoothing, ignore_index)
    return loss, (logits, labels, lse)


def _vjp_bwd(smoothing, ignore_index, res, g):
    logits, labels, lse = res
    lf = logits.astype(jnp.float32)
    probs = jnp.exp(lf - lse[:, None])
    v = logits.shape[-1]
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    onehot = jax.nn.one_hot(safe_labels, v, dtype=jnp.float32)
    if smoothing > 0.0:
        target = (1.0 - smoothing) * onehot + smoothing / v
    else:
        target = onehot
    dlogits = (probs - target) * g[:, None]
    dlogits = jnp.where(valid[:, None], dlogits, 0.0)
    return dlogits.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_vjp_fwd, _vjp_bwd)


class SoftmaxCrossEntropyLoss:
    """Module-style parity API (``apex/contrib/xentropy/softmax_xentropy.py:6``)."""

    @staticmethod
    def apply(logits, labels, smoothing: float = 0.0, padding_idx: int = -100):
        return softmax_cross_entropy_loss(logits, labels, smoothing, padding_idx)
