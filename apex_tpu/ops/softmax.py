"""Fused scale + mask + softmax — Pallas TPU kernels with custom VJP.

Capability parity with the four Megatron softmax extensions
(``csrc/megatron/scaled_upper_triang_masked_softmax.{cpp,cu}``,
``scaled_masked_softmax.{cpp,cu}``, ``scaled_softmax.{cpp,cu}``,
``generic_scaled_masked_softmax.{cpp,cu}``): fused scale-by-alpha, mask fill,
and numerically-stable softmax, with the matching backward
``dx = scale * y * (dy - rowsum(dy * y))``.

Unlike the CUDA kernels — which cap sequence length at 16384 and require
power-of-two-friendly shapes (``csrc/megatron/scaled_masked_softmax.h:460``) —
the Pallas kernels tile arbitrary row lengths, so the "generic" variant is the
same code path. Masked positions are filled with ``-10000.0`` pre-softmax,
matching the reference's fill value.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._support import block_rows, cdiv, pallas_interpret, round_up, use_pallas

_MASK_FILL = -10000.0
_VMEM_BUDGET = 4 * 1024 * 1024


def _block_rows(kp: int) -> int:
    # fp32 rows (8-sublane); policy + cap tuning shared with the LN
    # kernels (ops/_support.block_rows). Cap 256 vs 512 measured ON THE
    # SOFTMAX KERNEL itself (round 5, interleaved same-process A/B,
    # fwd+bwd at 8192 rows x k=1024/2048): equal within 0.5% at both
    # key lengths, so unifying on the shared 256 default loses nothing
    # (ADVICE r4 flagged that the earlier A/B was LN-only).
    return block_rows(kp, jnp.float32, vmem_budget=_VMEM_BUDGET)


# ---------------------------------------------------------------------------
# forward / backward row kernels
# ---------------------------------------------------------------------------

def _fwd_body(x, mask, scale, k, sq, causal, row_offset):
    bm, kp = x.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (bm, kp), 1)
    valid = col < k
    logits = x.astype(jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, _MASK_FILL, logits)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (bm, kp), 0) + row_offset
        q_pos = row % sq
        logits = jnp.where(col > q_pos, _MASK_FILL, logits)
    logits = jnp.where(valid, logits, -jnp.inf)
    m = jnp.max(logits, axis=1, keepdims=True)
    e = jnp.exp(logits - m)
    e = jnp.where(valid, e, 0.0)
    return e / jnp.sum(e, axis=1, keepdims=True)


def _fwd_pallas(x2, mask2, scale, k, sq, causal, out_dtype):
    m_rows = x2.shape[0]
    kp = round_up(k, 128)
    bm = _block_rows(kp)
    grid = (cdiv(m_rows, bm),)
    pad = lambda a, v: jnp.pad(a, ((0, 0), (0, kp - k)), constant_values=v) if kp != k else a
    args = [pad(x2, 0)]
    in_specs = [pl.BlockSpec((bm, kp), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    if mask2 is not None:
        args.append(pad(mask2.astype(jnp.int8), 0))
        in_specs.append(pl.BlockSpec((bm, kp), lambda i: (i, 0), memory_space=pltpu.VMEM))

    def kernel(*refs):
        if mask2 is not None:
            x_ref, m_ref, y_ref = refs
            mask = m_ref[:] != 0
        else:
            x_ref, y_ref = refs
            mask = None
        row_offset = pl.program_id(0) * bm
        y = _fwd_body(x_ref[:], mask, scale, k, sq, causal, row_offset)
        y_ref[:] = y.astype(out_dtype)

    y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, kp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_rows, kp), out_dtype),
        interpret=pallas_interpret(),
    )(*args)
    return y[:, :k] if kp != k else y


def _fwd_jnp(x2, mask2, scale, k, sq, causal, out_dtype):
    logits = x2.astype(jnp.float32) * scale
    if mask2 is not None:
        logits = jnp.where(mask2, _MASK_FILL, logits)
    if causal:
        rows = x2.shape[0]
        q_pos = (jnp.arange(rows) % sq)[:, None]
        col = jnp.arange(k)[None, :]
        logits = jnp.where(col > q_pos, _MASK_FILL, logits)
    return jax.nn.softmax(logits, axis=-1).astype(out_dtype)


def _bwd_pallas(dy2, y2, scale, k):
    m_rows = dy2.shape[0]
    kp = round_up(k, 128)
    bm = _block_rows(kp)
    grid = (cdiv(m_rows, bm),)
    pad = lambda a: jnp.pad(a, ((0, 0), (0, kp - k))) if kp != k else a

    def kernel(dy_ref, y_ref, dx_ref):
        dy = dy_ref[:].astype(jnp.float32)
        y = y_ref[:].astype(jnp.float32)
        s = jnp.sum(dy * y, axis=1, keepdims=True)
        dx_ref[:] = (scale * y * (dy - s)).astype(dy_ref.dtype)

    dx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, kp), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, kp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, kp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_rows, kp), dy2.dtype),
        interpret=pallas_interpret(),
    )(pad(dy2), pad(y2))
    return dx[:, :k] if kp != k else dx


def _bwd_jnp(dy2, y2, scale, k):
    dy = dy2.astype(jnp.float32)
    y = y2.astype(jnp.float32)
    s = jnp.sum(dy * y, axis=1, keepdims=True)
    return (scale * y * (dy - s)).astype(dy2.dtype)


# ---------------------------------------------------------------------------
# custom-vjp core over flattened rows
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _softmax_core(x2, mask2, scale, sq, causal):
    k = x2.shape[-1]
    fwd = _fwd_pallas if use_pallas() else _fwd_jnp
    return fwd(x2, mask2, scale, k, sq, causal, x2.dtype)


def _core_fwd(x2, mask2, scale, sq, causal):
    y = _softmax_core(x2, mask2, scale, sq, causal)
    return y, y


def _core_bwd(scale, sq, causal, y, dy):
    k = y.shape[-1]
    bwd = _bwd_pallas if use_pallas() else _bwd_jnp
    return bwd(dy, y, scale, k), None


_softmax_core.defvjp(_core_fwd, _core_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def scaled_softmax(x: jax.Array, scale: float = 1.0) -> jax.Array:
    """``softmax(scale * x)`` (reference: ``csrc/megatron/scaled_softmax.cpp``)."""
    k = x.shape[-1]
    y = _softmax_core(x.reshape(-1, k), None, float(scale), 0, False)
    return y.reshape(x.shape)


def scaled_masked_softmax(x: jax.Array, mask: Optional[jax.Array],
                          scale: float = 1.0) -> jax.Array:
    """``softmax(scale * x.masked_fill(mask, -10000))``.

    ``x``: ``(b, np, sq, sk)``; ``mask``: broadcastable bool, True = masked out
    (reference: ``csrc/megatron/scaled_masked_softmax.cpp``).
    """
    if mask is None:
        return scaled_softmax(x, scale)
    k = x.shape[-1]
    mask_b = jnp.broadcast_to(mask, x.shape).reshape(-1, k)
    y = _softmax_core(x.reshape(-1, k), mask_b, float(scale), 0, False)
    return y.reshape(x.shape)


def scaled_upper_triang_masked_softmax(x: jax.Array, scale: float = 1.0) -> jax.Array:
    """Causal softmax over ``(attn_batches, sq, sk)`` with sq == sk
    (reference: ``csrc/megatron/scaled_upper_triang_masked_softmax.cpp``)."""
    sq, sk = x.shape[-2], x.shape[-1]
    if sq != sk:
        # the reference extension requires square attention scores; a
        # flattened row%sq mask would silently mis-align for sq != sk
        raise ValueError(
            f"scaled_upper_triang_masked_softmax requires sq == sk, got {sq} != {sk}; "
            "use scaled_masked_softmax with an explicit causal mask instead")
    y = _softmax_core(x.reshape(-1, sk), None, float(scale), sq, True)
    return y.reshape(x.shape)


def generic_scaled_masked_softmax(x: jax.Array, mask: Optional[jax.Array],
                                  scale: float = 1.0) -> jax.Array:
    """No shape constraints (reference: ``generic_scaled_masked_softmax.cpp``) —
    on TPU the main kernel already handles arbitrary row lengths."""
    return scaled_masked_softmax(x, mask, scale)
