"""Fused decode-step attention over a PAGED KV cache.

The serving engine's decode roofline (PERF.md, BENCH_r05) showed the
gap to the HBM read-bandwidth bound *growing* with batch — 78%/76%/65%
at bs 1/8/32 — which indicts the unfused chain, not the cache reads:
XLA's paged-cache gather materializes a ``[b, S, f]`` temporary (read
pool + write temp + re-read temp = ~3x the stream), and the per-slot
append is a separate scatter program. This module is the fused
alternative (PAPERS.md: "LLM Inference Acceleration via Efficient
Operation Fusion", arXiv 2502.17728; ClusterFusion++'s whole-block
decode fusion is the same territory):

- :func:`fused_paged_decode_attention` — ONE jitted region per decode
  step and layer: the new K/V rows land as a donated in-place scatter,
  and attention is a single VMEM-resident flash pass over the slot's
  mapped pages (Pallas kernel, page table scalar-prefetched so each
  page block DMAs straight from its pool row). The KV stream is read
  from HBM exactly once per step; the only HBM writes are the appended
  rows. No gathered-cache temporary exists in any memory space.

Two extensions raise the effective bandwidth ceiling past the PR 9
roofline (docs/serving.md#kv-quantization, #speculative-decoding):

- **Query windows** (``q`` rank 4): each slot appends and attends over
  ``w`` consecutive rows in one pass — the verify step of
  self-speculative decoding, which amortizes one read of the KV stream
  over up to ``w`` emitted tokens. ``w == 1`` reproduces the PR 9
  single-token step bit-for-bit (the window formulation degenerates to
  the same arrays and the same reduction order).
- **int8 pools with per-(page, kv-head) scales** (``k_scales`` /
  ``v_scales``): pages are the quantization blocks. Appends quantize
  with RESCALE-ON-APPEND — a page's scale only ever grows (scatter-max
  of the incoming rows' absmax), resident int8 rows are rescaled by
  ``old/new``, and the new rows quantize at the final scale — and the
  kernel dequantizes inline on the VMEM-resident block, so the HBM
  stream is half the bf16 bytes with no new read site.

Layouts (see docs/serving.md#paged-kv):

- pool: ``[n_pages, page_size, kv_heads * head_dim]`` per layer — the
  fused heads-minor dim keeps every page read full-lane, exactly like
  the flat cache's ``[b, S, h*d]`` form (PERF.md round 5), and is the
  dim :class:`~apex_tpu.serving.fleet.ShardedEngine` shards over the
  tensor axis.
- scale sidecar: ``[n_pages, kv_heads]`` float32 per pool — sharded
  ``P(None, tensor)`` so each rank's scales cover exactly its head
  slice (per-head absmax is rank-local under TP).
- page table: ``[b, pages_per_slot]`` int32, logical page ``j`` of slot
  ``r`` lives in pool row ``page_table[r, j]``; unmapped entries hold
  the out-of-range sentinel ``n_pages`` (reads clamp + mask, scatters
  drop). Window rows past the table's span also clamp to the sentinel,
  so an over-long window can never corrupt the slot's own last page.

Dispatch follows the repo convention (:mod:`apex_tpu.ops._support`):
the Pallas kernel on TPU (or under ``APEX_TPU_FORCE_PALLAS=interpret``
for CI parity), and a pure-``jnp`` reference elsewhere. The reference
reproduces the flat cache's single-token MXU formulation bit-for-bit on
the gathered logical view, so the paged engine stays TOKEN-EXACT
against the flat engine on CPU (the tier-1 parity bar); the kernel's
flash accumulation is validated against the reference to numerical
tolerance in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._support import cdiv, pallas_interpret, use_pallas

__all__ = ["fused_paged_decode_attention", "paged_pages_for",
           "paged_quant_fill", "paged_quant_scatter"]

#: the masked-score floor the flat decode path uses — shared so paged
#: and flat softmax see bitwise-identical masked entries
_NEG = -1e30

#: int8 quantization range: symmetric, -127..127 (keeping -128 out of
#: the code domain makes the scale exactly absmax/127 and negation
#: lossless)
_QMAX = 127.0


def paged_pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold ``tokens`` cache rows."""
    return cdiv(tokens, page_size)


def _window_dest(page_table, positions, w, page_size):
    """Scatter coordinates for a ``w``-row append window per slot:
    row ``t`` of slot ``r`` lands at logical position
    ``positions[r] + t``. Positions past the table's span map to the
    sentinel ``n_pages`` (a plain gather would CLAMP to the table's
    last column and corrupt the slot's own final page)."""
    b = page_table.shape[0]
    pps = page_table.shape[1]
    idx = positions[:, None] + jnp.arange(w)[None, :]        # [b, w]
    page_idx = idx // page_size
    dest_page = jnp.take_along_axis(
        page_table, jnp.clip(page_idx, 0, pps - 1), axis=1)
    dest_page = jnp.where(page_idx < pps, dest_page,
                          jnp.int32(2 ** 30))  # past any pool: drops
    return dest_page.astype(jnp.int32), (idx % page_size).astype(jnp.int32)


def _append_rows(pages, rows, page_table, positions, page_size):
    """Scatter each slot's ``w`` new rows at their cache positions.
    One window per slot; with the pool donated into the jitted step this
    compiles to in-place writes, never a pool copy. Unmapped sentinel
    entries (and window rows past the table) drop instead of corrupting
    a foreign page."""
    b, w, f = rows.shape
    dest_page, dest_row = _window_dest(page_table, positions, w, page_size)
    return pages.at[dest_page, dest_row].set(
        rows.astype(pages.dtype), mode="drop")


# -- int8 page quantization --------------------------------------------------


def paged_quant_scatter(pages, scales, rows, dest_page, dest_row):
    """Rescale-on-append row scatter into an int8 pool.

    ``rows`` ``[n, kv_heads * head_dim]`` land at
    ``(dest_page[i], dest_row[i])``; out-of-range ``dest_page`` drops
    the row (sentinel convention). Scale lifecycle: a page's per-kv-head
    scale MONOTONICALLY grows to cover the incoming rows' absmax
    (scatter-max), resident int8 rows of touched pages are rescaled by
    ``old/new`` (duplicate destinations write identical values, so the
    scatter stays deterministic), and the new rows quantize at the
    final scale. A zero scale means "nothing valid resident": the ratio
    rescale then zeroes whatever bits the recycled page held.

    Returns ``(pages, scales)``.
    """
    n_pages, ps, f = pages.shape
    kvh = scales.shape[1]
    dh = f // kvh
    rf = rows.astype(jnp.float32).reshape(-1, kvh, dh)
    want = jnp.max(jnp.abs(rf), axis=-1) / _QMAX             # [n, kvh]
    new_scales = scales.at[dest_page].max(want, mode="drop")
    cf = jnp.clip(dest_page, 0, n_pages - 1)
    ns = new_scales[cf]                                      # [n, kvh]
    safe = jnp.where(ns > 0.0, ns, 1.0)
    ratio = scales[cf] / safe                                # old/new <= 1
    resident = pages[cf].astype(jnp.float32) \
        * jnp.repeat(ratio, dh, axis=-1)[:, None, :]
    pages = pages.at[dest_page].set(
        jnp.clip(jnp.round(resident), -_QMAX, _QMAX).astype(pages.dtype),
        mode="drop")
    q = jnp.clip(jnp.round(rf / safe[:, :, None]), -_QMAX, _QMAX)
    pages = pages.at[dest_page, dest_row].set(
        q.reshape(-1, f).astype(pages.dtype), mode="drop")
    return pages, new_scales


def paged_quant_fill(pages, scales, chunks, dest_page):
    """Whole-page overwrite into an int8 pool (the prefill chunk path):
    ``chunks`` ``[n, page_size, f]`` REPLACE pages ``dest_page`` —
    content and scale alike (``.set``, not ``.max``: a freshly mapped
    page owes nothing to its previous occupant). Sentinel destinations
    drop. Returns ``(pages, scales)``."""
    n, ps, f = chunks.shape
    kvh = scales.shape[1]
    dh = f // kvh
    cf = chunks.astype(jnp.float32).reshape(n, ps, kvh, dh)
    amax = jnp.max(jnp.abs(cf), axis=(1, 3))                 # [n, kvh]
    scale = amax / _QMAX
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(cf / safe[:, None, :, None]), -_QMAX, _QMAX)
    pages = pages.at[dest_page].set(
        q.reshape(n, ps, f).astype(pages.dtype), mode="drop")
    scales = scales.at[dest_page].set(scale, mode="drop")
    return pages, scales


def _quant_append(pages, scales, rows, page_table, positions, page_size):
    """Windowed rescale-on-append: the int8 counterpart of
    :func:`_append_rows`."""
    b, w, f = rows.shape
    dest_page, dest_row = _window_dest(page_table, positions, w, page_size)
    return paged_quant_scatter(pages, scales, rows.reshape(b * w, f),
                               dest_page.reshape(-1), dest_row.reshape(-1))


def _dequant_view(pages_g, scales_g, dh, dtype):
    """Gathered int8 pages ``[b, pps, ps, f]`` + gathered scales
    ``[b, pps, kvh]`` -> dequantized ``[b, pps, ps, f]`` in ``dtype``."""
    sc = jnp.repeat(scales_g, dh, axis=-1)[:, :, None, :]    # [b,pps,1,f]
    return (pages_g.astype(jnp.float32) * sc).astype(dtype)


# -- reference path (CPU / pallas off) ---------------------------------------


def _reference(q, k_new, v_new, k_pages, v_pages, k_scales, v_scales,
               page_table, positions, group, sliding_window):
    """Gathered-view reference: append, then run the flat cache's
    single-token MXU formulation (transformer._flat_cache_attention,
    ``s == 1`` branch) over the logical ``[b, S, f]`` view
    ``pool[page_table]``, with the ``w`` window queries folded into the
    query-head axis (every einsum reduction is per-query-column
    independent, so ``w`` windowed queries are bitwise-identical to
    ``w`` sequential single-row calls — and ``w == 1`` is the PR 9
    reference unchanged). Real rows see the exact same operand values
    and reduction order as the flat path (padded rows mask to exact
    zeros), so flat-vs-paged engine parity is bitwise, not approximate."""
    n_pages, page_size, f = k_pages.shape
    b, w, hl, dh = q.shape
    kvh = f // dh
    if k_scales is not None:
        k_pages, k_scales = _quant_append(
            k_pages, k_scales, k_new, page_table, positions, page_size)
        v_pages, v_scales = _quant_append(
            v_pages, v_scales, v_new, page_table, positions, page_size)
    else:
        k_pages = _append_rows(k_pages, k_new, page_table, positions,
                               page_size)
        v_pages = _append_rows(v_pages, v_new, page_table, positions,
                               page_size)
    pt = jnp.minimum(page_table, n_pages - 1)     # clamp sentinels (masked)
    if k_scales is not None:
        ck = _dequant_view(k_pages[pt], k_scales[pt], dh, q.dtype)
        cv = _dequant_view(v_pages[pt], v_scales[pt], dh, q.dtype)
        ck = ck.reshape(b, -1, f)
        cv = cv.reshape(b, -1, f)
    else:
        ck = k_pages[pt].reshape(b, -1, f)
        cv = v_pages[pt].reshape(b, -1, f)
    S = ck.shape[1]
    slots = jnp.arange(S)
    # per-query validity: window query t of slot r covers logical rows
    # [0, positions[r] + t]
    t = (jnp.arange(w * hl) // hl)[None, None, :]
    lim = positions[:, None, None] + t
    invalid = slots[None, :, None] > lim
    if sliding_window is not None:
        invalid = jnp.logical_or(
            invalid, slots[None, :, None] <= lim - sliding_window)
    inv_scale = jnp.sqrt(jnp.asarray(dh, jnp.float32)).astype(q.dtype)
    # K stream through one MXU GEMM per batch (Qblock holds each query
    # head's vector in its K/V head's row block, zeros elsewhere) — the
    # same full-lane formulation as the flat path
    qq = q.reshape(b, w * hl, dh)
    q_tiled = jnp.tile(qq.transpose(0, 2, 1), (1, kvh, 1))
    frow = jnp.arange(kvh * dh)[:, None]
    jcol = jnp.arange(w * hl)[None, :]
    blockmask = (frow // dh == (jcol % hl) // group).astype(q.dtype)
    qblock = q_tiled * blockmask                           # [b, f, w*hl]
    scores = jnp.einsum("bsf,bfh->bsh", ck.astype(q.dtype),
                        qblock) / inv_scale                # [b, S, w*hl]
    sf = jnp.where(invalid, jnp.asarray(_NEG, jnp.float32),
                   scores.astype(jnp.float32))
    sf = sf - jnp.max(sf, axis=1, keepdims=True)
    e = jnp.exp(sf)
    probs = (e / jnp.sum(e, axis=1, keepdims=True)).astype(q.dtype)
    ctx_big = jnp.einsum("bsh,bsf->bhf", probs, cv.astype(q.dtype))
    sel = (jnp.arange(kvh)[None, :]
           == (jnp.arange(hl) // group)[:, None]).astype(q.dtype)
    ctx = jnp.einsum("bwjkd,jk->bwjd",
                     ctx_big.reshape(b, w, hl, kvh, dh), sel)
    return ctx.reshape(b, w, hl * dh), k_pages, v_pages, k_scales, v_scales


# -- Pallas kernel -----------------------------------------------------------


def _decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                   page_size, group, window, quantized, sliding_window):
    """One (slot, page-block) grid cell of the streaming decode pass.

    The page table is scalar-prefetched, so block ``(r, j)``'s K/V page
    DMAs directly from pool row ``page_table[r, j]`` into VMEM — the
    gather never exists as an array. Softmax is the standard flash
    recurrence over page blocks (running max / normalizer / weighted
    accumulator in VMEM scratch, carried across the slot's inner grid
    iterations); the final block rescales and writes the context rows.
    The ``window`` query rows fold into the per-kv-head query block
    (``group * window`` rows), each masked to its own validity limit
    ``pos + t``. Quantized pools dequantize the VMEM-resident block
    in-register from the gathered per-page scales — HBM still streams
    int8. Pages past the slot's valid length are skipped (their DMA is
    the residual cost of the rectangular grid — ~one page per slot in
    steady state since the engine allocates pages on demand)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    r = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[r]                  # first window row's append index

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j * page_size <= pos + (window - 1))
    def _accumulate():
        w, hl, dh = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
        kvh = hl // group
        # [w, kvh, group, dh] -> [kvh, w*group, dh]: per-kv-head query
        # block with the window folded in
        qh = q_ref[0].reshape(w, kvh, group, dh).transpose(1, 0, 2, 3) \
            .reshape(kvh, w * group, dh).astype(jnp.float32)
        kb = k_ref[0].reshape(page_size, kvh, dh).astype(jnp.float32)
        vb = v_ref[0].reshape(page_size, kvh, dh).astype(jnp.float32)
        if quantized:
            kb = kb * ks_ref[0, 0][None, :, None]
            vb = vb * vs_ref[0, 0][None, :, None]
        s_blk = jax.lax.dot_general(
            qh, kb, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [kvh, w*group, ps]
        s_blk = s_blk / jnp.sqrt(jnp.float32(dh))
        row = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        t = jax.lax.broadcasted_iota(
            jnp.int32, (1, w * group, 1), 1) // group
        lim = pos + t
        invalid = row > lim
        if sliding_window is not None:
            invalid = jnp.logical_or(invalid, row <= lim - sliding_window)
        s_blk = jnp.where(invalid, _NEG, s_blk)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_blk - m_new[..., None])    # [kvh, w*group, ps]
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, vb, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [kvh, w*group, dh]
        acc_ref[...] = acc_ref[...] * alpha[..., None] + pv
        m_ref[...] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        w, hl, dh = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
        kvh = hl // group
        # l > 0 for every real window row: row `pos + t` itself is valid
        # by construction (garbage rows past the slot's window are
        # normalized over whatever survived the mask — the engine never
        # reads them)
        l = jnp.where(l_ref[...] > 0.0, l_ref[...], 1.0)
        ctx = acc_ref[...] / l[..., None]        # [kvh, w*group, dh]
        ctx = ctx.reshape(kvh, w, group, dh).transpose(1, 0, 2, 3)
        o_ref[...] = ctx.reshape(1, w, hl * dh).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("group", "sliding_window"))
def _pallas(q, k_new, v_new, k_pages, v_pages, k_scales, v_scales,
            page_table, positions, group, sliding_window):
    n_pages, page_size, f = k_pages.shape
    b, w, hl, dh = q.shape
    kvh = f // dh
    pages_per_slot = page_table.shape[1]
    # append first (donated in-place row writes); the kernel then
    # streams pages that already contain the new rows — one read of the
    # stream, w rows written, no ordering hazard (the rows' pages are
    # mapped)
    quantized = k_scales is not None
    if quantized:
        k_pages, k_scales = _quant_append(
            k_pages, k_scales, k_new, page_table, positions, page_size)
        v_pages, v_scales = _quant_append(
            v_pages, v_scales, v_new, page_table, positions, page_size)
    else:
        k_pages = _append_rows(k_pages, k_new, page_table, positions,
                               page_size)
        v_pages = _append_rows(v_pages, v_new, page_table, positions,
                               page_size)
    pt = jnp.minimum(page_table, n_pages - 1).astype(jnp.int32)

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, group=group, window=w,
        quantized=quantized, sliding_window=sliding_window)
    in_specs = [
        pl.BlockSpec((1, w, hl, dh), lambda r, j, pt, pos: (r, 0, 0, 0)),
        pl.BlockSpec((1, page_size, f),
                     lambda r, j, pt, pos: (pt[r, j], 0, 0)),
        pl.BlockSpec((1, page_size, f),
                     lambda r, j, pt, pos: (pt[r, j], 0, 0)),
    ]
    inputs = [pt, positions.astype(jnp.int32), q, k_pages, v_pages]
    if quantized:
        # per-page scales, pre-gathered to the table layout so block
        # (r, j) reads its own page's row — tiny f32 sidecar next to
        # the int8 stream
        in_specs += [
            pl.BlockSpec((1, 1, kvh), lambda r, j, pt, pos: (r, j, 0)),
            pl.BlockSpec((1, 1, kvh), lambda r, j, pt, pos: (r, j, 0)),
        ]
        inputs += [k_scales[pt], v_scales[pt]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages_per_slot),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, w, hl * dh),
                               lambda r, j, pt, pos: (r, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, w * group), jnp.float32),      # running max
            pltpu.VMEM((kvh, w * group), jnp.float32),      # normalizer
            pltpu.VMEM((kvh, w * group, dh), jnp.float32),  # weighted acc
        ])
    ctx = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, w, hl * dh), q.dtype),
        interpret=pallas_interpret(),
    )(*inputs)
    return ctx, k_pages, v_pages, k_scales, v_scales


def fused_paged_decode_attention(q, k_new, v_new, k_pages, v_pages,
                                 page_table, positions, *,
                                 queries_per_group: int = 1,
                                 sliding_window=None,
                                 k_scales=None, v_scales=None):
    """One fused decode step for one layer over the paged KV pool.

    Args:
      q: ``[b, local_heads, head_dim]`` (single-token decode) or
        ``[b, w, local_heads, head_dim]`` (a ``w``-row verify window —
        speculative decoding) — query vectors, rope already applied.
      k_new, v_new: ``[b, kv_heads * head_dim]`` (or
        ``[b, w, kv_heads * head_dim]``) — this step's K/V rows.
      k_pages, v_pages: ``[n_pages, page_size, kv_heads * head_dim]`` —
        the layer's page pool (bf16/f32, or int8 with scales).
      page_table: ``[b, pages_per_slot]`` int32 — pool rows backing each
        slot's logical pages; unmapped entries hold the sentinel
        ``n_pages``.
      positions: ``[b]`` int32 — each slot's append index (tokens
        already cached). Window row ``t`` lands at ``positions[r] + t``
        — its page MUST be mapped for rows the engine will read back
        (rows past the table clamp to the sentinel and drop) — and
        window query ``t`` attends over logical rows
        ``[0, positions[r] + t]``.
      queries_per_group: query heads per K/V head (GQA/MQA grouping).
      sliding_window: optional Mistral-style local-attention window.
      k_scales, v_scales: ``[n_pages, kv_heads]`` float32 per-page
        scale sidecars — REQUIRED with int8 pools, forbidden otherwise.

    Returns ``(ctx, k_pages, v_pages)`` — plus ``k_scales, v_scales``
    when quantized. ``ctx`` is ``[b, local_heads * head_dim]`` for
    rank-3 ``q``, else ``[b, w, local_heads * head_dim]``.
    """
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
        k_new = k_new[:, None]
        v_new = v_new[:, None]
    if q.ndim != 4:
        raise ValueError(
            f"q must be [b, heads, head_dim] or [b, w, heads, head_dim], "
            f"got {q.shape}")
    if k_pages.ndim != 3 or k_pages.shape != v_pages.shape:
        raise ValueError(
            f"pools must be matching [n_pages, page_size, kv_heads * "
            f"head_dim], got {k_pages.shape} / {v_pages.shape}")
    b, w, hl, dh = q.shape
    if hl % queries_per_group:
        raise ValueError(
            f"heads ({hl}) not divisible by queries_per_group "
            f"({queries_per_group})")
    kvh = hl // queries_per_group
    if k_pages.shape[-1] != kvh * dh:
        raise ValueError(
            f"pool minor dim {k_pages.shape[-1]} != kv_heads * head_dim "
            f"({kvh} * {dh})")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    if (k_pages.dtype == jnp.int8) != (k_scales is not None):
        raise ValueError(
            f"int8 pools need scale sidecars (and only int8 pools take "
            f"them); pool dtype {k_pages.dtype}, "
            f"scales {'set' if k_scales is not None else 'None'}")
    if k_scales is not None and k_scales.shape != (k_pages.shape[0], kvh):
        raise ValueError(
            f"scales must be [n_pages, kv_heads] = "
            f"({k_pages.shape[0]}, {kvh}), got {k_scales.shape}")
    fn = _pallas if use_pallas() else _reference
    ctx, k_pages, v_pages, k_scales, v_scales = fn(
        q, k_new, v_new, k_pages, v_pages, k_scales, v_scales,
        page_table, positions, queries_per_group, sliding_window)
    if squeeze:
        ctx = ctx[:, 0]
    if k_scales is None:
        return ctx, k_pages, v_pages
    return ctx, k_pages, v_pages, k_scales, v_scales
