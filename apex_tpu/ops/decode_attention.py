"""Fused decode-step attention over a PAGED KV cache.

The serving engine's decode roofline (PERF.md, BENCH_r05) showed the
gap to the HBM read-bandwidth bound *growing* with batch — 78%/76%/65%
at bs 1/8/32 — which indicts the unfused chain, not the cache reads:
XLA's paged-cache gather materializes a ``[b, S, f]`` temporary (read
pool + write temp + re-read temp = ~3x the stream), and the per-slot
append is a separate scatter program. This module is the fused
alternative (PAPERS.md: "LLM Inference Acceleration via Efficient
Operation Fusion", arXiv 2502.17728; ClusterFusion++'s whole-block
decode fusion is the same territory):

- :func:`fused_paged_decode_attention` — ONE jitted region per decode
  step and layer: the new K/V row lands as a donated in-place one-row
  scatter, and attention is a single VMEM-resident flash pass over the
  slot's mapped pages (Pallas kernel, page table scalar-prefetched so
  each page block DMAs straight from its pool row). The KV stream is
  read from HBM exactly once per step; the only HBM write is the
  appended row. No gathered-cache temporary exists in any memory space.

Layouts (see docs/serving.md#paged-kv):

- pool: ``[n_pages, page_size, kv_heads * head_dim]`` per layer — the
  fused heads-minor dim keeps every page read full-lane, exactly like
  the flat cache's ``[b, S, h*d]`` form (PERF.md round 5), and is the
  dim :class:`~apex_tpu.serving.fleet.ShardedEngine` shards over the
  tensor axis.
- page table: ``[b, pages_per_slot]`` int32, logical page ``j`` of slot
  ``r`` lives in pool row ``page_table[r, j]``; unmapped entries hold
  the out-of-range sentinel ``n_pages`` (reads clamp + mask, scatters
  drop).

Dispatch follows the repo convention (:mod:`apex_tpu.ops._support`):
the Pallas kernel on TPU (or under ``APEX_TPU_FORCE_PALLAS=interpret``
for CI parity), and a pure-``jnp`` reference elsewhere. The reference
reproduces the flat cache's single-token MXU formulation bit-for-bit on
the gathered logical view, so the paged engine stays TOKEN-EXACT
against the flat engine on CPU (the tier-1 parity bar); the kernel's
flash accumulation is validated against the reference to numerical
tolerance in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._support import cdiv, pallas_interpret, use_pallas

__all__ = ["fused_paged_decode_attention", "paged_pages_for"]

#: the masked-score floor the flat decode path uses — shared so paged
#: and flat softmax see bitwise-identical masked entries
_NEG = -1e30


def paged_pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold ``tokens`` cache rows."""
    return cdiv(tokens, page_size)


def _append_rows(pages, rows, page_table, positions, page_size):
    """Scatter each slot's new row at its own cache position:
    ``pages[page_table[r, p // page_size], p % page_size] = rows[r]``.
    One row per slot; with the pool donated into the jitted step this
    compiles to an in-place write, never a pool copy. Unmapped sentinel
    entries (engine bug) drop instead of corrupting a foreign page."""
    b = rows.shape[0]
    dest_page = page_table[jnp.arange(b), positions // page_size]
    dest_row = positions % page_size
    return pages.at[dest_page, dest_row].set(
        rows.astype(pages.dtype), mode="drop")


# -- reference path (CPU / pallas off) ---------------------------------------


def _reference(q, k_new, v_new, k_pages, v_pages, page_table, positions,
               group, sliding_window):
    """Gathered-view reference: append, then run the flat cache's
    single-token MXU formulation (transformer._flat_cache_attention,
    ``s == 1`` branch) over the logical ``[b, S, f]`` view
    ``pool[page_table]``. Real rows see the exact same operand values
    and reduction order as the flat path (padded rows mask to exact
    zeros), so flat-vs-paged engine parity is bitwise, not approximate."""
    n_pages, page_size, f = k_pages.shape
    b, hl, dh = q.shape
    kvh = f // dh
    k_pages = _append_rows(k_pages, k_new, page_table, positions, page_size)
    v_pages = _append_rows(v_pages, v_new, page_table, positions, page_size)
    pt = jnp.minimum(page_table, n_pages - 1)     # clamp sentinels (masked)
    ck = k_pages[pt].reshape(b, -1, f)
    cv = v_pages[pt].reshape(b, -1, f)
    S = ck.shape[1]
    slots = jnp.arange(S)[None, :]
    invalid = slots > positions[:, None]
    if sliding_window is not None:
        invalid = jnp.logical_or(
            invalid, slots <= positions[:, None] - sliding_window)
    inv_scale = jnp.sqrt(jnp.asarray(dh, jnp.float32)).astype(q.dtype)
    # K stream through one MXU GEMM per batch (Qblock holds each query
    # head's vector in its K/V head's row block, zeros elsewhere) — the
    # same full-lane formulation as the flat path
    q_tiled = jnp.tile(q.transpose(0, 2, 1), (1, kvh, 1))
    frow = jnp.arange(kvh * dh)[:, None]
    jcol = jnp.arange(hl)[None, :]
    blockmask = (frow // dh == jcol // group).astype(q.dtype)
    qblock = q_tiled * blockmask                           # [b, f, hl]
    scores = jnp.einsum("bsf,bfh->bsh", ck.astype(q.dtype),
                        qblock) / inv_scale                # [b, S, hl]
    sf = jnp.where(invalid[:, :, None], jnp.asarray(_NEG, jnp.float32),
                   scores.astype(jnp.float32))
    sf = sf - jnp.max(sf, axis=1, keepdims=True)
    e = jnp.exp(sf)
    probs = (e / jnp.sum(e, axis=1, keepdims=True)).astype(q.dtype)
    ctx_big = jnp.einsum("bsh,bsf->bhf", probs, cv.astype(q.dtype))
    sel = (jnp.arange(kvh)[None, :]
           == (jnp.arange(hl) // group)[:, None]).astype(q.dtype)
    ctx = jnp.einsum("bjkd,jk->bjd", ctx_big.reshape(b, hl, kvh, dh), sel)
    return ctx.reshape(b, hl * dh), k_pages, v_pages


# -- Pallas kernel -----------------------------------------------------------


def _decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, page_size, group,
                   sliding_window):
    """One (slot, page-block) grid cell of the streaming decode pass.

    The page table is scalar-prefetched, so block ``(r, j)``'s K/V page
    DMAs directly from pool row ``page_table[r, j]`` into VMEM — the
    gather never exists as an array. Softmax is the standard flash
    recurrence over page blocks (running max / normalizer / weighted
    accumulator in VMEM scratch, carried across the slot's inner grid
    iterations); the final block rescales and writes the context row.
    Pages past the slot's valid length are skipped (their DMA is the
    residual cost of the rectangular grid — ~one page per slot in
    steady state since the engine allocates pages on demand)."""
    r = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[r]                         # append index == last valid

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j * page_size <= pos)
    def _accumulate():
        hl, dh = q_ref.shape[1], q_ref.shape[2]
        kvh = hl // group
        qh = q_ref[0].reshape(kvh, group, dh).astype(jnp.float32)
        kb = k_ref[0].reshape(page_size, kvh, dh).astype(jnp.float32)
        vb = v_ref[0].reshape(page_size, kvh, dh).astype(jnp.float32)
        s_blk = jax.lax.dot_general(
            qh, kb, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [kvh, group, page_size]
        s_blk = s_blk / jnp.sqrt(jnp.float32(dh))
        row = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        invalid = row > pos
        if sliding_window is not None:
            invalid = jnp.logical_or(invalid, row <= pos - sliding_window)
        s_blk = jnp.where(invalid, _NEG, s_blk)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_blk - m_new[..., None])    # [kvh, group, page_size]
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, vb, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)  # [kvh, group, dh]
        acc_ref[...] = acc_ref[...] * alpha[..., None] + pv
        m_ref[...] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        hl, dh = q_ref.shape[1], q_ref.shape[2]
        # l > 0 always: position `pos` itself is valid by construction
        ctx = acc_ref[...] / l_ref[...][..., None]
        o_ref[...] = ctx.reshape(1, hl * dh).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("group", "sliding_window"))
def _pallas(q, k_new, v_new, k_pages, v_pages, page_table, positions,
            group, sliding_window):
    n_pages, page_size, f = k_pages.shape
    b, hl, dh = q.shape
    kvh = f // dh
    pages_per_slot = page_table.shape[1]
    # append first (donated in-place row write); the kernel then streams
    # pages that already contain the new row — one read of the stream,
    # one row written, no ordering hazard (the row's page is mapped)
    k_pages = _append_rows(k_pages, k_new, page_table, positions, page_size)
    v_pages = _append_rows(v_pages, v_new, page_table, positions, page_size)
    pt = jnp.minimum(page_table, n_pages - 1).astype(jnp.int32)

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, group=group,
        sliding_window=sliding_window)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages_per_slot),
        in_specs=[
            pl.BlockSpec((1, hl, dh), lambda r, j, pt, pos: (r, 0, 0)),
            pl.BlockSpec((1, page_size, f),
                         lambda r, j, pt, pos: (pt[r, j], 0, 0)),
            pl.BlockSpec((1, page_size, f),
                         lambda r, j, pt, pos: (pt[r, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hl * dh), lambda r, j, pt, pos: (r, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, group), jnp.float32),       # running max
            pltpu.VMEM((kvh, group), jnp.float32),       # normalizer
            pltpu.VMEM((kvh, group, dh), jnp.float32),   # weighted acc
        ])
    ctx = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hl * dh), q.dtype),
        interpret=pallas_interpret(),
    )(pt, positions.astype(jnp.int32), q, k_pages, v_pages)
    return ctx, k_pages, v_pages


def fused_paged_decode_attention(q, k_new, v_new, k_pages, v_pages,
                                 page_table, positions, *,
                                 queries_per_group: int = 1,
                                 sliding_window=None):
    """One fused decode step for one layer over the paged KV pool.

    Args:
      q: ``[b, local_heads, head_dim]`` — this step's query vectors
        (one token per slot, rope already applied).
      k_new, v_new: ``[b, kv_heads * head_dim]`` — this step's K/V rows.
      k_pages, v_pages: ``[n_pages, page_size, kv_heads * head_dim]`` —
        the layer's page pool.
      page_table: ``[b, pages_per_slot]`` int32 — pool rows backing each
        slot's logical pages; unmapped entries hold the sentinel
        ``n_pages``.
      positions: ``[b]`` int32 — each slot's append index (tokens
        already cached). The new row lands at ``positions[r]`` — its
        page MUST be mapped (the engine allocates on demand before the
        step) — and attention covers logical rows ``[0, positions[r]]``.
      queries_per_group: query heads per K/V head (GQA/MQA grouping).
      sliding_window: optional Mistral-style local-attention window.

    Returns ``(ctx [b, local_heads * head_dim], k_pages, v_pages)`` —
    the context rows and the pools with the new rows appended.
    """
    if q.ndim != 3:
        raise ValueError(f"q must be [b, heads, head_dim], got {q.shape}")
    if k_pages.ndim != 3 or k_pages.shape != v_pages.shape:
        raise ValueError(
            f"pools must be matching [n_pages, page_size, kv_heads * "
            f"head_dim], got {k_pages.shape} / {v_pages.shape}")
    b, hl, dh = q.shape
    if hl % queries_per_group:
        raise ValueError(
            f"heads ({hl}) not divisible by queries_per_group "
            f"({queries_per_group})")
    if k_pages.shape[-1] != (hl // queries_per_group) * dh:
        raise ValueError(
            f"pool minor dim {k_pages.shape[-1]} != kv_heads * head_dim "
            f"({hl // queries_per_group} * {dh})")
    fn = _pallas if use_pallas() else _reference
    return fn(q, k_new, v_new, k_pages, v_pages, page_table,
              positions, queries_per_group, sliding_window)
