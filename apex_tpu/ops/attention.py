"""Flash attention — Pallas TPU kernels with custom VJP.

Capability parity with the reference's fused attention extensions:

- ``fmha`` (``apex/contrib/fmha/fmha.py:33-90``, kernels under
  ``apex/contrib/csrc/fmha/``): BERT-style fused multi-head attention,
  padded/varlen batches, seq <= 512.
- ``fast_multihead_attn`` (``apex/contrib/multihead_attn/*.py``): fused
  self/encdec attention fwd/bwd built from strided-batched GEMMs + fused
  softmax (``softmax.cuh``).

The TPU design is *not* a port of those kernels: it is an online-softmax
(flash) attention tiled for the MXU, O(sq) memory, with no sequence-length
cap (the CUDA kernels cap at 512/16k). The backward recomputes attention
probabilities blockwise (the standard flash backward), trading FLOPs for HBM
traffic — the right trade on TPU where HBM bandwidth is the bottleneck.

Layout: ``[batch, heads, seq, head_dim]``; accumulation in fp32 regardless of
input dtype (matching the CUDA kernels' fp32 softmax accumulators).

Masking supports the reference's two modes: ``causal`` (upper-triangular,
``scaled_upper_triang_masked_softmax`` semantics with the usual
``sk - sq`` offset for cross/incremental attention) and per-batch valid
key/value lengths ``kv_lengths`` (the fmha varlen/padded-batch capability,
``fmha.py:41-56``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._support import (pallas_interpret, round_up,
                                   tpu_compiler_params, use_pallas)

__all__ = ["flash_attention", "flash_attention_packed",
           "packed_attention_supported", "flash_chunk_fwd",
           "flash_chunk_bwd"]

_NEG_INF = -1e30
# lse sentinel for fully-masked (padding) query rows: exp(s - BIG) == 0 in the
# backward recompute, so padded rows contribute nothing to dk/dv.
_LSE_PAD = 1e30

# Tuned on TPU v5e (fwd+bwd, causal, head_dim 64): (1024, 1024) wins for
# every key length >= 1024 — in-jit chained microbenches (round 4) measure
# it 25-30% faster than (512, 512) at s=1k/2k/4k (grid-step overhead and
# softmax VPU work amortize over bigger blocks) and 1.47x faster at 32k
# (PERF.md round 3). Round 3's "(512,512) optimum for 1k-4k" was an
# artifact of dispatch-overhead-polluted timing. Below 1k keys the
# (512, 512) default stays: call sites clamp blocks to the (rounded)
# sequence anyway, so the gate's effect is keeping the measured
# power-of-two tiles rather than unmeasured clamped odd sizes.
# (1024, 2048) exceeds the 16MB scoped-vmem budget.
_DEFAULT_BLOCK_Q = 512
_DEFAULT_BLOCK_K = 512
_LONG_SEQ = 1024
_LONG_BLOCK = 1024


def _auto_blocks(block_q, block_k, sk):
    """Resolve None block sizes by key length (see tuning note above).
    The long-seq upgrade applies only when the caller specified neither
    block: auto-completing one side of an explicit choice could assemble
    an over-VMEM pair like (1024, 2048)."""
    if block_q is None and block_k is None and sk >= _LONG_SEQ:
        return _LONG_BLOCK, _LONG_BLOCK
    return (block_q or _DEFAULT_BLOCK_Q), (block_k or _DEFAULT_BLOCK_K)


def _mask_block(s, i, j, bq, bk, sk, kvl, causal, window, q_off, k_off):
    """Mask a (bq, bk) logit block; returns (masked logits, validity).

    Positions are GLOBAL: query row ``r`` sits at ``r + q_off``, key column
    ``c`` at ``c + k_off``. Plain (single-chunk) attention passes
    ``q_off = sk - sq, k_off = 0``, reproducing the standard causal offset;
    context-parallel ring chunks pass ``q_off = rank*sc, k_off = j*sc`` so
    cross-chunk causality, sliding windows, and varlen limits are exact
    across shard boundaries. ``kvl`` (valid key length) is in global
    positions. ``window``: keep the last ``window`` keys incl. self
    (requires causal)."""
    row_g = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * bq + q_off
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * bk
    col_g = col + k_off
    valid = col < sk                       # local K padding bound
    if kvl is not None:
        valid = jnp.logical_and(valid, col_g < kvl)
    if causal:
        valid = jnp.logical_and(valid, col_g <= row_g)
    if window is not None:
        valid = jnp.logical_and(valid, col_g > row_g - window)
    return jnp.where(valid, s, _NEG_INF), valid


def _causal_block_skip(i, j, bq, bk, causal, window, q_off, k_off):
    """True when k-block j has at least one unmasked column for q-block i
    (below the causal diagonal AND, with a sliding window, not entirely in
    the masked-out far past — the skipped far-past blocks are what makes
    window attention O(s*window) instead of O(s^2), and what makes
    fully-future ring chunks near-free). Offsets as in :func:`_mask_block`;
    with traced offsets (ring chunks) the result is a traced bool for
    ``pl.when``."""
    keep = True
    if causal:
        keep = j * bk + k_off <= i * bq + bq - 1 + q_off
    if window is not None:
        keep = jnp.logical_and(
            keep, j * bk + bk - 1 + k_off > i * bq + q_off - window)
    return keep


def _causal_block_full(i, j, bq, bk, causal, q_off, k_off):
    """True when EVERY element of block (i, j) is causally valid (the
    block sits entirely on/below the diagonal): its mask arithmetic —
    two iotas, compares, selects over bq x bk elements — can be skipped.
    At long sequence almost every live block is interior (32k at
    (1024,1024): 496 of 528), and the mask was ~4 of the ~9 VPU ops per
    softmax element (round 5). Callers must separately establish that no
    window/varlen/key-padding mask applies."""
    if not causal:
        return True
    return j * bk + bk - 1 + k_off <= i * bq + q_off


def _when_blocks(step, keep, i, j, bq, bk, causal, window, have_kvl, pad,
                 q_off, k_off):
    """The one block-dispatch gate every flash kernel (fwd/dq/dkv) shares:
    ``step(masked)`` returns the kernel-body thunk with or without mask
    arithmetic; live interior causal blocks run the unmasked variant (see
    :func:`_causal_block_full`), everything else the masked one, and
    ``keep`` (the caller's :func:`_causal_block_skip`, possibly clamped
    for banded grids) gates liveness. Single-sourced so forward and
    backward masking can never desynchronize."""
    if causal or window is not None:
        if causal and window is None and not have_kvl and not pad:
            full = _causal_block_full(i, j, bq, bk, causal, q_off, k_off)
            pl.when(jnp.logical_and(keep, full))(step(False))
            pl.when(jnp.logical_and(keep, jnp.logical_not(full)))(
                step(True))
        else:
            pl.when(keep)(step(True))
    elif have_kvl or pad:
        step(True)()
    else:
        step(False)()


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_single_kernel(offs_ref, kvl_ref, q_ref, k_ref, v_ref, o_ref,
                       lse_ref, *, scale, bq, bk, sk, causal, window,
                       need_mask):
    """One-pass forward for the single-block case (sq <= bq and sk <= bk):
    plain max/exp/sum softmax with no m/l/acc scratch, no online-softmax
    rescale, and — when ``need_mask`` is statically False (non-causal, no
    window/varlen, keys unpadded) — no mask arithmetic at all. At short
    sequence the general kernel's per-grid-step bookkeeping dominates:
    BERT-shape (16,12,512,64) fwd measured 468 us against a 65 us FLOP
    bound, almost all of it scratch init + masking + rescale overhead
    across 192 one-block cells (round 5); this kernel removes it."""
    b = pl.program_id(0)
    q_off, k_off = offs_ref[0], offs_ref[1]

    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if need_mask:
            kvl = kvl_ref[b] if kvl_ref is not None else None
            s, valid = _mask_block(s, 0, 0, bq, bk, sk, kvl, causal, window,
                                   q_off, k_off)
            m = jnp.max(s, axis=1, keepdims=True)
            p = jnp.where(valid, jnp.exp(s - m), 0.0)
        else:
            m = jnp.max(s, axis=1, keepdims=True)
            p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        o = jax.lax.dot(p.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
        o = o * jnp.where(l > 0, 1.0 / l, 0.0)
        o_ref[0, 0] = o.astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(l), _LSE_PAD)
        lse_ref[0, 0] = jnp.broadcast_to(lse.T, lse_ref.shape[2:])

    if causal or window is not None:
        # fully-masked chunks (ring hops entirely in the causal future)
        # stay near-free, mirroring _dqkv_single_kernel
        keep = _causal_block_skip(0, 0, bq, bk, causal, window,
                                  q_off, k_off)
        pl.when(keep)(_compute)

        @pl.when(jnp.logical_not(keep))
        def _masked_out():
            o_ref[0, 0] = jnp.zeros_like(o_ref[0, 0])
            lse_ref[0, 0] = jnp.full_like(lse_ref[0, 0], _LSE_PAD)
    else:
        _compute()


def _single_need_mask(causal, window, kv_lengths, skp, sk):
    """Whether a single-block kernel needs mask arithmetic at all. Shared
    by the fwd and bwd dispatches — they MUST agree or the backward
    recompute diverges from the forward silently."""
    return (causal or window is not None or kv_lengths is not None
            or skp != sk)


def _run_fwd_single(q, k, v, kv_lengths, scale, causal, sq, sk, bq, bk,
                    group, window, q_off, k_off):
    """Single-block forward dispatch — see _fwd_single_kernel."""
    batch, heads, sqp, dp = q.shape
    need_mask = _single_need_mask(causal, window, kv_lengths, k.shape[2], sk)
    kvl_spec = []
    args = [_offsets(q_off, k_off, sq, sk)]
    if kv_lengths is not None:
        kvl_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(kv_lengths.astype(jnp.int32))
    o, lse = pl.pallas_call(
        _wrap_kernel(_fwd_single_kernel, kv_lengths, scale=scale, bq=bq,
                     bk=bk, sk=sk, causal=causal, window=window,
                     need_mask=need_mask),
        grid=(batch, heads),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + kvl_spec + [
            pl.BlockSpec((1, 1, bq, dp), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bk, dp), lambda b, h: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, bk, dp), lambda b, h: (b, h // group, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, dp), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h: (b, h, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, sqp, dp), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, 1, sqp), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=pallas_interpret(),
    )(*args, q, k, v)
    return o, lse[:, :, 0, :]


def _win_j_base(i, bq, bk, qoff_static, window):
    """First k-block that can intersect q-block ``i``'s window band (static
    offsets only — the banded-grid fast path for sliding windows)."""
    lo = i * bq + qoff_static - window + 1
    return jnp.maximum(lo // bk, 0)


def _win_i_base(j, bq, bk, qoff_static, window):
    """First q-block whose window band can reach k-block ``j``."""
    lo = j * bk - qoff_static
    return jnp.maximum(lo // bq, 0)


def _fwd_kernel(offs_ref, kvl_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, bq, bk, nk, sk,
                causal, window=None, win_grid=None):
    b, i, jl = pl.program_id(0), pl.program_id(2), pl.program_id(3)
    # banded grid: the j axis only walks blocks near the window diagonal;
    # jl is the grid coordinate, j the actual k-block index
    j = (jl + _win_j_base(i, bq, bk, win_grid, window)
         if win_grid is not None else jl)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(jl == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _step(masked):
        def go():
            q = q_ref[0, 0]
            k = k_ref[0, 0]
            v = v_ref[0, 0]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if masked:
                kvl = kvl_ref[b] if kvl_ref is not None else None
                s, valid = _mask_block(s, i, j, bq, bk, sk, kvl, causal,
                                       window, q_off, k_off)
            m_prev = m_scr[:, :1]
            l_prev = l_scr[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = (jnp.where(valid, jnp.exp(s - m_new), 0.0) if masked
                 else jnp.exp(s - m_new))
            l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32)
            m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
            l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        return go

    keep = _causal_block_skip(i, j, bq, bk, causal, window, q_off, k_off)
    if win_grid is not None:
        # banded grid can run past the last real k-block at the bottom
        # rows; those steps are skipped (their DMA is clipped in the
        # index maps)
        keep = jnp.logical_and(keep, j <= nk - 1)
    _when_blocks(_step, keep, i, j, bq, bk, causal, window,
                 kvl_ref is not None, nk * bk != sk, q_off, k_off)

    @pl.when(jl == pl.num_programs(3) - 1)
    def _finish():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        o = acc_scr[:] * jnp.where(l > 0, 1.0 / l, 0.0)
        o_ref[0, 0] = o.astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(l), _LSE_PAD)
        lse_ref[0, 0] = jnp.broadcast_to(lse.T, lse_ref.shape[2:])


def _offsets(q_off, k_off, sq, sk):
    """SMEM [q_off, k_off] operand; defaults to the classic queries-at-the-
    end convention (``q_off = sk - sq``)."""
    if q_off is None:
        q_off = sk - sq
    if k_off is None:
        k_off = 0
    return jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])


def _run_fwd(q, k, v, kv_lengths, scale, causal, sq, sk, bq, bk,
             group=1, window=None, q_off=None, k_off=None):
    """q/k/v padded to block multiples; returns padded (o, lse). ``group``
    q heads share each K/V head (GQA/MQA): the K/V index maps divide the
    head coordinate, so grouped heads reread the same blocks instead of the
    caller materializing a broadcast copy in HBM. ``q_off``/``k_off``:
    global-position offsets (traced OK) — see :func:`_mask_block`."""
    batch, heads, sqp, dp = q.shape
    skp = k.shape[2]
    nq, nk = sqp // bq, skp // bk
    if nq == 1 and nk == 1:
        # whole problem fits one (bq, bk) tile: one-pass kernel, no
        # online-softmax machinery (see _fwd_single_kernel)
        return _run_fwd_single(q, k, v, kv_lengths, scale, causal, sq, sk,
                               bq, bk, group, window, q_off, k_off)
    # banded grid for sliding windows with STATIC offsets (the plain flash
    # path): only the ~(window+bq)/bk k-blocks near the diagonal are walked,
    # making windowed attention O(s*window) in grid steps too, not just in
    # executed matmuls (grid overhead dominated the skip-only version)
    win_grid = None
    nk_grid = nk
    if window is not None and q_off is None and k_off is None:
        win_grid = sk - sq
        nk_grid = min(nk, (bq + window - 2) // bk + 2)

    def _kj(i, j):
        if win_grid is None:
            return j
        return jnp.minimum(j + _win_j_base(i, bq, bk, win_grid, window),
                           nk - 1)

    grid = (batch, heads, nq, nk_grid)
    kvl_spec = []
    args = [_offsets(q_off, k_off, sq, sk)]
    if kv_lengths is not None:
        kvl_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(kv_lengths.astype(jnp.int32))
    kernel = _wrap_kernel(_fwd_kernel, kv_lengths, scale=scale, bq=bq,
                          bk=bk, nk=nk, sk=sk, causal=causal,
                          window=window, win_grid=win_grid)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + kvl_spec + [
            pl.BlockSpec((1, 1, bq, dp), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, dp),
                         lambda b, h, i, j: (b, h // group, _kj(i, j), 0)),
            pl.BlockSpec((1, 1, bk, dp),
                         lambda b, h, i, j: (b, h // group, _kj(i, j), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, dp), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, sqp, dp), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, 1, sqp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, dp), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=pallas_interpret(),
    )(*args, q, k, v)
    return o, lse[:, :, 0, :]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _recompute_p_ds(q, k, v, do, lse, delta, i, j, *, scale, bq, bk, sk,
                    kvl, causal, window, q_off, k_off, need_mask=True,
                    keep=None, inv_keep=1.0):
    """The flash-backward block recompute every backward kernel shares:
    rebuild the (bq, bk) probabilities from the stashed lse and form
    ``ds = p * (dp - delta)``. Returns ``(p, ds)`` (both fp32).
    ``need_mask=False`` (statically all-valid block: non-causal, no
    window/varlen, keys unpadded) skips the mask arithmetic — at short
    sequence it is a measurable share of the kernel (round 5).
    ``keep``/``inv_keep``: attention-dropout mask regenerated from the
    forward's seed — dp is masked+rescaled BEFORE the ds identity, which
    stays exact because delta = do.o already sums the DROPPED probs (the
    same softmax-jacobian algebra as the dropout-free case)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if need_mask:
        s, _ = _mask_block(s, i, j, bq, bk, sk, kvl, causal, window,
                           q_off, k_off)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if keep is not None:
        dp = jnp.where(keep, dp * inv_keep, 0.0)
    return p, p * (dp - delta)


def _dq_kernel(offs_ref, kvl_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_scr, *, scale, bq, bk, nk, sk, causal,
               window=None, win_grid=None):
    b, i, jl = pl.program_id(0), pl.program_id(2), pl.program_id(3)
    j = (jl + _win_j_base(i, bq, bk, win_grid, window)
         if win_grid is not None else jl)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(jl == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _step(masked):
        def go():
            k = k_ref[0, 0]
            kvl = kvl_ref[b] if kvl_ref is not None else None
            _, ds = _recompute_p_ds(
                q_ref[0, 0], k, v_ref[0, 0], do_ref[0, 0],
                lse_ref[0, 0].reshape(1, bq).T,
                delta_ref[0, 0].reshape(1, bq).T,
                i, j, scale=scale, bq=bq, bk=bk, sk=sk, kvl=kvl,
                causal=causal, window=window, q_off=q_off, k_off=k_off,
                need_mask=masked)
            dq_scr[:] = dq_scr[:] + scale * jax.lax.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32)
        return go

    keep = _causal_block_skip(i, j, bq, bk, causal, window, q_off, k_off)
    if win_grid is not None:
        keep = jnp.logical_and(keep, j <= nk - 1)
    _when_blocks(_step, keep, i, j, bq, bk, causal, window,
                 kvl_ref is not None, nk * bk != sk, q_off, k_off)

    @pl.when(jl == pl.num_programs(3) - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, kvl_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, bq, bk, nq, sk, causal, group=1,
                window=None, win_grid=None, nq_grid=None):
    # grid: (batch, kv_heads, nk, group * nq_grid) — the trailing dim walks
    # every (q head in group, q block) pair so dk/dv accumulate over the
    # whole query group in one scratch pass (GQA/MQA backward); with a
    # banded window grid only the q-blocks near the diagonal are walked
    b, j, t = pl.program_id(0), pl.program_id(2), pl.program_id(3)
    ng = nq if nq_grid is None else nq_grid
    il = t % ng
    i = (il + _win_i_base(j, bq, bk, win_grid, window)
         if win_grid is not None else il)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _step(masked):
        def go():
            q = q_ref[0, 0]
            do = do_ref[0, 0]
            kvl = kvl_ref[b] if kvl_ref is not None else None
            p, ds = _recompute_p_ds(
                q, k_ref[0, 0], v_ref[0, 0], do,
                lse_ref[0, 0].reshape(1, bq).T,
                delta_ref[0, 0].reshape(1, bq).T,
                i, j, scale=scale, bq=bq, bk=bk, sk=sk, kvl=kvl,
                causal=causal, window=window, q_off=q_off, k_off=k_off,
                need_mask=masked)
            dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return go

    keep = _causal_block_skip(i, j, bq, bk, causal, window, q_off, k_off)
    if win_grid is not None:
        keep = jnp.logical_and(keep, i <= nq - 1)
    _when_blocks(_step, keep, i, j, bq, bk, causal, window,
                 kvl_ref is not None, pl.num_programs(2) * bk != sk,
                 q_off, k_off)

    @pl.when(t == pl.num_programs(3) - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _dqkv_fused_kernel(offs_ref, kvl_ref, dq_in_ref, q_ref, k_ref, v_ref,
                       do_ref, lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                       dk_scr, dv_scr, *, scale, bq, bk, nq, sk, causal,
                       group=1, window=None):
    """Fused multi-block backward: ONE pass over the (j, t=(g, i)) grid
    computes dq, dk and dv together. The separate dq/dkv kernels each
    redo the s = qk^T recompute, the exp, the mask arithmetic and the
    dp = do.v^T matmul, and re-DMA every operand block — at 32k that
    duplication was ~30% of the whole backward (PERF.md round 5). Here
    dk/dv accumulate in scratch over the inner t sweep exactly as in
    :func:`_dkv_kernel`, while dq blocks accumulate across the OUTER j
    dim through an fp32 buffer aliased input->output: each step reads its
    dq block, adds this j's contribution (or passes it through unchanged
    for causally dead blocks — every step must write its window), and
    writes it back. Correctness of the read-modify-write needs every
    consecutive grid step to touch a DIFFERENT dq window (else the input
    window is not re-fetched and a contribution is lost): guaranteed by
    the dispatch condition nq >= 2 with no banded-window grid (the
    banded clamp can revisit the same window; those shapes keep the
    two-kernel path)."""
    b, j, t = pl.program_id(0), pl.program_id(2), pl.program_id(3)
    i = t % nq
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _step(masked):
        def go():
            q = q_ref[0, 0]
            k = k_ref[0, 0]
            do = do_ref[0, 0]
            kvl = kvl_ref[b] if kvl_ref is not None else None
            p, ds = _recompute_p_ds(
                q, k, v_ref[0, 0], do,
                lse_ref[0, 0].reshape(1, bq).T,
                delta_ref[0, 0].reshape(1, bq).T,
                i, j, scale=scale, bq=bq, bk=bk, sk=sk, kvl=kvl,
                causal=causal, window=window, q_off=q_off, k_off=k_off,
                need_mask=masked)
            dq_ref[0, 0] = dq_in_ref[0, 0] + scale * jax.lax.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32)
            dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return go

    keep = _causal_block_skip(i, j, bq, bk, causal, window, q_off, k_off)
    _when_blocks(_step, keep, i, j, bq, bk, causal, window,
                 kvl_ref is not None, pl.num_programs(2) * bk != sk,
                 q_off, k_off)
    if causal or window is not None:
        # dead blocks contribute nothing but MUST still write their dq
        # window (an unwritten window would flush stale VMEM on the next
        # index change)
        @pl.when(jnp.logical_not(keep))
        def _passthrough():
            dq_ref[0, 0] = dq_in_ref[0, 0]

    @pl.when(t == pl.num_programs(3) - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _run_bwd_fused(q, k, v, do, lse, delta, kv_lengths, scale, causal,
                   sq, sk, bq, bk, group, window, q_off, k_off):
    """Dispatch for :func:`_dqkv_fused_kernel` (win_grid-free multi-block
    shapes). Returns (dq fp32, dk, dv)."""
    batch, heads, sqp, dp = q.shape
    kv_heads, skp = k.shape[1], k.shape[2]
    nq, nk = sqp // bq, skp // bk
    # machine-check of the aliased dq read-modify-write precondition
    # (_dqkv_fused_kernel: every consecutive grid step must touch a
    # DISTINCT dq window, guaranteed by nq >= 2 with no banded-window
    # grid). The default CI suite runs interpret mode, which never takes
    # this path — so the invariant must hold by construction, not by
    # suite coverage; a dispatcher change that violates it fails loudly
    # here instead of corrupting gradients.
    banded = window is not None and q_off is None and k_off is None
    if nq < 2 or banded:
        raise AssertionError(
            f"_run_bwd_fused dispatched outside its precondition "
            f"(nq={nq}, banded_window_grid={banded}): the aliased dq "
            f"accumulation requires nq >= 2 and a non-banded grid — "
            f"these shapes must keep the two-kernel backward")

    def _qh(h, t):
        return h * group + t // nq

    kvl_spec = []
    args = [_offsets(q_off, k_off, sq, sk)]
    if kv_lengths is not None:
        kvl_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(kv_lengths.astype(jnp.int32))
    dq_zero = jnp.zeros(q.shape, jnp.float32)
    qi_spec = pl.BlockSpec((1, 1, bq, dp),
                           lambda b, h, j, t: (b, _qh(h, t), t % nq, 0))
    dq, dk, dv = pl.pallas_call(
        _wrap_kernel(_dqkv_fused_kernel, kv_lengths, scale=scale, bq=bq,
                     bk=bk, nq=nq, sk=sk, causal=causal, group=group,
                     window=window),
        grid=(batch, kv_heads, nk, group * nq),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + kvl_spec + [
            qi_spec,                                                # dq acc
            qi_spec,                                                # q
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, t: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, t: (b, h, j, 0)),
            qi_spec,                                                # do
            pl.BlockSpec((1, 1, 1, bq),
                         lambda b, h, j, t: (b, _qh(h, t), 0, t % nq)),
            pl.BlockSpec((1, 1, 1, bq),
                         lambda b, h, j, t: (b, _qh(h, t), 0, t % nq)),
        ],
        out_specs=[
            qi_spec,
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, t: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, t: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, jnp.float32),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, dp), jnp.float32),
                        pltpu.VMEM((bk, dp), jnp.float32)],
        input_output_aliases={len(kvl_spec) + 1: 0},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=pallas_interpret(),
    )(*args, dq_zero, q, k, v, do, lse, delta)
    return dq.astype(q.dtype), dk, dv


def _dqkv_single_kernel(offs_ref, kvl_ref, q_ref, k_ref, v_ref, do_ref,
                        lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                        dk_scr, dv_scr, *, scale, bq, bk, sk, causal,
                        window, need_mask=True):
    """Fused one-pass backward for the single-block case (sq <= bq and
    sk <= bk): s/p are computed ONCE and all three cotangents come out of
    the same VMEM residency — at short seq the separate dq/dkv kernels
    each redo the s=qk^T recompute and re-DMA q/k/v/do, and that (not
    FLOPs) dominates; measured 1.4x faster fwd+bwd at the GPT bench shape
    (b8 h16 s1024 d64). Grid (batch, kv_heads, group): the trailing dim
    walks the query heads sharing this K/V head (GQA — grouping lives
    entirely in the grid/index maps), accumulating dk/dv in scratch and
    writing dq per head."""
    b, t = pl.program_id(0), pl.program_id(2)
    q_off, k_off = offs_ref[0], offs_ref[1]

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        do = do_ref[0, 0]
        kvl = kvl_ref[b] if kvl_ref is not None else None
        p, ds = _recompute_p_ds(
            q, k, v_ref[0, 0], do,
            lse_ref[0, 0].reshape(1, bq).T,
            delta_ref[0, 0].reshape(1, bq).T,
            0, 0, scale=scale, bq=bq, bk=bk, sk=sk, kvl=kvl, causal=causal,
            window=window, q_off=q_off, k_off=k_off, need_mask=need_mask)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_ref[0, 0] = (scale * jax.lax.dot(
            ds.astype(k.dtype), k,
            preferred_element_type=jnp.float32)).astype(dq_ref.dtype)

    if causal or window is not None:
        # the fully-masked case (causal future / window far past) must stay
        # near-free: ring-attention backward hops route here whenever the
        # chunk fits one block, and cp/2 of them are entirely future
        keep = _causal_block_skip(0, 0, bq, bk, causal, window,
                                  q_off, k_off)
        pl.when(keep)(_step)

        @pl.when(jnp.logical_not(keep))
        def _zero_dq():
            dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])
    else:
        _step()

    @pl.when(t == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# packed-QKV path (layout-native single-block attention)
# ---------------------------------------------------------------------------
# The fused QKV projection emits [s, b, G*(qpg+2)*d] with each group's
# columns ordered q_0..q_{qpg-1} | k | v. The kernels here consume that
# buffer DIRECTLY (flattened to [s, b*W], one contiguous column block per
# grid cell) and the backward writes dqkv back in the same packed layout —
# so the [s,b,..] <-> [b,h,s,d] transposes around the attention call and
# the [s,b,h,3,d]-minor cotangent reassembly disappear entirely. At 355M
# those copies were ~18 ms of a 202 ms step (PERF.md round 5); a strided/
# contiguous DMA A/B measured the layout-native reads at parity with the
# [b,h,s,d] blocks (428 vs 445 us/call at b8 h16 s1024 d64). Single-block
# only — any s with round_up(s, 8) <= 1024 (see _packed_supported: ragged
# lengths pad to the sublane multiple internally, padded keys masked via
# kv_lengths) — because the (s, s) fp32 logits of one cell must fit VMEM,
# which is also the regime where the copies dominate (at 32k the O(s)
# copies vanish next to O(s^2) attention work). RoPE and attention dropout
# run in-kernel on this path (rot/rate kernel params below).


def packed_geometry(num_groups: int, qpg: int, head_dim: int):
    """Choose groups-per-cell so both the per-cell qkv slab and the output
    slab are 128-lane aligned. Returns (gpc, in_w, out_w) or None when no
    alignment exists (then callers fall back to the 4D path)."""
    for gpc in (1, 2):
        if num_groups % gpc:
            continue
        in_w = gpc * (qpg + 2) * head_dim
        out_w = gpc * qpg * head_dim
        if in_w % 128 == 0 and out_w % 128 == 0:
            return gpc, in_w, out_w
    return None


def _packed_supported(s, num_groups, qpg, head_dim):
    # any s up to 1024: rows pad to the 8-sublane multiple inside
    # flash_attention_packed (padded keys masked via kv_lengths; padded
    # query rows sliced off), and Mosaic handles the ragged lane extents
    # of the (s, s) logits block correctly (verified on hardware at
    # s=200/520 — reductions respect logical shapes)
    return (round_up(s, 8) <= 1024 and head_dim % 8 == 0
            and packed_geometry(num_groups, qpg, head_dim) is not None)


def _drop_combo(b, head):
    """The ONE (batch, global-head) -> hash-key mapping every dropout
    mask shares: forward kernel, backward regeneration, XLA fallback and
    the parity test all call this — a drifted copy would make the
    backward regenerate a different mask than the forward applied, with
    no error raised. Stride 4096 bounds heads per model."""
    return b * 4096 + head


def _hash_keep(seed, combo, shape, rate):
    """Deterministic per-position dropout keep-mask: a murmur3-style
    integer hash of (seed, combo, row, col) in pure elementwise uint32
    math. The forward kernel, the backward's regeneration, interpret
    mode and the XLA fallback therefore produce BIT-IDENTICAL masks —
    unlike the Mosaic PRNG, whose bit-to-position assignment is not
    stable across differently-compiled kernels (measured: a mask
    extracted by a second kernel with the same seed differed). This is
    how the backward re-derives the forward's mask without storing s^2
    bytes (the reference fmha stores a philox offset for the same
    purpose). ``combo`` folds (batch, global head) — scalar in-kernel,
    broadcastable array on the XLA path. Keep probability = 1 - rate.
    The row/col position keys are THIN (s,1)/(1,s) iotas combined by one
    broadcasting op — two full-tile (s,s) uint32 iotas plus the hash
    chain exceeded the 16 MB scoped-vmem stack by 2.4 MB in the s=1024
    backward kernel."""
    ones = tuple(1 for _ in shape[:-2])
    r = jax.lax.broadcasted_iota(jnp.uint32, ones + (shape[-2], 1),
                                 len(shape) - 2)
    c = jax.lax.broadcasted_iota(jnp.uint32, ones + (1, shape[-1]),
                                 len(shape) - 1)
    k = (jnp.asarray(seed).astype(jnp.uint32)
         + jnp.asarray(combo).astype(jnp.uint32) * jnp.uint32(0x27D4EB2F))
    x = (r * jnp.uint32(0x9E3779B1) + k) ^ (c * jnp.uint32(0x85EBCA77))
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> jnp.uint32(16))
    thresh = jnp.uint32(min(int(rate * 2.0 ** 32), 2 ** 32 - 1))
    return x >= thresh


def _rope_block(t, cos, sin, rot):
    """Rotate-half RoPE over the first ``rot`` columns of a (s, d) block
    (Megatron ``concat(f, f)`` convention: the sin/cos halves repeat, so
    the inverse is the same map with ``-sin`` — pass negated sin). ``cos``/
    ``sin`` are fp32 (s, d) with cos=1/sin=0 past ``rot``."""
    tf = t.astype(jnp.float32)
    half = jnp.concatenate([-tf[:, rot // 2: rot], tf[:, : rot // 2]],
                           axis=1)
    if rot < t.shape[1]:
        half = jnp.concatenate(
            [half, jnp.zeros((t.shape[0], t.shape[1] - rot), jnp.float32)],
            axis=1)
    return (tf * cos + half * sin).astype(t.dtype)


def _fwd_packed_kernel(kvl_ref, rope_refs, seed_ref, qkv_ref, o_ref,
                       lse_ref, *,
                       scale, s, d, qpg, gpc, causal, window, need_mask,
                       rot=0, rate=0.0):
    """One grid cell = ``gpc`` whole K/V groups of one batch row. Slices are
    static column offsets into the packed slab; per-head math is the same
    one-pass softmax as :func:`_fwd_single_kernel` (sq == sk == s, offsets
    0 — a self-attention block is never fully masked, so no skip gate).
    ``rot > 0``: apply RoPE to the q/k slices in-kernel (the packed layout
    has no pre-kernel [s,b,h,d] view to rotate). ``rate > 0``: attention
    dropout on the (normalized) probabilities with an in-kernel PRNG mask
    (torch semantics: softmax, then dropout, then @v — the 1/l
    normalization commutes with the positionwise mask)."""
    b = pl.program_id(0)
    cell = pl.program_id(1)
    for g in range(gpc):
        base = g * (qpg + 2) * d
        k = qkv_ref[:, base + qpg * d: base + (qpg + 1) * d]
        v = qkv_ref[:, base + (qpg + 1) * d: base + (qpg + 2) * d]
        if rot:
            k = _rope_block(k, rope_refs[0][...], rope_refs[1][...], rot)
        for j in range(qpg):
            q = qkv_ref[:, base + j * d: base + (j + 1) * d]
            if rot:
                q = _rope_block(q, rope_refs[0][...], rope_refs[1][...],
                                rot)
            sm = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32
                                     ) * scale
            if need_mask:
                kvl = kvl_ref[b] if kvl_ref is not None else None
                sm, valid = _mask_block(sm, 0, 0, s, s, s, kvl, causal,
                                        window, 0, 0)
                m = jnp.max(sm, axis=1, keepdims=True)
                p = jnp.where(valid, jnp.exp(sm - m), 0.0)
            else:
                m = jnp.max(sm, axis=1, keepdims=True)
                p = jnp.exp(sm - m)
            l = jnp.sum(p, axis=1, keepdims=True)
            h = g * qpg + j
            if rate > 0.0:
                keep = _hash_keep(seed_ref[0],
                                  _drop_combo(b, cell * (gpc * qpg) + h),
                                  p.shape, rate)
                p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
            o = jax.lax.dot(p.astype(v.dtype), v,
                            preferred_element_type=jnp.float32)
            o = o * jnp.where(l > 0, 1.0 / l, 0.0)
            o_ref[:, h * d:(h + 1) * d] = o.astype(o_ref.dtype)
            lse = jnp.where(l > 0, m + jnp.log(l), _LSE_PAD)
            lse_ref[0, h] = lse.reshape(1, s)


def _dqkv_packed_kernel(kvl_ref, rope_refs, seed_ref, qkv_ref, do_ref,
                        o_ref, lse_ref,
                        dqkv_ref, *, scale, s, d, qpg, gpc, causal, window,
                        need_mask, rot=0, rate=0.0):
    """Fused one-pass backward writing dq/dk/dv straight into the packed
    [s, cell-width] layout. dK/dV accumulate over the cell's query group in
    registers (the whole group lives in one cell by construction). delta
    (rowwise do . o) is computed in-kernel from the o block — as an XLA
    pre-pass it cost ~107 us/layer of separate HBM traffic at 355M.
    ``rot > 0``: the recompute rotates q/k exactly as the forward did, and
    the emitted dq/dk are un-rotated (RoPE is skew-orthogonal per row:
    inverse = same map with -sin) so the cotangent matches the RAW packed
    projection output. ``rate > 0``: the dropout keep-mask is regenerated
    from the forward's (seed, batch, cell, head) coordinates — nothing is
    stored."""
    b = pl.program_id(0)
    cell = pl.program_id(1)
    if rot:
        cos, sin = rope_refs[0][...], rope_refs[1][...]
    for g in range(gpc):
        base = g * (qpg + 2) * d
        k = qkv_ref[:, base + qpg * d: base + (qpg + 1) * d]
        v = qkv_ref[:, base + (qpg + 1) * d: base + (qpg + 2) * d]
        if rot:
            k = _rope_block(k, cos, sin, rot)
        dk_acc = jnp.zeros((s, d), jnp.float32)
        dv_acc = jnp.zeros((s, d), jnp.float32)
        for j in range(qpg):
            q = qkv_ref[:, base + j * d: base + (j + 1) * d]
            if rot:
                q = _rope_block(q, cos, sin, rot)
            h = g * qpg + j
            do = do_ref[:, h * d:(h + 1) * d]
            delta = jnp.sum(do.astype(jnp.float32)
                            * o_ref[:, h * d:(h + 1) * d].astype(
                                jnp.float32),
                            axis=1, keepdims=True)
            kvl = kvl_ref[b] if kvl_ref is not None else None
            keep = (None if rate == 0.0
                    else _hash_keep(seed_ref[0],
                                    _drop_combo(b, cell * (gpc * qpg) + h),
                                    (s, s), rate))
            p, ds = _recompute_p_ds(
                q, k, v, do,
                lse_ref[0, h].reshape(1, s).T,
                delta,
                0, 0, scale=scale, bq=s, bk=s, sk=s, kvl=kvl,
                causal=causal, window=window, q_off=0, k_off=0,
                need_mask=need_mask, keep=keep,
                inv_keep=1.0 / (1.0 - rate) if rate else 1.0)
            dq = scale * jax.lax.dot(ds.astype(k.dtype), k,
                                     preferred_element_type=jnp.float32)
            if rot:
                dq = _rope_block(dq, cos, -sin, rot)
            dqkv_ref[:, base + j * d: base + (j + 1) * d] = \
                dq.astype(dqkv_ref.dtype)
            if keep is not None:
                # dV flows through the DROPPED probabilities
                p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
            dv_acc = dv_acc + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_acc = dk_acc + scale * jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        if rot:
            dk_acc = _rope_block(dk_acc, cos, -sin, rot)
        dqkv_ref[:, base + qpg * d: base + (qpg + 1) * d] = \
            dk_acc.astype(dqkv_ref.dtype)
        dqkv_ref[:, base + (qpg + 1) * d: base + (qpg + 2) * d] = \
            dv_acc.astype(dqkv_ref.dtype)


def _run_fwd_packed(qkv2, kv_lengths, rope, drop, *, scale, s, batch, W,
                    d, qpg, geom, heads, causal, window):
    """qkv2: [s, batch*W]; returns (o2 [s, batch*heads*d], lse [b,H,1,s]).
    ``geom`` is packed_geometry's (gpc, in_w, out_w) — the ONE source of
    the cell widths the BlockSpecs and kernel loop bounds share. ``rope``:
    None or (cos, sin) fp32 [s, d] (padded past the rotary dim)."""
    gpc, in_w, out_w = geom
    n_cells = W // in_w
    hpc = gpc * qpg
    need_mask = causal or window is not None or kv_lengths is not None
    kvl_spec = []
    args = []
    if kv_lengths is not None:
        kvl_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(kv_lengths.astype(jnp.int32))
    rot = 0
    if rope is not None:
        rot = int(rope[2])
        kvl_spec = kvl_spec + [pl.BlockSpec((s, d), lambda b, c: (0, 0))] * 2
        args += [rope[0], rope[1]]
    rate = 0.0
    if drop is not None:
        rate = float(drop[1])
        kvl_spec = kvl_spec + [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(drop[0])
    o, lse = pl.pallas_call(
        _wrap_kernel_nooffs(_fwd_packed_kernel, kv_lengths, rope,
                            dropout=drop is not None,
                            scale=scale,
                            s=s, d=d, qpg=qpg, gpc=gpc, causal=causal,
                            window=window, need_mask=need_mask, rot=rot,
                            rate=rate),
        grid=(batch, n_cells),
        in_specs=kvl_spec + [
            pl.BlockSpec((s, in_w), lambda b, c: (0, b * n_cells + c)),
        ],
        out_specs=[
            pl.BlockSpec((s, out_w), lambda b, c: (0, b * n_cells + c)),
            pl.BlockSpec((1, hpc, 1, s), lambda b, c: (b, c, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, batch * heads * d), qkv2.dtype),
            jax.ShapeDtypeStruct((batch, heads, 1, s), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=pallas_interpret(),
    )(*args, qkv2)
    return o, lse


def _run_bwd_packed(qkv2, do2, o2, lse, kv_lengths, rope, drop, *, scale,
                    s, batch, W, d, qpg, geom, heads, causal, window):
    gpc, in_w, out_w = geom
    n_cells = W // in_w
    hpc = gpc * qpg
    need_mask = causal or window is not None or kv_lengths is not None
    kvl_spec = []
    args = []
    if kv_lengths is not None:
        kvl_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(kv_lengths.astype(jnp.int32))
    rot = 0
    if rope is not None:
        rot = int(rope[2])
        kvl_spec = kvl_spec + [pl.BlockSpec((s, d), lambda b, c: (0, 0))] * 2
        args += [rope[0], rope[1]]
    rate = 0.0
    if drop is not None:
        rate = float(drop[1])
        kvl_spec = kvl_spec + [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(drop[0])
    return pl.pallas_call(
        _wrap_kernel_nooffs(_dqkv_packed_kernel, kv_lengths, rope,
                            dropout=drop is not None,
                            scale=scale,
                            s=s, d=d, qpg=qpg, gpc=gpc, causal=causal,
                            window=window, need_mask=need_mask, rot=rot,
                            rate=rate),
        grid=(batch, n_cells),
        in_specs=kvl_spec + [
            pl.BlockSpec((s, in_w), lambda b, c: (0, b * n_cells + c)),
            pl.BlockSpec((s, out_w), lambda b, c: (0, b * n_cells + c)),
            pl.BlockSpec((s, out_w), lambda b, c: (0, b * n_cells + c)),
            pl.BlockSpec((1, hpc, 1, s), lambda b, c: (b, c, 0, 0)),
        ],
        out_specs=pl.BlockSpec((s, in_w), lambda b, c: (0, b * n_cells + c)),
        out_shape=jax.ShapeDtypeStruct(qkv2.shape, qkv2.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=pallas_interpret(),
    )(*args, qkv2, do2, o2, lse)


def _wrap_kernel_nooffs(fn, kv_lengths, rope, dropout=False, **kw):
    """Like :func:`_wrap_kernel` for the packed kernels (no offsets
    operand: sq == sk == s, offsets statically zero). Slots None into the
    kernel's ``kvl_ref``/``rope_refs``/``seed_ref`` positions for absent
    operands."""
    have_kvl = kv_lengths is not None

    def wrapped(*refs, **k2):
        idx = 0
        kvl = None
        if have_kvl:
            kvl, idx = refs[0], 1
        rope_refs = None
        if rope is not None:
            rope_refs, idx = (refs[idx], refs[idx + 1]), idx + 2
        seed_ref = None
        if dropout:
            seed_ref, idx = refs[idx], idx + 1
        return fn(kvl, rope_refs, seed_ref, *refs[idx:], **k2)

    return functools.partial(wrapped, **kw)


def _packed_unpack(qkv, qpg, d):
    """[s, b, G*(qpg+2)*d] -> q/k/v in [b, h, s, d] (reference path)."""
    s, b, W = qkv.shape
    g = W // ((qpg + 2) * d)
    qkv5 = qkv.reshape(s, b, g, qpg + 2, d)
    q = qkv5[:, :, :, :qpg].reshape(s, b, g * qpg, d)
    k = qkv5[:, :, :, qpg]
    v = qkv5[:, :, :, qpg + 1]
    return (t.transpose(1, 2, 0, 3) for t in (q, k, v))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_packed(qkv, kv_lengths, rope_cos, rope_sin, seed, scale, causal,
                  window, qpg, d, rot, rate):
    o, _ = _flash_packed_fwd_impl(qkv, kv_lengths, rope_cos, rope_sin,
                                  seed, scale, causal, window, qpg, d, rot,
                                  rate)
    return o


def _packed_geom_of(qkv, qpg, d):
    s, b, W = qkv.shape
    g = W // ((qpg + 2) * d)
    gpc, in_w, out_w = packed_geometry(g, qpg, d)
    return s, b, W, g, (gpc, in_w, out_w), g * qpg


def _rope_tuple(rope_cos, rope_sin, rot):
    return None if rot == 0 else (rope_cos, rope_sin, rot)


def _drop_tuple(seed, rate):
    return None if rate == 0.0 else (seed, rate)


def _flash_packed_fwd_impl(qkv, kv_lengths, rope_cos, rope_sin, seed,
                           scale, causal, window, qpg, d, rot, rate):
    s, b, W, g, geom, heads = _packed_geom_of(qkv, qpg, d)
    o2, lse = _run_fwd_packed(
        qkv.reshape(s, b * W), kv_lengths, _rope_tuple(rope_cos, rope_sin,
                                                       rot),
        _drop_tuple(seed, rate),
        scale=scale, s=s, batch=b, W=W,
        d=d, qpg=qpg, geom=geom, heads=heads, causal=causal, window=window)
    return o2.reshape(s, b, heads * d), lse


def _flash_packed_vjp_fwd(qkv, kv_lengths, rope_cos, rope_sin, seed, scale,
                          causal, window, qpg, d, rot, rate):
    o, lse = _flash_packed_fwd_impl(qkv, kv_lengths, rope_cos, rope_sin,
                                    seed, scale, causal, window, qpg, d,
                                    rot, rate)
    return o, (qkv, kv_lengths, rope_cos, rope_sin, seed, o, lse)


def _flash_packed_vjp_bwd(scale, causal, window, qpg, d, rot, rate, res,
                          do):
    qkv, kv_lengths, rope_cos, rope_sin, seed, o, lse = res
    s, b, W, g, geom, heads = _packed_geom_of(qkv, qpg, d)
    dqkv = _run_bwd_packed(
        qkv.reshape(s, b * W), do.reshape(s, b * heads * d),
        o.reshape(s, b * heads * d), lse,
        kv_lengths, _rope_tuple(rope_cos, rope_sin, rot),
        _drop_tuple(seed, rate),
        scale=scale, s=s, batch=b, W=W, d=d, qpg=qpg, geom=geom,
        heads=heads, causal=causal, window=window)
    dkvl = (None if kv_lengths is None
            else np.zeros(kv_lengths.shape, dtype=jax.dtypes.float0))
    # rope tables / dropout seed are constants (zero cotangent)
    dcos = None if rope_cos is None else jnp.zeros_like(rope_cos)
    dsin = None if rope_sin is None else jnp.zeros_like(rope_sin)
    dseed = (None if seed is None
             else np.zeros(seed.shape, dtype=jax.dtypes.float0))
    return dqkv.reshape(s, b, W), dkvl, dcos, dsin, dseed


_flash_packed.defvjp(_flash_packed_vjp_fwd, _flash_packed_vjp_bwd)


def flash_attention_packed(
    qkv: jax.Array,
    *,
    queries_per_group: int,
    head_dim: int,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    kv_lengths: Optional[jax.Array] = None,
    sliding_window: Optional[int] = None,
    rope_freqs: Optional[jax.Array] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
) -> jax.Array:
    """Self-attention over a packed QKV projection, layout-native.

    Args:
      qkv: ``[s, b, G*(qpg+2)*head_dim]`` — the fused QKV projection output,
        each group's columns ordered ``q_0..q_{qpg-1} | k | v`` (the
        ``ParallelAttention`` convention). GQA/MQA falls out of ``G``/
        ``qpg``; MHA is ``qpg == 1``.
      queries_per_group: query heads per K/V group (``qpg``).

    Returns ``[s, b, G*qpg*head_dim]`` context in model layout — no
    [b,h,s,d] transposes on either side of the kernel, and the VJP emits
    the packed ``dqkv`` cotangent directly (see the section comment).
    Callers must pre-check :func:`packed_attention_supported`.

    ``rope_freqs``: optional RoPE angles for positions 0..s-1 (shape
    ``[s, rot_dim]`` or the ``[s, 1, 1, rot_dim]``
    :func:`~apex_tpu.ops.fused_rope` layout, Megatron concat(f, f)
    convention, rot_dim even): q and k are rotated IN-KERNEL — the packed
    layout never materializes a pre-kernel [s,b,h,d] view to rotate — and
    the VJP un-rotates dq/dk so the cotangent matches the raw projection.

    ``dropout_rate``/``dropout_seed``: attention dropout on the softmax
    probabilities (torch semantics; the reference fmha capability),
    applied in-kernel from a position-deterministic integer hash mask
    (:func:`_hash_keep`) that the backward REGENERATES from the same
    (seed, batch, head, position) coordinates — no s^2 mask bytes are
    stored, and the Pallas kernels, interpret mode and the pure-XLA
    fallback all drop the SAME positions for a given seed.
    ``dropout_seed`` is an int32 ``[1]`` array; the caller derives it
    from its PRNG key (distinct per layer/step as desired).
    """
    s, b, W = qkv.shape
    qpg, d = queries_per_group, head_dim
    g = W // ((qpg + 2) * d)
    if W != g * (qpg + 2) * d:
        raise ValueError(f"packed width {W} is not a multiple of the group "
                         f"block {(qpg + 2) * d}")
    if sliding_window is not None and not causal:
        raise ValueError("sliding_window requires causal attention")
    scale = float(softmax_scale if softmax_scale is not None
                  else 1.0 / np.sqrt(d))
    rot = 0
    cos = sin = None
    if rope_freqs is not None:
        f = rope_freqs.reshape(s, -1).astype(jnp.float32)
        rot = f.shape[-1]
        if rot % 2 or rot > d:
            raise ValueError(f"rotary dim {rot} must be even and <= "
                             f"head_dim {d}")
        pad = ((0, 0), (0, d - rot))
        cos = jnp.pad(jnp.cos(f), pad, constant_values=1.0)
        sin = jnp.pad(jnp.sin(f), pad)
    if dropout_rate < 0.0 or dropout_rate >= 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs a dropout_seed")
    if not use_pallas():
        q, k, v = _packed_unpack(qkv, qpg, d)
        if rot:
            from apex_tpu.ops.rope import fused_rope
            f4 = rope_freqs.reshape(s, 1, 1, rot)
            # rope expects [s, b, h, d]
            q = fused_rope(q.transpose(2, 0, 1, 3), f4).transpose(1, 2, 0, 3)
            k = fused_rope(k.transpose(2, 0, 1, 3), f4).transpose(1, 2, 0, 3)
        ctx = _mha_reference(q, k, v, kv_lengths, scale, causal,
                             sliding_window,
                             dropout_rate=dropout_rate,
                             dropout_seed=dropout_seed)
        return ctx.transpose(2, 0, 1, 3).reshape(s, b, g * qpg * d)
    if not _packed_supported(s, g, qpg, d):
        raise ValueError(
            f"packed attention unsupported for s={s}, groups={g}, "
            f"qpg={qpg}, d={d} — gate on packed_attention_supported()")
    sp = round_up(s, 8)
    if sp != s:
        # pad rows to the sublane multiple; padded KEY slots are masked
        # via kv_lengths (a padded QUERY row then holds a real softmax
        # over the true keys — harmless: its rows are sliced off, and in
        # the VJP its do rows are zero so it contributes nothing)
        qkv = jnp.pad(qkv, ((0, sp - s), (0, 0), (0, 0)))
        kv_lengths = (jnp.full((b,), s, jnp.int32) if kv_lengths is None
                      else kv_lengths)
        if cos is not None:
            cos = jnp.pad(cos, ((0, sp - s), (0, 0)), constant_values=1.0)
            sin = jnp.pad(sin, ((0, sp - s), (0, 0)))
    seed = (None if dropout_rate == 0.0
            else dropout_seed.reshape((1,)).astype(jnp.int32))
    out = _flash_packed(qkv, kv_lengths, cos, sin, seed, scale, causal,
                        sliding_window, qpg, d, rot, float(dropout_rate))
    return out[:s] if sp != s else out


def packed_attention_supported(s: int, num_groups: int,
                               queries_per_group: int,
                               head_dim: int) -> bool:
    """Whether :func:`flash_attention_packed` has a kernel for this shape
    (callers fall back to the [b,h,s,d] path otherwise). The pure-XLA
    reference path accepts anything; this predicate is about the Pallas
    geometry: 128-lane-aligned cells, one (s, s) block in VMEM."""
    if not use_pallas():
        return True
    return _packed_supported(s, num_groups, queries_per_group, head_dim)


def _wrap_kernel(fn, kv_lengths, **kw):
    """Bind kernel keywords; with no kv_lengths operand, slot None into the
    kernel's ``kvl_ref`` position (shared by all backward dispatches)."""
    if kv_lengths is not None:
        return functools.partial(fn, **kw)
    return functools.partial(
        lambda offs, *r, **k2: fn(offs, None, *r, **k2), **kw)


def _run_bwd_single(q, k, v, do, lse, delta, kv_lengths, scale, causal,
                    sq, sk, bq, bk, group, window, q_off, k_off):
    """Single-block fused dq/dk/dv dispatch — see _dqkv_single_kernel."""
    batch, _, sqp, dp = q.shape
    kv_heads = k.shape[1]
    need_mask = _single_need_mask(causal, window, kv_lengths, k.shape[2], sk)
    kvl_spec = []
    args = [_offsets(q_off, k_off, sq, sk)]
    if kv_lengths is not None:
        kvl_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(kv_lengths.astype(jnp.int32))
    dq, dk, dv = pl.pallas_call(
        _wrap_kernel(_dqkv_single_kernel, kv_lengths, scale=scale, bq=bq,
                     bk=bk, sk=sk, causal=causal, window=window,
                     need_mask=need_mask),
        grid=(batch, kv_heads, group),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + kvl_spec + [
            pl.BlockSpec((1, 1, bq, dp),
                         lambda b, h, t: (b, h * group + t, 0, 0)),  # q
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, t: (b, h, 0, 0)),  # k
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, t: (b, h, 0, 0)),  # v
            pl.BlockSpec((1, 1, bq, dp),
                         lambda b, h, t: (b, h * group + t, 0, 0)),  # do
            pl.BlockSpec((1, 1, 1, bq),
                         lambda b, h, t: (b, h * group + t, 0, 0)),  # lse
            pl.BlockSpec((1, 1, 1, bq),
                         lambda b, h, t: (b, h * group + t, 0, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, dp),
                         lambda b, h, t: (b, h * group + t, 0, 0)),
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, t: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, t: (b, h, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, dp), jnp.float32),
                        pltpu.VMEM((bk, dp), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=pallas_interpret(),
    )(*args, q, k, v, do, lse, delta)
    return dq, dk, dv


def _run_bwd(q, k, v, do, lse, delta, kv_lengths, scale, causal,
             sq, sk, bq, bk, group=1, window=None, q_off=None, k_off=None):
    batch, heads, sqp, dp = q.shape
    kv_heads, skp = k.shape[1], k.shape[2]
    nq, nk = sqp // bq, skp // bk
    if nq == 1 and nk == 1:
        # whole problem fits one (bq, bk) tile: fused one-pass backward
        return _run_bwd_single(q, k, v, do, lse, delta, kv_lengths, scale,
                               causal, sq, sk, bq, bk, group, window,
                               q_off, k_off)
    # banded window grids (see _run_fwd)
    win_grid = None
    nk_grid, nq_grid = nk, nq
    if window is not None and q_off is None and k_off is None:
        win_grid = sk - sq
        nk_grid = min(nk, (bq + window - 2) // bk + 2)
        nq_grid = min(nq, (bk + window - 2) // bq + 2)
    if win_grid is None and nq >= 2 and not pallas_interpret():
        # fused one-pass dq/dk/dv (see _dqkv_fused_kernel); the banded
        # window grid and nq == 1 keep the two-kernel path — their block
        # revisit patterns break the aliased dq accumulation's
        # distinct-consecutive-windows requirement. Interpret mode also
        # keeps the two-kernel path: the interpreter reads inputs
        # functionally, so input_output_aliases does not feed a step's
        # dq write back to later steps (the accumulation is a compiled
        # Mosaic window-DMA mechanism); hardware parity is pinned by
        # TestFusedMultiblockBackward under APEX_TPU_TEST_TPU=1.
        return _run_bwd_fused(q, k, v, do, lse, delta, kv_lengths, scale,
                              causal, sq, sk, bq, bk, group, window,
                              q_off, k_off)

    def _kj(i, j):
        if win_grid is None:
            return j
        return jnp.minimum(j + _win_j_base(i, bq, bk, win_grid, window),
                           nk - 1)

    def _qi(j, t):
        if win_grid is None:
            return t % nq
        return jnp.minimum(
            t % nq_grid + _win_i_base(j, bq, bk, win_grid, window), nq - 1)

    def _qh(h, t):
        return h * group + t // (nq if win_grid is None else nq_grid)

    kvl_spec = []
    args = [_offsets(q_off, k_off, sq, sk)]
    if kv_lengths is not None:
        kvl_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args.append(kv_lengths.astype(jnp.int32))

    row_specs = [
        pl.BlockSpec((1, 1, bq, dp), lambda b, h, i, j: (b, h, i, 0)),   # q
        pl.BlockSpec((1, 1, bk, dp),
                     lambda b, h, i, j: (b, h // group, _kj(i, j), 0)),  # k
        pl.BlockSpec((1, 1, bk, dp),
                     lambda b, h, i, j: (b, h // group, _kj(i, j), 0)),  # v
        pl.BlockSpec((1, 1, bq, dp), lambda b, h, i, j: (b, h, i, 0)),   # do
        pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),    # lse
        pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),    # delta
    ]
    dq = pl.pallas_call(
        _wrap_kernel(_dq_kernel, kv_lengths, scale=scale, bq=bq, bk=bk, nk=nk, sk=sk,
             causal=causal, window=window, win_grid=win_grid),
        grid=(batch, heads, nq, nk_grid),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + kvl_spec
        + row_specs,
        out_specs=pl.BlockSpec((1, 1, bq, dp), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dp), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=pallas_interpret(),
    )(*args, q, k, v, do, lse, delta)

    # trailing grid dim walks (q head in group, q block) pairs:
    # t = g*nq_grid + i_local
    col_specs = [
        pl.BlockSpec((1, 1, bq, dp),
                     lambda b, h, j, t: (b, _qh(h, t), _qi(j, t), 0)),   # q
        pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, t: (b, h, j, 0)),   # k
        pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, t: (b, h, j, 0)),   # v
        pl.BlockSpec((1, 1, bq, dp),
                     lambda b, h, j, t: (b, _qh(h, t), _qi(j, t), 0)),   # do
        pl.BlockSpec((1, 1, 1, bq),
                     lambda b, h, j, t: (b, _qh(h, t), 0, _qi(j, t))),   # lse
        pl.BlockSpec((1, 1, 1, bq),
                     lambda b, h, j, t: (b, _qh(h, t), 0, _qi(j, t))),   # delta
    ]
    dk, dv = pl.pallas_call(
        _wrap_kernel(_dkv_kernel, kv_lengths, scale=scale, bq=bq, bk=bk, nq=nq, sk=sk,
             causal=causal, group=group, window=window,
             win_grid=win_grid, nq_grid=nq_grid),
        grid=(batch, kv_heads, nk, group * nq_grid),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + kvl_spec
        + col_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, dp), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, dp), jnp.float32),
                        pltpu.VMEM((bk, dp), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=pallas_interpret(),
    )(*args, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# padding helpers + custom_vjp plumbing
# ---------------------------------------------------------------------------

def _pad_qkv(q, k, v, bq, bk):
    sq, d = q.shape[2], q.shape[3]
    sk = k.shape[2]
    # head dim pads to a multiple of 64, not 128: Mosaic handles 64-lane
    # blocks, and the common head_dim=64 case halves kernel HBM traffic and
    # QK^T/PV FLOPs vs padding to 128 (measured ~20% faster fwd+bwd on v5e)
    sqp, skp, dp = round_up(sq, bq), round_up(sk, bk), round_up(d, 64)

    def pad(x, sp):
        return jnp.pad(x, ((0, 0), (0, 0), (0, sp - x.shape[2]),
                           (0, dp - d)))
    return pad(q, sqp), pad(k, skp), pad(v, skp)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kv_lengths, scale, causal, bq, bk, window):
    o, _ = _flash_fwd_impl(q, k, v, kv_lengths, scale, causal, bq, bk,
                           window)
    return o


def _flash_fwd_impl(q, k, v, kv_lengths, scale, causal, bq, bk, window):
    sq, d = q.shape[2], q.shape[3]
    sk = k.shape[2]
    group = q.shape[1] // k.shape[1]
    qp, kp, vp = _pad_qkv(q, k, v, bq, bk)
    o, lse = _run_fwd(qp, kp, vp, kv_lengths, scale, causal, sq, sk, bq, bk,
                      group=group, window=window)
    return o[:, :, :sq, :d], lse[:, :, :sq]


def _flash_vjp_fwd(q, k, v, kv_lengths, scale, causal, bq, bk, window):
    o, lse = _flash_fwd_impl(q, k, v, kv_lengths, scale, causal, bq, bk,
                             window)
    return o, (q, k, v, kv_lengths, o, lse)


def _flash_vjp_bwd(scale, causal, bq, bk, window, res, do):
    q, k, v, kv_lengths, o, lse = res
    sq, d = q.shape[2], q.shape[3]
    sk = k.shape[2]
    sqp = round_up(sq, bq)
    qp, kp, vp = _pad_qkv(q, k, v, bq, bk)
    dop = jnp.pad(do, ((0, 0), (0, 0), (0, sqp - sq),
                       (0, qp.shape[3] - d)))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.pad(delta, ((0, 0), (0, 0), (0, sqp - sq)))
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, sqp - sq)),
                   constant_values=_LSE_PAD)
    # reshape row-vectors to (B, H, 1, sqp) for the (1,1,1,bq) block specs
    dq, dk, dv = _run_bwd(qp, kp, vp, dop, lsep[:, :, None, :],
                          delta[:, :, None, :], kv_lengths, scale, causal,
                          sq, sk, bq, bk, group=q.shape[1] // k.shape[1],
                          window=window)
    dq = dq[:, :, :sq, :d]
    dk = dk[:, :, :sk, :d]
    dv = dv[:, :, :sk, :d]
    if kv_lengths is None:
        dkvl = None
    else:
        dkvl = np.zeros(kv_lengths.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dkvl


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# chunk-level API (ring attention building blocks)
# ---------------------------------------------------------------------------
# Non-differentiable raw kernels over one (q chunk, kv chunk) pair with
# GLOBAL position offsets: ring attention composes these per hop and defines
# its own vjp (apex_tpu/ops/ring_attention.py). The lse convention matches
# the flash kernel: fp32 ``m + log(l)`` per row, ``_LSE_PAD`` for rows with
# no visible keys.

def _chunk_valid(sq, sk, q_start, k_start, kv_lengths, causal, window):
    row_g = q_start + jnp.arange(sq)[:, None]
    col_g = k_start + jnp.arange(sk)[None, :]
    valid = jnp.ones((sq, sk), bool)
    if causal:
        valid = jnp.logical_and(valid, col_g <= row_g)
    if window is not None:
        valid = jnp.logical_and(valid, col_g > row_g - window)
    valid = valid[None, None]                            # [1, 1, sq, sk]
    if kv_lengths is not None:
        valid = jnp.logical_and(
            valid, (col_g[None] < kv_lengths[:, None, None])[:, None])
    return valid


def _chunk_reference_fwd(q, k, v, kv_lengths, scale, causal, window,
                         q_start, k_start):
    group = q.shape[1] // k.shape[1]
    if group > 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    sq, sk = q.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = _chunk_valid(sq, sk, q_start, k_start, kv_lengths, causal,
                         window)
    s = jnp.where(valid, s, _NEG_INF)
    any_valid = jnp.any(valid, axis=-1)
    m = jnp.max(s, axis=-1)
    l = jnp.sum(jnp.exp(s - m[..., None]), axis=-1)
    lse = jnp.where(any_valid, m + jnp.log(l), _LSE_PAD)
    p = jnp.where(any_valid[..., None], jnp.exp(s - lse[..., None]), 0.0)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype), lse


def _chunk_reference_bwd(q, k, v, do, lse, delta, kv_lengths, scale,
                         causal, window, q_start, k_start):
    group = q.shape[1] // k.shape[1]
    kf = jnp.repeat(k, group, axis=1) if group > 1 else k
    vf = jnp.repeat(v, group, axis=1) if group > 1 else v
    sq, sk = q.shape[2], kf.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    valid = _chunk_valid(sq, sk, q_start, k_start, kv_lengths, causal,
                         window)
    s = jnp.where(valid, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None].astype(jnp.float32))
    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do32, vf.astype(jnp.float32))
    ds = p * (dp - delta[..., None].astype(jnp.float32))
    dq = scale * jnp.einsum("bhqk,bhkd->bhqd", ds, kf.astype(jnp.float32))
    dk = scale * jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    if group > 1:
        b, _, skc, d = k.shape
        dk = dk.reshape(b, k.shape[1], group, skc, d).sum(2)
        dv = dv.reshape(b, k.shape[1], group, skc, d).sum(2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def flash_chunk_fwd(q, k, v, *, q_start, k_start, causal=False, window=None,
                    kv_lengths=None, softmax_scale=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """One flash forward over a (q chunk, kv chunk) pair -> ``(o, lse)``.

    ``q_start``/``k_start`` (traced OK) place the chunks in GLOBAL sequence
    positions, so causal masks, sliding windows, and ``kv_lengths`` (global
    valid length) are exact across chunk boundaries; a chunk that is
    entirely in the causal future costs only grid overhead (every k-block
    is skipped) and returns ``lse = _LSE_PAD`` rows that merge with weight
    zero."""
    scale = float(softmax_scale if softmax_scale is not None
                  else 1.0 / np.sqrt(q.shape[-1]))
    if not use_pallas():
        return _chunk_reference_fwd(q, k, v, kv_lengths, scale, causal,
                                    window, q_start, k_start)
    sq, d = q.shape[2], q.shape[3]
    sk = k.shape[2]
    block_q, block_k = _auto_blocks(block_q, block_k, sk)
    bq = min(block_q, round_up(sq, 8))
    bk = min(block_k, round_up(sk, 128))
    group = q.shape[1] // k.shape[1]
    qp, kp, vp = _pad_qkv(q, k, v, bq, bk)
    o, lse = _run_fwd(qp, kp, vp, kv_lengths, scale, causal, sq, sk, bq, bk,
                      group=group, window=window, q_off=q_start,
                      k_off=k_start)
    return o[:, :, :sq, :d], lse[:, :, :sq]


def flash_chunk_bwd(q, k, v, do, lse, delta, *, q_start, k_start,
                    causal=False, window=None, kv_lengths=None,
                    softmax_scale=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """Flash backward over one chunk pair with the GLOBAL ``lse``/``delta``
    residuals -> ``(dq, dk, dv)``. Exactness rests on the flash-backward
    decomposition: with the global log-sum-exp, per-chunk contributions sum
    to the full-sequence gradients."""
    scale = float(softmax_scale if softmax_scale is not None
                  else 1.0 / np.sqrt(q.shape[-1]))
    if not use_pallas():
        return _chunk_reference_bwd(q, k, v, do, lse, delta, kv_lengths,
                                    scale, causal, window, q_start, k_start)
    sq, d = q.shape[2], q.shape[3]
    sk = k.shape[2]
    block_q, block_k = _auto_blocks(block_q, block_k, sk)
    bq = min(block_q, round_up(sq, 8))
    bk = min(block_k, round_up(sk, 128))
    group = q.shape[1] // k.shape[1]
    sqp = round_up(sq, bq)
    qp, kp, vp = _pad_qkv(q, k, v, bq, bk)
    dop = jnp.pad(do, ((0, 0), (0, 0), (0, sqp - sq),
                       (0, qp.shape[3] - d)))
    deltap = jnp.pad(delta, ((0, 0), (0, 0), (0, sqp - sq)))
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, sqp - sq)),
                   constant_values=_LSE_PAD)
    dq, dk, dv = _run_bwd(qp, kp, vp, dop, lsep[:, :, None, :],
                          deltap[:, :, None, :], kv_lengths, scale, causal,
                          sq, sk, bq, bk, group=group, window=window,
                          q_off=q_start, k_off=k_start)
    return (dq[:, :, :sq, :d], dk[:, :, :k.shape[2], :d],
            dv[:, :, :k.shape[2], :d])


# ---------------------------------------------------------------------------
# reference (XLA) path
# ---------------------------------------------------------------------------

def _mha_reference(q, k, v, kv_lengths, scale, causal, window=None,
                   dropout_rate=0.0, dropout_seed=None):
    sq, sk = q.shape[2], k.shape[2]
    if k.shape[1] != q.shape[1]:     # GQA/MQA: broadcast the K/V heads
        group = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    col = jnp.arange(sk)[None, None, None, :]
    row = jnp.arange(sq)[None, None, :, None]
    valid = jnp.ones(s.shape, dtype=bool)
    if kv_lengths is not None:
        valid = jnp.logical_and(valid, col < kv_lengths[:, None, None, None])
    if causal:
        valid = jnp.logical_and(valid, col <= row + (sk - sq))
    if window is not None:
        valid = jnp.logical_and(valid, col > row + (sk - sq) - window)
    s = jnp.where(valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (empty batch elements / kv_lengths == 0) get zero
    # output + zero grads, matching the Pallas path's l == 0 guard
    p = jnp.where(jnp.any(valid, axis=-1, keepdims=True), p, 0.0)
    if dropout_rate > 0.0:
        b, h = p.shape[0], p.shape[1]
        combo = _drop_combo(
            jnp.arange(b, dtype=jnp.uint32)[:, None, None, None],
            jnp.arange(h, dtype=jnp.uint32)[None, :, None, None])
        keep = _hash_keep(jnp.asarray(dropout_seed).reshape(()), combo,
                          p.shape, dropout_rate)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    kv_lengths: Optional[jax.Array] = None,
    sliding_window: Optional[int] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """Multi-head attention ``softmax(scale * q @ k^T + mask) @ v``.

    Args:
      q: ``[batch, heads, seq_q, head_dim]``.
      k, v: ``[batch, kv_heads, seq_k, head_dim]`` — ``kv_heads`` may divide
        ``heads`` (GQA; ``kv_heads == 1`` is MQA): grouped query heads read
        the same K/V blocks inside the kernel, so no broadcast copy of K/V
        ever lands in HBM, and dK/dV accumulate over the group in one
        scratch pass.
      causal: upper-triangular mask with the standard ``seq_k - seq_q`` offset
        (reference ``scaled_upper_triang_masked_softmax`` semantics).
      softmax_scale: defaults to ``1/sqrt(head_dim)``.
      kv_lengths: optional int32 ``[batch]`` valid key/value lengths (the
        fmha padded-batch capability, ``apex/contrib/fmha/fmha.py:41-56``).
      sliding_window: keep only the last ``sliding_window`` keys per query
        (incl. self; requires ``causal``) — Mistral-class local attention.
        Far-past K blocks are skipped entirely, so cost is O(seq * window)
        rather than O(seq^2).
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("flash_attention expects [batch, heads, seq, dim]")
    if k.shape[1] != v.shape[1] or q.shape[1] % k.shape[1]:
        raise ValueError(
            f"kv_heads ({k.shape[1]}) must divide query heads "
            f"({q.shape[1]}) for GQA/MQA")
    if sliding_window is not None:
        if not causal:
            raise ValueError("sliding_window requires causal attention")
        if sliding_window < 1:
            raise ValueError(f"sliding_window must be >= 1, got "
                             f"{sliding_window}")
    scale = float(softmax_scale if softmax_scale is not None
                  else 1.0 / np.sqrt(q.shape[-1]))
    if not use_pallas():
        return _mha_reference(q, k, v, kv_lengths, scale, causal,
                              sliding_window)
    block_q, block_k = _auto_blocks(block_q, block_k, k.shape[2])
    bq = min(block_q, round_up(q.shape[2], 8))
    bk = min(block_k, round_up(k.shape[2], 128))
    return _flash(q, k, v, kv_lengths, scale, causal, bq, bk,
                  sliding_window)
