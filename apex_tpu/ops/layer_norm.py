"""Fused LayerNorm / RMSNorm — Pallas TPU kernels with custom VJP.

Capability parity with ``fused_layer_norm_cuda``
(``csrc/layer_norm_cuda.cpp:445-459``, kernels ``csrc/layer_norm_cuda_kernel.cu``):
forward returns normalized output with per-row mean/invvar statistics; backward
produces dx and (for affine) dweight/dbias; RMSNorm shares the machinery; a
``memory_efficient`` variant recomputes x̂ from the output instead of saving
the input (reference: ``apex/normalization/fused_layer_norm.py:32-191``).

TPU design: rows are tiled onto the grid, each block normalizes ``(BM, H)`` in
VMEM with fp32 accumulation (the CUDA warp-shuffle Welford reduction,
``layer_norm_cuda_kernel.cu:353-426``, becomes a VPU row reduction); dweight /
dbias are accumulated as per-block partials then summed by XLA, replacing the
two-stage cross-CTA reduction of ``cuComputeGradInput``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._support import block_rows, cdiv, min_sublane, pallas_interpret, round_up, use_pallas

_VMEM_BUDGET = 4 * 1024 * 1024  # per-operand block budget, bytes


def _norm_shapes(x, normalized_shape):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    h = int(np.prod(normalized_shape))
    m = x.size // h
    return m, h, tuple(normalized_shape)


def _block_rows(h_pad: int, dtype) -> int:
    # cap tuning history + constraints documented in the shared helper
    return block_rows(h_pad, dtype, vmem_budget=_VMEM_BUDGET)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, invvar_ref, *, h, eps,
                is_rms, has_affine, out_dtype):
    xf = x_ref[:].astype(jnp.float32)
    bm, hp = xf.shape
    mask = jax.lax.broadcasted_iota(jnp.int32, (bm, hp), 1) < h
    xf = jnp.where(mask, xf, 0.0)
    if is_rms:
        mean = jnp.zeros((bm, 1), jnp.float32)
        var = jnp.sum(xf * xf, axis=1, keepdims=True) / h
    else:
        mean = jnp.sum(xf, axis=1, keepdims=True) / h
        cent = jnp.where(mask, xf - mean, 0.0)
        var = jnp.sum(cent * cent, axis=1, keepdims=True) / h
    invvar = jax.lax.rsqrt(var + eps)
    xhat = (xf - mean) * invvar
    if has_affine:
        y = xhat * w_ref[:].astype(jnp.float32)
        if b_ref is not None:
            y = y + b_ref[:].astype(jnp.float32)
    else:
        y = xhat
    y_ref[:] = y.astype(out_dtype)
    mean_ref[:] = mean
    invvar_ref[:] = invvar


def _fwd_pallas(x2, w, b, h, eps, is_rms, out_dtype):
    m = x2.shape[0]
    hp = round_up(h, 128)
    bm = _block_rows(hp, x2.dtype)
    grid = (cdiv(m, bm),)
    has_affine = w is not None
    xp = jnp.pad(x2, ((0, 0), (0, hp - h))) if hp != h else x2
    pad_row = lambda a: (jnp.pad(a.reshape(1, -1).astype(jnp.float32),
                                 ((0, 0), (0, hp - h))) if hp != h
                         else a.reshape(1, -1).astype(jnp.float32))
    args = [xp]
    in_specs = [pl.BlockSpec((bm, hp), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    if has_affine:
        args.append(pad_row(w))
        in_specs.append(pl.BlockSpec((1, hp), lambda i: (0, 0), memory_space=pltpu.VMEM))
    if b is not None:
        args.append(pad_row(b))
        in_specs.append(pl.BlockSpec((1, hp), lambda i: (0, 0), memory_space=pltpu.VMEM))

    def kernel(*refs):
        if has_affine and b is not None:
            x_ref, w_ref, b_ref, y_ref, mean_ref, iv_ref = refs
        elif has_affine:
            x_ref, w_ref, y_ref, mean_ref, iv_ref = refs
            b_ref = None
        else:
            x_ref, y_ref, mean_ref, iv_ref = refs
            w_ref = b_ref = None
        _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, iv_ref,
                    h=h, eps=eps, is_rms=is_rms, has_affine=has_affine,
                    out_dtype=out_dtype)

    y, mean, invvar = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bm, hp), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, hp), out_dtype),
            jax.ShapeDtypeStruct((m, 1), jnp.float32),
            jax.ShapeDtypeStruct((m, 1), jnp.float32),
        ],
        interpret=pallas_interpret(),
    )(*args)
    if hp != h:
        y = y[:, :h]
    return y, mean[:, 0], invvar[:, 0]


def _fwd_jnp(x2, w, b, h, eps, is_rms, out_dtype):
    xf = x2.astype(jnp.float32)
    if is_rms:
        mean = jnp.zeros((x2.shape[0],), jnp.float32)
        var = jnp.mean(xf * xf, axis=1)
    else:
        mean = jnp.mean(xf, axis=1)
        var = jnp.mean(jnp.square(xf - mean[:, None]), axis=1)
    invvar = jax.lax.rsqrt(var + eps)
    xhat = (xf - mean[:, None]) * invvar[:, None]
    y = xhat
    if w is not None:
        y = y * w.reshape(1, -1).astype(jnp.float32)
    if b is not None:
        y = y + b.reshape(1, -1).astype(jnp.float32)
    return y.astype(out_dtype), mean, invvar


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_kernel(dy_ref, x_ref, mean_ref, iv_ref, w_ref,
                dx_ref, dw_ref, db_ref, *, h, m_total, is_rms, has_affine, x_dtype):
    dy = dy_ref[:].astype(jnp.float32)
    xf = x_ref[:].astype(jnp.float32)
    bm, hp = dy.shape
    # mask padded columns AND out-of-range tail rows: dw/db reduce over the
    # row axis, so garbage rows in the last block would pollute them
    row_offset = pl.program_id(0) * bm
    row_ok = (jax.lax.broadcasted_iota(jnp.int32, (bm, hp), 0) + row_offset) < m_total
    mask = (jax.lax.broadcasted_iota(jnp.int32, (bm, hp), 1) < h) & row_ok
    dy = jnp.where(mask, dy, 0.0)
    xf = jnp.where(mask, xf, 0.0)
    mean = mean_ref[:]
    invvar = iv_ref[:]
    xhat = (xf - mean) * invvar
    xhat = jnp.where(mask, xhat, 0.0)
    if has_affine:
        wf = w_ref[:].astype(jnp.float32)
        dyw = dy * wf
    else:
        dyw = dy
    c2 = jnp.sum(dyw * xhat, axis=1, keepdims=True) / h
    if is_rms:
        dx = invvar * (dyw - xhat * c2)
    else:
        c1 = jnp.sum(dyw, axis=1, keepdims=True) / h
        dx = invvar * (dyw - c1 - xhat * c2)
    dx_ref[:] = jnp.where(mask, dx, 0.0).astype(x_dtype)
    if has_affine:
        # dweight/dbias: reduce the block's rows down to 8 sublanes and
        # accumulate into a single (8, hp) output revisited by every grid
        # step (TPU grid steps run sequentially); caller sums the 8 rows.
        first = pl.program_id(0) == 0

        @pl.when(first)
        def _():
            dw_ref[:] = jnp.zeros_like(dw_ref)
            if db_ref is not None:
                db_ref[:] = jnp.zeros_like(db_ref)

        contrib = (dy * xhat).reshape(bm // 8, 8, hp)
        dw_ref[:] += jnp.sum(contrib, axis=0)
        if db_ref is not None:
            db_ref[:] += jnp.sum(dy.reshape(bm // 8, 8, hp), axis=0)


def _bwd_pallas(dy2, x2, mean, invvar, w, h, is_rms, has_bias):
    m = x2.shape[0]
    hp = round_up(h, 128)
    bm = _block_rows(hp, x2.dtype)
    grid = (cdiv(m, bm),)
    has_affine = w is not None
    pad = lambda a: jnp.pad(a, ((0, 0), (0, hp - h))) if hp != h else a
    args = [pad(dy2), pad(x2), mean.reshape(-1, 1), invvar.reshape(-1, 1)]
    in_specs = [
        pl.BlockSpec((bm, hp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((bm, hp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((bm, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((bm, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]
    if has_affine:
        wp = w.reshape(1, -1).astype(jnp.float32)
        if hp != h:
            wp = jnp.pad(wp, ((0, 0), (0, hp - h)))
        args.append(wp)
        in_specs.append(pl.BlockSpec((1, hp), lambda i: (0, 0), memory_space=pltpu.VMEM))

    out_specs = [pl.BlockSpec((bm, hp), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((m, hp), x2.dtype)]
    if has_affine:
        out_specs.append(pl.BlockSpec((8, hp), lambda i: (0, 0), memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((8, hp), jnp.float32))
        if has_bias:
            out_specs.append(pl.BlockSpec((8, hp), lambda i: (0, 0), memory_space=pltpu.VMEM))
            out_shape.append(jax.ShapeDtypeStruct((8, hp), jnp.float32))

    def kernel(*refs):
        n_in = len(args)
        ins, outs = refs[:n_in], refs[n_in:]
        dy_ref, x_ref, mean_ref, iv_ref = ins[:4]
        w_ref = ins[4] if has_affine else None
        dx_ref = outs[0]
        dw_ref = outs[1] if has_affine else None
        db_ref = outs[2] if (has_affine and has_bias) else None
        _bwd_kernel(dy_ref, x_ref, mean_ref, iv_ref, w_ref, dx_ref, dw_ref, db_ref,
                    h=h, m_total=m, is_rms=is_rms, has_affine=has_affine,
                    x_dtype=x2.dtype)

    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=pallas_interpret(),
    )(*args)
    dx = outs[0][:, :h]
    dw = db = None
    if has_affine:
        dw = jnp.sum(outs[1], axis=0)[:h]
        if has_bias:
            db = jnp.sum(outs[2], axis=0)[:h]
    return dx, dw, db


def _bwd_jnp(dy2, x2, mean, invvar, w, h, is_rms, has_bias):
    dy = dy2.astype(jnp.float32)
    xf = x2.astype(jnp.float32)
    xhat = (xf - mean[:, None]) * invvar[:, None]
    dyw = dy * w.reshape(1, -1).astype(jnp.float32) if w is not None else dy
    c2 = jnp.mean(dyw * xhat, axis=1, keepdims=True)
    if is_rms:
        dx = invvar[:, None] * (dyw - xhat * c2)
    else:
        c1 = jnp.mean(dyw, axis=1, keepdims=True)
        dx = invvar[:, None] * (dyw - c1 - xhat * c2)
    dw = jnp.sum(dy * xhat, axis=0) if w is not None else None
    db = jnp.sum(dy, axis=0) if (w is not None and has_bias) else None
    return dx.astype(x2.dtype), dw, db


# ---------------------------------------------------------------------------
# public functional API (mirrors apex/normalization/fused_layer_norm.py)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _norm(x, weight, bias, normalized_shape, eps, is_rms, memory_efficient,
          out_dtype):
    y, _, _ = _norm_fwd_impl(x, weight, bias, normalized_shape, eps, is_rms,
                             out_dtype)
    return y


def _norm_fwd_impl(x, weight, bias, normalized_shape, eps, is_rms,
                   out_dtype=None):
    m, h, _ = _norm_shapes(x, normalized_shape)
    x2 = x.reshape(m, h)
    if out_dtype is None:
        # default: promote semantics (bf16 x + fp32 weight -> fp32 out);
        # callers that immediately consume the output in the compute dtype
        # pass out_dtype=x.dtype so the kernel writes half the bytes and
        # no downstream convert materializes (round 5: each transformer
        # LN wrote a 25 MB fp32 tensor a GEMM then re-cast to bf16)
        out_dtype = (x.dtype if weight is None
                     else jnp.promote_types(x.dtype, weight.dtype))
        if out_dtype == jnp.float64:
            out_dtype = jnp.float32
    fwd = _fwd_pallas if use_pallas() else _fwd_jnp
    y, mean, invvar = fwd(x2, weight, bias, h, eps, is_rms, out_dtype)
    return y.reshape(x.shape), mean, invvar


def _norm_vjp_fwd(x, weight, bias, normalized_shape, eps, is_rms,
                  memory_efficient, out_dtype):
    y, mean, invvar = _norm_fwd_impl(x, weight, bias, normalized_shape, eps,
                                     is_rms, out_dtype)
    # zero-size marker carrying x's dtype (x itself may not be saved)
    x_dtype_marker = jnp.zeros((0,), x.dtype)
    if memory_efficient:
        # save output instead of input; x̂ is recomputed in bwd
        # (reference memory-efficient variant, fused_layer_norm.py:43-77)
        return y, (None, y, mean, invvar, weight, bias, x_dtype_marker)
    return y, (x, y, mean, invvar, weight, bias, x_dtype_marker)


def _norm_vjp_bwd(normalized_shape, eps, is_rms, memory_efficient,
                  out_dtype, res, dy):
    x_dtype = res[-1].dtype
    res = res[:-1]
    if memory_efficient:
        _, y, mean, invvar, weight, bias = res
        m, h, _ = _norm_shapes(y, normalized_shape)
        y2 = y.reshape(m, h).astype(jnp.float32)
        if weight is not None:
            wf = weight.reshape(1, -1).astype(jnp.float32)
            safe_w = jnp.where(jnp.abs(wf) < 1e-12, 1.0, wf)
            y2 = y2 - (bias.reshape(1, -1).astype(jnp.float32) if bias is not None else 0.0)
            xhat = y2 / safe_w
        else:
            xhat = y2
        x2 = xhat / invvar[:, None] + mean[:, None]
        x2 = x2.astype(y.dtype)
    else:
        x, y, mean, invvar, weight, bias = res
        m, h, _ = _norm_shapes(x, normalized_shape)
        x2 = x.reshape(m, h)
    dy2 = dy.reshape(m, h)
    has_bias = bias is not None
    bwd = _bwd_pallas if use_pallas() else _bwd_jnp
    dx, dw, db = bwd(dy2, x2, mean, invvar, weight, h, is_rms, has_bias)
    dx = dx.reshape(dy.shape).astype(x_dtype)
    dwo = dw.reshape(weight.shape).astype(weight.dtype) if weight is not None else None
    dbo = db.reshape(bias.shape).astype(bias.dtype) if has_bias else None
    return dx, dwo, dbo


_norm.defvjp(_norm_vjp_fwd, _norm_vjp_bwd)


def fused_layer_norm_affine(x, weight, bias, normalized_shape, eps: float = 1e-5,
                            memory_efficient: bool = False, out_dtype=None):
    """Reference: ``fused_layer_norm_affine`` (``fused_layer_norm.py:194-204``).
    ``out_dtype=None`` keeps promote semantics; pass ``x.dtype`` when the
    consumer runs in the compute dtype anyway (halves the kernel's write
    bytes under mixed precision — see _norm_fwd_impl)."""
    return _norm(x, weight, bias, _as_shape(normalized_shape), eps, False,
                 memory_efficient, out_dtype)


def fused_layer_norm(x, normalized_shape, eps: float = 1e-5,
                     memory_efficient: bool = False, out_dtype=None):
    """Non-affine variant (``fused_layer_norm.py:207-214``)."""
    return _norm(x, None, None, _as_shape(normalized_shape), eps, False,
                 memory_efficient, out_dtype)


def fused_rms_norm_affine(x, weight, normalized_shape, eps: float = 1e-5,
                          memory_efficient: bool = False, out_dtype=None):
    """Reference: ``fused_rms_norm_affine`` (``fused_layer_norm.py:217-227``)."""
    return _norm(x, weight, None, _as_shape(normalized_shape), eps, True,
                 memory_efficient, out_dtype)


def fused_rms_norm(x, normalized_shape, eps: float = 1e-5,
                   memory_efficient: bool = False, out_dtype=None):
    return _norm(x, None, None, _as_shape(normalized_shape), eps, True,
                 memory_efficient, out_dtype)


def _as_shape(s) -> Tuple[int, ...]:
    return (s,) if isinstance(s, int) else tuple(s)
