"""Fused 1x1-conv (GEMM) + input-BN-affine/ReLU + output-stats epilogue.

TPU-native answer to the reference's fused convolution epilogues
(``apex/contrib/conv_bias_relu/conv_bias_relu.py:12-78``,
``apex/contrib/bottleneck/bottleneck.py:134-262`` — cuDNN-frontend fused
conv graphs): on TPU the ResNet bottleneck's HBM bound is the separate
batch-norm passes over every conv output, so this kernel folds three
memory passes into one:

  * the BN normalize+ReLU of the *input* activation is applied on the fly
    while tiles stream in (no materialized normalized tensor),
  * the 1x1 convolution is the MXU GEMM ``z @ W``,
  * the per-channel batch statistics of the *output* (needed by the next
    BN) are accumulated in a VMEM epilogue while output tiles stream out
    (no separate statistics pass).

Statistics are **shifted** sums ``(sum(y - c), sum((y - c)^2))`` with ``c``
the running mean: the shift centers the one-pass moment computation so the
``E[x^2] - E[x]^2`` form does not catastrophically cancel (the reason the
reference uses Welford kernels, ``csrc/welford.cu``).

The backward kernel is one pass too: it recomputes ``z`` from the saved
raw input, folds the statistics cotangent into ``dy`` (the term
``ds0 + 2(y-c)*ds1``), and produces ``dx``, ``dW``, ``da``, ``db`` plus the
channel reductions in a single read of (x, dy, y).

Layout contract: ``x: [M, K]``, ``w: [K, N]`` with M = batch*H*W flattened
outside — NHWC is the TPU-native layout so a 1x1 conv IS this GEMM.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._support import cdiv, pallas_interpret, use_pallas

__all__ = ["conv1x1_bn_act", "conv3x3_bn_act"]

_BM_CANDIDATES = (1024, 896, 768, 640, 512, 448, 384, 320, 256, 224, 192,
                  160, 128, 112, 96, 80, 64, 48, 32, 16)


def _pick_bm(m: int, per_row_bytes: int, budget: int) -> int:
    fitting = [bm for bm in _BM_CANDIDATES if bm * per_row_bytes <= budget]
    if not fitting:
        return 16
    for bm in fitting:                     # prefer a divisor of M (no mask)
        if m % bm == 0:
            return bm
    return fitting[0]


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, a_ref, b_ref, w_ref, c_ref, y_ref, s_ref, acc_ref, *,
                affine, relu, m, bm, out_dtype):
    i = pl.program_id(0)
    nm = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    if affine:
        z = x.astype(jnp.float32) * a_ref[...] + b_ref[...]
        if relu:
            z = jnp.maximum(z, 0.0)
        z = z.astype(w_ref.dtype)
    else:
        z = x.astype(w_ref.dtype)
    y = jnp.dot(z, w_ref[...], preferred_element_type=jnp.float32)
    yc = y - c_ref[...]
    if m % bm != 0:
        rows = jax.lax.broadcasted_iota(jnp.int32, yc.shape, 0) + i * bm
        yc = jnp.where(rows < m, yc, 0.0)
    acc_ref[0:1, :] += jnp.sum(yc, axis=0, keepdims=True)
    acc_ref[1:2, :] += jnp.sum(yc * yc, axis=0, keepdims=True)
    y_ref[...] = y.astype(out_dtype)

    @pl.when(i == nm - 1)
    def _():
        s_ref[...] = acc_ref[...]


def _fwd_pallas(x2, a, b, w, shift, *, affine, relu):
    m, k = x2.shape
    n = w.shape[1]
    esz = jnp.dtype(x2.dtype).itemsize
    # resident: w + stats acc; streamed per row: x, y (double-buffered) + f32 y
    budget = 6 * 1024 * 1024 - w.size * jnp.dtype(w.dtype).itemsize
    bm = _pick_bm(m, per_row_bytes=2 * esz * (k + n) + 4 * n,
                  budget=max(budget, 1 << 20))
    grid = (cdiv(m, bm),)
    a2 = a.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    b2 = b.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    c2 = shift.reshape(1, n)
    kernel = functools.partial(_fwd_kernel, affine=affine, relu=relu, m=m,
                               bm=bm, out_dtype=x2.dtype)
    y, s = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec(a2.shape, lambda i: (0, 0)),
            pl.BlockSpec(b2.shape, lambda i: (0, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((2, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x2.dtype),
            jax.ShapeDtypeStruct((2, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((2, n), jnp.float32)],
        interpret=pallas_interpret(),
    )(x2, a2, b2, w, c2)
    return y, s


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_kernel(x_ref, a_ref, b_ref, w_ref, c_ref, y_ref, dy_ref, ds_ref,
                dx_ref, dw_ref, dab_ref, dwacc_ref, dabacc_ref, *,
                affine, relu, m, bm):
    i = pl.program_id(0)
    nm = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        dwacc_ref[...] = jnp.zeros_like(dwacc_ref)
        if affine:
            dabacc_ref[...] = jnp.zeros_like(dabacc_ref)

    x32 = x_ref[...].astype(jnp.float32)
    if m % bm != 0:
        # tail rows may read padding (NaN in interpret mode): zero them so
        # they cannot reach the dW/da/db accumulators through 0*NaN
        xrows = jax.lax.broadcasted_iota(jnp.int32, x32.shape, 0) + i * bm
        x32 = jnp.where(xrows < m, x32, 0.0)
    # fold the statistics cotangent into dy: s = (sum(y-c), sum((y-c)^2))
    dy_eff = (dy_ref[...].astype(jnp.float32) + ds_ref[0:1, :]
              + 2.0 * (y_ref[...].astype(jnp.float32) - c_ref[...])
              * ds_ref[1:2, :])
    if affine:
        pre = x32 * a_ref[...] + b_ref[...]
        z = jnp.maximum(pre, 0.0) if relu else pre
    else:
        z = x32
    if m % bm != 0:
        rows = jax.lax.broadcasted_iota(jnp.int32, dy_eff.shape, 0) + i * bm
        dy_eff = jnp.where(rows < m, dy_eff, 0.0)
    dy_c = dy_eff.astype(w_ref.dtype)
    dwacc_ref[...] += jax.lax.dot_general(
        z.astype(w_ref.dtype), dy_c, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dz = jax.lax.dot_general(
        dy_c, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if affine:
        dg = jnp.where(pre > 0.0, dz, 0.0) if relu else dz
        dabacc_ref[0:1, :] += jnp.sum(dg * x32, axis=0, keepdims=True)
        dabacc_ref[1:2, :] += jnp.sum(dg, axis=0, keepdims=True)
        dx = dg * a_ref[...]
    else:
        dx = dz
    dx_ref[...] = dx.astype(dx_ref.dtype)

    @pl.when(i == nm - 1)
    def _():
        dw_ref[...] = dwacc_ref[...]
        if affine:
            dab_ref[...] = dabacc_ref[...]


def _bwd_pallas(x2, a, b, w, shift, y, dy, ds, *, affine, relu):
    m, k = x2.shape
    n = w.shape[1]
    esz = jnp.dtype(x2.dtype).itemsize
    wbytes = w.size * jnp.dtype(w.dtype).itemsize + 4 * w.size
    budget = 9 * 1024 * 1024 - wbytes
    bm = _pick_bm(m, per_row_bytes=2 * esz * (2 * k + 2 * n) + 4 * (k + n),
                  budget=max(budget, 1 << 20))
    grid = (cdiv(m, bm),)
    a2 = a.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    b2 = b.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    c2 = shift.reshape(1, n)
    kernel = functools.partial(_bwd_kernel, affine=affine, relu=relu, m=m,
                               bm=bm)
    nab = k if affine else 1
    dx, dw, dab = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec(a2.shape, lambda i: (0, 0)),
            pl.BlockSpec(b2.shape, lambda i: (0, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((2, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((2, nab), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, k), x2.dtype),
            jax.ShapeDtypeStruct((k, n), jnp.float32),
            jax.ShapeDtypeStruct((2, nab), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((k, n), jnp.float32),
                        pltpu.VMEM((2, nab), jnp.float32)],
        interpret=pallas_interpret(),
    )(x2, a2, b2, w, c2, y, dy, ds)
    return dx, dw, dab


# ---------------------------------------------------------------------------
# reference composition (non-TPU fallback; also the parity oracle in tests)
# ---------------------------------------------------------------------------

def _ref_impl(x2, a, b, w, shift, *, affine, relu):
    if affine:
        z = x2.astype(jnp.float32) * a[None, :] + b[None, :]
        if relu:
            z = jnp.maximum(z, 0.0)
        z = z.astype(w.dtype)
    else:
        z = x2.astype(w.dtype)
    y = jnp.dot(z, w, preferred_element_type=jnp.float32)
    yc = y - shift[None, :]
    s = jnp.stack([jnp.sum(yc, axis=0), jnp.sum(yc * yc, axis=0)])
    return y.astype(x2.dtype), s


# ---------------------------------------------------------------------------
# custom-VJP wrappers (one per static (affine, relu) combination)
# ---------------------------------------------------------------------------

def _build_vjp_op(fwd_pallas, bwd_pallas, affine: bool, relu: bool):
    """Shared custom-VJP scaffolding for the fused conv kernels: primal =
    ``fwd_pallas``, cotangents (incl. the stats cotangent) routed through
    ``bwd_pallas``; da/db come back through the [2, K] accumulator, the
    shift is statistics-driven (zero gradient)."""

    def fwd_impl(x, a, b, w, shift):
        return fwd_pallas(x, a, b, w, shift, affine=affine, relu=relu)

    @jax.custom_vjp
    def op(x, a, b, w, shift):
        return fwd_impl(x, a, b, w, shift)

    def op_fwd(x, a, b, w, shift):
        y, s = fwd_impl(x, a, b, w, shift)
        return (y, s), (x, a, b, w, shift, y)

    def op_bwd(res, cots):
        x, a, b, w, shift, y = res
        dy, ds = cots
        dx, dw, dab = bwd_pallas(x, a, b, w, shift, y, dy, ds,
                                 affine=affine, relu=relu)
        if affine:
            da = dab[0].astype(a.dtype)
            db = dab[1].astype(b.dtype)
        else:
            da = jnp.zeros_like(a)
            db = jnp.zeros_like(b)
        return (dx, da, db, dw.astype(w.dtype), jnp.zeros_like(shift))

    op.defvjp(op_fwd, op_bwd)
    return op


@functools.lru_cache(maxsize=None)
def _make_op(affine: bool, relu: bool):
    return _build_vjp_op(_fwd_pallas, _bwd_pallas, affine, relu)


def conv1x1_bn_act(x, w, a: Optional[jax.Array] = None,
                   b: Optional[jax.Array] = None, *, relu: bool = False,
                   stats_shift: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """Fused ``y = relu(x*a + b) @ w`` with per-channel output statistics.

    ``x: [..., K]`` (flattened to [M, K]), ``w: [K, N]``; ``a``/``b`` are the
    input BN's per-channel normalize coefficients (fp32, [K]) — omit both for
    an identity input transform (input already normalized). Returns
    ``(y [..., N], stats [2, N])`` with ``stats = (sum(y-c), sum((y-c)^2))``
    over rows, ``c = stats_shift`` (fp32 [N], typically the running mean —
    centers the one-pass moments; zeros when omitted).
    """
    affine = a is not None
    if not affine and (b is not None or relu):
        raise ValueError("b/relu require the input affine: pass both a and "
                         "b, or neither")
    k = x.shape[-1]
    n = w.shape[1]
    # the backward kernel keeps W (bf16) + a fp32 dW accumulator resident in
    # VMEM (~6 bytes/element); beyond ~1.5M weight elements that plus the
    # streamed tiles exceeds the ~16MB scoped-vmem budget — fall back to the
    # XLA composition (hit only by the deepest stage's downsample matrix)
    pallas_ok = use_pallas() and k * n <= (3 << 19)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, k)
    if stats_shift is None:
        stats_shift = jnp.zeros((n,), jnp.float32)
    stats_shift = jax.lax.stop_gradient(stats_shift.astype(jnp.float32))
    if pallas_ok:
        af = a.astype(jnp.float32) if affine else jnp.zeros((1,), jnp.float32)
        bf = b.astype(jnp.float32) if affine else jnp.zeros((1,), jnp.float32)
        y, s = _make_op(affine, relu)(x2, af, bf, w, stats_shift)
    else:
        if affine:
            y, s = _ref_impl(x2, a.astype(jnp.float32),
                             b.astype(jnp.float32), w, stats_shift,
                             affine=True, relu=relu)
        else:
            y, s = _ref_impl(x2, None, None, w, stats_shift,
                             affine=False, relu=False)
    return y.reshape(*lead, n), s


# ===========================================================================
# 3x3 convolution (stride 1, SAME) + input BN-affine/ReLU + stats epilogue
# ===========================================================================
#
# The bottleneck's middle conv as a Pallas kernel so the whole block
# interior stays in one layout domain (XLA<->Pallas layout copies are what
# ate the 1x1 kernels' win — PERF.md round 3). Each grid step processes a
# few whole images: the 3x3 is nine shifted [bn*H*W, K] x [K, N] GEMMs
# over a zero-padded VMEM copy of the normalized input — no halo exchange
# between blocks because blocks never split an image. The backward is one
# pass too: reads (x, dy, y), writes dx, accumulates dW[3,3]/da/db in VMEM.

def _c3_zpad(z, H, W):
    """[bn, H, W, C] -> [bn, H+2, W+2, C] zero-padded (VMEM)."""
    return jnp.pad(z, ((0, 0), (1, 1), (1, 1), (0, 0)))


def _c3_fwd_kernel(x_ref, a_ref, b_ref, w_ref, c_ref, y_ref, s_ref,
                   acc_ref, *, affine, relu, H, W, out_dtype):
    i = pl.program_id(0)
    nm = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                   # [bn, H, W, K]
    bn, _, _, k = x.shape
    n = w_ref.shape[-1]
    if affine:
        z = x.astype(jnp.float32) * a_ref[...] + b_ref[...]
        if relu:
            z = jnp.maximum(z, 0.0)
        z = z.astype(w_ref.dtype)
    else:
        z = x.astype(w_ref.dtype)
    zp = _c3_zpad(z, H, W)
    acc = jnp.zeros((bn * H * W, n), jnp.float32)
    for dr in range(3):
        for dc in range(3):
            tap = zp[:, dr:dr + H, dc:dc + W, :].reshape(bn * H * W, k)
            acc += jnp.dot(tap, w_ref[dr, dc],
                           preferred_element_type=jnp.float32)
    yc = acc - c_ref[...]
    acc_ref[0:1, :] += jnp.sum(yc, axis=0, keepdims=True)
    acc_ref[1:2, :] += jnp.sum(yc * yc, axis=0, keepdims=True)
    y_ref[...] = acc.reshape(bn, H, W, n).astype(out_dtype)

    @pl.when(i == nm - 1)
    def _():
        s_ref[...] = acc_ref[...]


def _c3_pick_bn(nimg, H, W, k, n, bwd=False):
    """Images per grid step under a VMEM budget. The kernel's working set
    is much larger than the streamed tiles: the padded z copy, the fp32
    accumulator, and the nine materialized tap slices all live on the
    Mosaic stack — budget accordingly (measured: ~5.3 MB/image at
    56x56x64 forward)."""
    per_img = H * W * (2 * k + 2 * n      # x + y tiles
                       + 9 * 2 * k        # materialized tap slices
                       + 4 * n + 4 * k)   # fp32 acc + padded z
    if bwd:
        per_img += H * W * (4 * k         # fp32 dzp
                            + 9 * 2 * n   # dy taps
                            + 4 * k)      # dg/x32
    budget = 8 * 1024 * 1024
    bn = max(1, min(8, budget // max(per_img, 1)))
    while nimg % bn:
        bn -= 1
    return bn


def _c3_fwd_pallas(x, a, b, w, shift, *, affine, relu):
    nimg, H, W, k = x.shape
    n = w.shape[-1]
    bn = _c3_pick_bn(nimg, H, W, k, n)
    grid = (nimg // bn,)
    a2 = a.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    b2 = b.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    c2 = shift.reshape(1, n)
    kernel = functools.partial(_c3_fwd_kernel, affine=affine, relu=relu,
                               H=H, W=W, out_dtype=x.dtype)
    y, s = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, H, W, k), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec(a2.shape, lambda i: (0, 0)),
            pl.BlockSpec(b2.shape, lambda i: (0, 0)),
            pl.BlockSpec((3, 3, k, n), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, H, W, n), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((2, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nimg, H, W, n), x.dtype),
            jax.ShapeDtypeStruct((2, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((2, n), jnp.float32)],
        interpret=pallas_interpret(),
    )(x, a2, b2, w, c2)
    return y, s


def _c3_bwd_kernel(x_ref, a_ref, b_ref, w_ref, c_ref, y_ref, dy_ref,
                   ds_ref, dx_ref, dw_ref, dab_ref, dwacc_ref, dabacc_ref,
                   *, affine, relu, H, W):
    i = pl.program_id(0)
    nm = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        dwacc_ref[...] = jnp.zeros_like(dwacc_ref)
        if affine:
            dabacc_ref[...] = jnp.zeros_like(dabacc_ref)

    x = x_ref[...]                                   # [bn, H, W, K]
    bn, _, _, k = x.shape
    n = w_ref.shape[-1]
    # compute the effective cotangent and cast to bf16 in one expression so
    # the fp32 temporaries die immediately (VMEM stack pressure)
    dy_c = (dy_ref[...].astype(jnp.float32)
            + ds_ref[0:1, :].reshape(1, 1, 1, n)
            + 2.0 * (y_ref[...].astype(jnp.float32)
                     - c_ref[...].reshape(1, 1, 1, n))
            * ds_ref[1:2, :].reshape(1, 1, 1, n)).astype(w_ref.dtype)
    if affine:
        pre = (x.astype(jnp.float32) * a_ref[...] + b_ref[...])
        mask = pre > 0.0                              # bool, relu subgrad
        z = jnp.maximum(pre, 0.0) if relu else pre
        zb = z.astype(w_ref.dtype)
    else:
        zb = x.astype(w_ref.dtype)
    zp = _c3_zpad(zb, H, W)
    # wgrad needs a 2D contraction (Mosaic matmul: single contracting
    # dim); the dgrad dot runs ND (contract the trailing channel dim)
    dy2 = dy_c.reshape(bn * H * W, n)
    dzp = jnp.zeros((bn, H + 2, W + 2, k), jnp.float32)
    for dr in range(3):
        for dc in range(3):
            tap = zp[:, dr:dr + H, dc:dc + W, :]
            dwacc_ref[dr, dc] += jax.lax.dot_general(
                tap.reshape(bn * H * W, k), dy2, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dtap = jax.lax.dot_general(
                dy_c, w_ref[dr, dc], (((3,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)   # [bn, H, W, K]
            # scatter-add is unsupported in Mosaic: accumulate via a
            # statically-padded add instead
            dzp = dzp + jnp.pad(
                dtap, ((0, 0), (dr, 2 - dr), (dc, 2 - dc), (0, 0)))
    dz = dzp[:, 1:H + 1, 1:W + 1, :]
    if affine:
        dg = jnp.where(mask, dz, 0.0) if relu else dz
        dabacc_ref[0:1, :] += jnp.sum(
            dg * x.astype(jnp.float32), axis=(0, 1, 2)).reshape(1, k)
        dabacc_ref[1:2, :] += jnp.sum(dg, axis=(0, 1, 2)).reshape(1, k)
        dx = dg * a_ref[...]
    else:
        dx = dz
    dx_ref[...] = dx.astype(dx_ref.dtype)

    @pl.when(i == nm - 1)
    def _():
        dw_ref[...] = dwacc_ref[...]
        if affine:
            dab_ref[...] = dabacc_ref[...]


def _c3_bwd_pallas(x, a, b, w, shift, y, dy, ds, *, affine, relu):
    nimg, H, W, k = x.shape
    n = w.shape[-1]
    bn = _c3_pick_bn(nimg, H, W, k, n, bwd=True)
    grid = (nimg // bn,)
    a2 = a.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    b2 = b.reshape(1, k) if affine else jnp.zeros((1, 1), jnp.float32)
    c2 = shift.reshape(1, n)
    kernel = functools.partial(_c3_bwd_kernel, affine=affine, relu=relu,
                               H=H, W=W)
    nab = k if affine else 1
    dx, dw, dab = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, H, W, k), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec(a2.shape, lambda i: (0, 0)),
            pl.BlockSpec(b2.shape, lambda i: (0, 0)),
            pl.BlockSpec((3, 3, k, n), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((bn, H, W, n), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bn, H, W, n), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((2, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, H, W, k), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, k, n), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((2, nab), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nimg, H, W, k), x.dtype),
            jax.ShapeDtypeStruct((3, 3, k, n), jnp.float32),
            jax.ShapeDtypeStruct((2, nab), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3, 3, k, n), jnp.float32),
                        pltpu.VMEM((2, nab), jnp.float32)],
        interpret=pallas_interpret(),
    )(x, a2, b2, w, c2, y, dy, ds)
    return dx, dw, dab


def _c3_ref_impl(x, a, b, w, shift, *, affine, relu):
    """XLA composition oracle for the 3x3 kernel."""
    if affine:
        z = x.astype(jnp.float32) * a.reshape(1, 1, 1, -1) \
            + b.reshape(1, 1, 1, -1)
        if relu:
            z = jnp.maximum(z, 0.0)
        z = z.astype(w.dtype)
    else:
        z = x.astype(w.dtype)
    # no preferred_element_type: its f32 output makes the conv's autodiff
    # transpose mix f32 cotangents with bf16 weights (dtype error); stats
    # from the materialized-output dtype match the unfused baseline anyway
    y = jax.lax.conv_general_dilated(
        z, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    yc = y.astype(jnp.float32) - shift.reshape(1, 1, 1, -1)
    s = jnp.stack([jnp.sum(yc, axis=(0, 1, 2)),
                   jnp.sum(yc * yc, axis=(0, 1, 2))])
    return y.astype(x.dtype), s


@functools.lru_cache(maxsize=None)
def _make_c3_op(affine: bool, relu: bool):
    return _build_vjp_op(_c3_fwd_pallas, _c3_bwd_pallas, affine, relu)


def conv3x3_bn_act(x, w, a: Optional[jax.Array] = None,
                   b: Optional[jax.Array] = None, *, relu: bool = False,
                   stats_shift: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """Fused 3x3 stride-1 SAME conv with input BN-affine/ReLU and output
    statistics — the :func:`conv1x1_bn_act` contract on ``x: [N, H, W, K]``
    and ``w: [3, 3, K, N']``. Falls back to the XLA composition off-TPU."""
    affine = a is not None
    if not affine and (b is not None or relu):
        raise ValueError("b/relu require the input affine: pass both a and "
                         "b, or neither")
    n = w.shape[-1]
    if stats_shift is None:
        stats_shift = jnp.zeros((n,), jnp.float32)
    stats_shift = jax.lax.stop_gradient(stats_shift.astype(jnp.float32))
    # the backward keeps the 3x3 weights (bf16) + a fp32 dW accumulator
    # resident (~54*K*N bytes — excludes the deepest stage's 512x512), and
    # holds one whole image's working set on the VMEM stack (~12 MB at
    # 56x56x64 — excludes the widest stage until the kernel grows manual
    # halo DMAs); outside those bounds the XLA composition is used
    # Stats-dtype note (ADVICE r3): the Pallas kernels (here and 1x1) and
    # _ref_impl reduce statistics from the fp32 GEMM accumulator, while
    # _c3_ref_impl reduces from the bf16-MATERIALIZED output (its docstring
    # explains the autodiff dtype constraint). A fused ResNet whose stages
    # straddle these gates therefore mixes the two sources; the difference
    # is one bf16 rounding of y before the reduction — below BN's eps in
    # every parity test — but it IS a per-path difference, gated exactly
    # here.
    k = w.shape[-2]
    fits = (54 * k * n <= (8 << 20)
            and x.shape[1] * x.shape[2] <= 1024)   # <=32x32 measured bound
    if use_pallas() and fits:
        af = a.astype(jnp.float32) if affine else jnp.zeros((1,),
                                                            jnp.float32)
        bf = b.astype(jnp.float32) if affine else jnp.zeros((1,),
                                                            jnp.float32)
        return _make_c3_op(affine, relu)(x, af, bf, w, stats_shift)
    if affine:
        return _c3_ref_impl(x, a.astype(jnp.float32),
                            b.astype(jnp.float32), w, stats_shift,
                            affine=True, relu=relu)
    return _c3_ref_impl(x, None, None, w, stats_shift, affine=False,
                        relu=False)
