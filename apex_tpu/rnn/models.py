"""RNN model factories.

Parity with ``apex/RNN/models.py:21-55``: ``LSTM``, ``GRU``, ``ReLU``,
``Tanh``, ``mLSTM`` — each returns a functional :class:`RNNModel` with the
reference's gate multipliers and hidden-state counts.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.rnn.backend import RNNModel
from apex_tpu.rnn.cells import (
    gru_cell,
    lstm_cell,
    mlstm_cell,
    rnn_relu_cell,
    rnn_tanh_cell,
)

__all__ = ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM"]


def _build(cell, gate_multiplier, n_hidden, input_size, hidden_size,
           num_layers, bias, batch_first, dropout, bidirectional,
           output_size, multiplicative=False):
    return RNNModel(
        cell=cell, gate_multiplier=gate_multiplier,
        n_hidden_states=n_hidden, input_size=input_size,
        hidden_size=hidden_size, num_layers=num_layers, bias=bias,
        batch_first=batch_first, dropout=dropout,
        bidirectional=bidirectional, output_size=output_size,
        multiplicative=multiplicative)


def LSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size: Optional[int] = None):
    """Reference ``models.py:21-26`` (gate_multiplier=4, 2 hidden states)."""
    return _build(lstm_cell, 4, 2, input_size, hidden_size, num_layers, bias,
                  batch_first, dropout, bidirectional, output_size)


def GRU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
        dropout=0.0, bidirectional=False, output_size: Optional[int] = None):
    """Reference ``models.py:28-33`` (gate_multiplier=3, 1 hidden state)."""
    if output_size is not None and output_size != hidden_size:
        # GRU's update-gate mix (1-z)*n + z*h needs h in gate space; a
        # recurrent projection would make the shapes incompatible (torch's
        # GRUCell, which the reference stacks, has the same constraint)
        raise ValueError(
            "GRU does not support a recurrent projection "
            f"(output_size={output_size} != hidden_size={hidden_size})")
    return _build(gru_cell, 3, 1, input_size, hidden_size, num_layers, bias,
                  batch_first, dropout, bidirectional, output_size)


def ReLU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size: Optional[int] = None):
    """Reference ``models.py:35-40``."""
    return _build(rnn_relu_cell, 1, 1, input_size, hidden_size, num_layers,
                  bias, batch_first, dropout, bidirectional, output_size)


def Tanh(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size: Optional[int] = None):
    """Reference ``models.py:42-47``."""
    return _build(rnn_tanh_cell, 1, 1, input_size, hidden_size, num_layers,
                  bias, batch_first, dropout, bidirectional, output_size)


def mLSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
          dropout=0.0, bidirectional=False, output_size: Optional[int] = None):
    """Reference ``models.py:49-55`` + ``cells.py:12-53``."""
    return _build(mlstm_cell, 4, 2, input_size, hidden_size, num_layers,
                  bias, batch_first, dropout, bidirectional, output_size,
                  multiplicative=True)
