"""RNN cell functions.

Pure-function counterparts of the torch fused cells the reference stacks
(``apex/RNN/models.py:1-55`` imports ``LSTMCell/RNNReLUCell/RNNTanhCell/
GRUCell`` from torch; ``apex/RNN/cells.py:56-...`` defines ``mLSTMCell``).
Each takes ``(x [B,in], hidden, params)`` and returns the new hidden tuple;
gate chunk order matches torch (i, f, g, o for LSTM; r, z, n for GRU) so
parity tests can copy weights straight across.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["rnn_relu_cell", "rnn_tanh_cell", "lstm_cell", "gru_cell",
           "mlstm_cell"]


def _linear(x, w, b=None):
    out = x @ w.T
    return out if b is None else out + b


def rnn_relu_cell(x, hidden, p):
    (h,) = hidden
    return (jax.nn.relu(_linear(x, p["w_ih"], p.get("b_ih"))
                        + _linear(h, p["w_hh"], p.get("b_hh"))),)


def rnn_tanh_cell(x, hidden, p):
    (h,) = hidden
    return (jnp.tanh(_linear(x, p["w_ih"], p.get("b_ih"))
                     + _linear(h, p["w_hh"], p.get("b_hh"))),)


def _lstm_gates(gates, c):
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def lstm_cell(x, hidden, p):
    h, c = hidden
    gates = (_linear(x, p["w_ih"], p.get("b_ih"))
             + _linear(h, p["w_hh"], p.get("b_hh")))
    return _lstm_gates(gates, c)


def gru_cell(x, hidden, p):
    (h,) = hidden
    gi = _linear(x, p["w_ih"], p.get("b_ih"))
    gh = _linear(h, p["w_hh"], p.get("b_hh"))
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return ((1.0 - z) * n + z * h,)


def mlstm_cell(x, hidden, p):
    """Multiplicative LSTM (reference ``cells.py:56-...``): the hidden state
    is modulated by an input-dependent factor before the gate matmul."""
    h, c = hidden
    m = _linear(x, p["w_mih"]) * _linear(h, p["w_mhh"])
    gates = (_linear(x, p["w_ih"], p.get("b_ih"))
             + _linear(m, p["w_hh"], p.get("b_hh")))
    return _lstm_gates(gates, c)
