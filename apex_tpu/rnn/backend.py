"""RNN backend: time scan, layer stacking, bidirectionality.

Counterpart of ``apex/RNN/RNNBackend.py`` (``bidirectionalRNN`` :25,
``stackedRNN`` :90, ``RNNCell`` :232): where the reference drives a Python
loop over timesteps with stateful hidden attributes, the TPU version is a
``lax.scan`` over the time axis (one compile regardless of length) with
hidden state threaded functionally; layers are a Python loop (heterogeneous
input sizes), and bidirectionality runs a reversed scan and concatenates
features — the same composition the reference builds from module wrappers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

__all__ = ["RNNModel"]


def _cell_param_shapes(gate_multiplier, input_size, hidden_size, output_size,
                       bias, multiplicative):
    gate_size = gate_multiplier * hidden_size
    shapes = {"w_ih": (gate_size, input_size),
              "w_hh": (gate_size, output_size)}
    if output_size != hidden_size:
        # recurrent projection (reference RNNCell w_ho, RNNBackend.py:253-255)
        shapes["w_ho"] = (output_size, hidden_size)
    if bias:
        shapes["b_ih"] = (gate_size,)
        shapes["b_hh"] = (gate_size,)
    if multiplicative:
        shapes["w_mih"] = (output_size, input_size)
        shapes["w_mhh"] = (output_size, output_size)
    return shapes


@dataclass
class RNNModel:
    """A stacked (optionally bidirectional) recurrent model.

    Built by the factory functions in :mod:`apex_tpu.rnn.models` (the
    reference's ``toRNNBackend``, ``models.py:9-18``). Input layout is
    time-major ``[T, B, input_size]`` unless ``batch_first``.
    """

    cell: Callable
    gate_multiplier: int
    n_hidden_states: int
    input_size: int
    hidden_size: int
    num_layers: int
    bias: bool = True
    batch_first: bool = False
    dropout: float = 0.0
    bidirectional: bool = False
    output_size: Optional[int] = None
    multiplicative: bool = False

    def __post_init__(self):
        if self.output_size is None:
            self.output_size = self.hidden_size

    # -- parameters ---------------------------------------------------------

    def _layer_shapes(self, layer: int) -> Dict[str, Tuple[int, ...]]:
        directions = 2 if self.bidirectional else 1
        in_size = (self.input_size if layer == 0
                   else self.output_size * directions)
        return _cell_param_shapes(self.gate_multiplier, in_size,
                                  self.hidden_size, self.output_size,
                                  self.bias, self.multiplicative)

    def init(self, key: jax.Array) -> List:
        """Uniform(-1/sqrt(hidden), 1/sqrt(hidden)) like the reference
        (``RNNBackend.py:271-276``). Returns a list of per-layer dicts (pairs
        of dicts when bidirectional)."""
        stdev = 1.0 / self.hidden_size ** 0.5
        directions = 2 if self.bidirectional else 1
        params = []
        for layer in range(self.num_layers):
            shapes = self._layer_shapes(layer)
            per_dir = []
            for d in range(directions):
                key, sub = jax.random.split(key)
                keys = jax.random.split(sub, len(shapes))
                per_dir.append({
                    name: jax.random.uniform(k, shape, minval=-stdev,
                                             maxval=stdev)
                    for k, (name, shape) in zip(keys, sorted(shapes.items()))
                })
            params.append(per_dir if self.bidirectional else per_dir[0])
        return params

    def spec(self):
        shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
        return jax.tree.map(lambda _: PartitionSpec(), shapes)

    # -- forward ------------------------------------------------------------

    def _zero_hidden(self, bsz, dtype):
        h = jnp.zeros((bsz, self.output_size), dtype)
        if self.n_hidden_states == 1:
            return (h,)
        return (h, jnp.zeros((bsz, self.hidden_size), dtype))

    def _run_layer(self, p, x, h0, reverse):
        def step(hidden, xt):
            new = self.cell(xt, hidden, p)
            out = new[0]
            if "w_ho" in p:
                out = out @ p["w_ho"].T
                new = (out,) + tuple(new[1:])
            return new, out

        hT, outs = lax.scan(step, h0, x, reverse=reverse)
        return outs, hT

    def apply(self, params, x, hidden=None, *, rng=None,
              deterministic: bool = True):
        """Returns ``(output [T,B,out*dirs], final_hiddens)`` where
        ``final_hiddens`` is a list (per layer) of hidden tuples (pairs of
        tuples when bidirectional)."""
        if self.batch_first:
            x = x.transpose(1, 0, 2)
        bsz = x.shape[1]
        finals = []
        for layer, p in enumerate(params):
            dirs = p if self.bidirectional else [p]
            h0s = (hidden[layer] if hidden is not None
                   else [self._zero_hidden(bsz, x.dtype) for _ in dirs])
            if not self.bidirectional and hidden is not None:
                h0s = [hidden[layer]]
            outs, hTs = [], []
            for d, pd in enumerate(dirs):
                o, hT = self._run_layer(pd, x, h0s[d], reverse=(d == 1))
                outs.append(o)
                hTs.append(hT)
            x = jnp.concatenate(outs, axis=-1) if self.bidirectional else outs[0]
            finals.append(hTs if self.bidirectional else hTs[0])
            if (self.dropout > 0.0 and not deterministic and rng is not None
                    and layer < self.num_layers - 1):
                rng, sub = jax.random.split(rng)
                keep = jax.random.bernoulli(sub, 1.0 - self.dropout, x.shape)
                x = jnp.where(keep, x / (1.0 - self.dropout), 0.0)
        if self.batch_first:
            x = x.transpose(1, 0, 2)
        return x, finals
