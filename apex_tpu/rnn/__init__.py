from apex_tpu.rnn.models import GRU, LSTM, ReLU, Tanh, mLSTM
from apex_tpu.rnn.backend import RNNModel

__all__ = ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM", "RNNModel"]
