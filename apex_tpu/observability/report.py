"""Run reports from JSONL metric logs — the ``apex_tpu.monitor`` backend.

Reads the record stream a :class:`~apex_tpu.observability.sinks.JsonlSink`
wrote during a run and folds it into one report dict / text page:

- **counter totals** — the last ``kind="counters"`` snapshot. For a run
  driven by :func:`apex_tpu.resilience.run_training` these reconcile
  *exactly* with ``TrainingResult.telemetry`` (the driver increments both
  from the same sites and flushes a final snapshot on exit).
- **step statistics** — p50/p95/mean step time, tokens/s, MFU over the
  per-step records, plus a trajectory (windowed means) so throughput
  regressions over the run are visible at a glance.
- **incident timeline** — every ``kind="event"`` record (skips,
  rollbacks, retraces, preemptions, resumes, captures) in ``seq`` order.
- **serving requests** — the ``kind="request"`` rows a
  :class:`~apex_tpu.serving.InferenceEngine` emits per terminal request:
  count and finish-reason split (these reconcile exactly with the
  engine's ``requests_*`` counters), plus queue/prefill/decode/total
  latency quantiles and per-request tokens/s.
- **serving incidents** — the supervisor/quarantine event stream
  (engine restarts, recovered requests, quarantined slots, breaker
  transitions, shed requests): per-type counts that reconcile
  key-for-key with the registry counters
  (:data:`SERVING_INCIDENT_COUNTERS` names the mapping; the tier-1
  serving-resilience tests assert it).
- **checkpoint incidents** — the retrying checkpoint manager's event
  stream (save retries/failures, restore fallbacks, checksum verify
  failures, partial-dir cleanups, abandoned async writes): per-type
  counts reconciling key-for-key with the ``ckpt_*`` counters
  (:data:`CHECKPOINT_INCIDENT_COUNTERS`), plus snapshot-blocked-time
  and write-duration histogram summaries.
- **SLO verdict** — when the log carries a ``kind="scenario"`` record
  with a declared ``"slo"`` section (what the loadtest runner embeds),
  or when the caller passes a spec (``--slo spec.json``), the report
  scores the run with :mod:`apex_tpu.observability.slo`: per-objective
  measured-vs-threshold lines and an overall PASS/FAIL.

Readers are defensive by contract: run logs outlive the writers that
produced them, so records missing newer fields (a pre-TTFT request row,
a step row without ``step``) must degrade to "no data" — never raise.

Pure stdlib on purpose: no jax import, so the CLI works on a laptop far
away from the TPU that wrote the log.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

from apex_tpu.observability.registry import percentile
from apex_tpu.observability.slo import (
    SLOSpec,
    evaluate_slos,
    measure_slo_metrics,
)
from apex_tpu.observability.trace import (
    build_timelines,
    check_span_conservation,
    format_timeline,
)

__all__ = ["read_records", "build_report", "render_report", "main",
           "SERVING_INCIDENT_COUNTERS", "SERVING_SHED_COUNTERS",
           "FLEET_INCIDENT_COUNTERS", "CHECKPOINT_INCIDENT_COUNTERS",
           "DEPLOY_ACTION_COUNTERS", "AUTOSCALE_ACTION_COUNTERS",
           "SENTINEL_INCIDENT_COUNTERS", "render_bundle"]

#: number of windows in the throughput/MFU trajectory
_TRAJECTORY_WINDOWS = 5

#: serving incident event -> registry counter: each event in the stream
#: is counted by exactly one increment of its counter at the same site,
#: so the report's per-type event counts reconcile key-for-key with the
#: final counter snapshot
SERVING_INCIDENT_COUNTERS = {
    "engine_restart": "engine_restarts",
    "tick_failure": "tick_failures",
    "slot_quarantined": "slots_quarantined",
    "request_recovered": "requests_recovered",
    "breaker_open": "breaker_opens",
    "breaker_half_open": "breaker_half_opens",
    "breaker_closed": "breaker_closes",
    # priority preemption (PR 20): a park and its token-exact resume
    # are each one event + one counter increment at the same site
    "request_preempted": "requests_preempted",
    "request_resumed": "requests_resumed",
}

#: ``request_shed`` events carry a ``reason`` field; each reason maps to
#: its own counter
SERVING_SHED_COUNTERS = {
    "breaker": "requests_shed_breaker",
    "deadline": "requests_shed_deadline",
    "fleet": "requests_shed_fleet",
    "pages_exhausted": "requests_shed_pages",
    "unknown_adapter": "requests_shed_adapter",
    "quota": "requests_shed_quota",
}

#: fleet incident event -> registry counter — same one-increment-per-
#: event contract as :data:`SERVING_INCIDENT_COUNTERS`, so the monitor's
#: fleet section reconciles key-for-key with the counter snapshot
FLEET_INCIDENT_COUNTERS = {
    "replica_drain": "replica_drains",
    "replica_rebuild": "replica_rebuilds",
    "request_migrated": "requests_migrated",
    # autoscaling + continuous deployment (PR 16)
    "replica_scale_up": "replica_scale_ups",
    "replica_scale_down": "replica_scale_downs",
    "deploy_start": "deploys_started",
    "deploy_complete": "deploys_completed",
    "deploy_rollback": "deploys_rolled_back",
    "deploy_rejected": "deploys_rejected",
    "canary_promoted": "canary_promotions",
    # brownout ladder + per-tenant quotas (PR 20)
    "brownout_escalate": "brownouts_escalated",
    "brownout_recover": "brownouts_recovered",
    "request_quota_deferred": "requests_deferred_quota",
}

#: ``kind="deploy"`` record action -> registry counter — each typed
#: deploy record is emitted at the same site as its counter increment
#: and event, so the monitor's deployments section reconciles
#: key-for-key with both the counter snapshot and the event timeline
DEPLOY_ACTION_COUNTERS = {
    "start": "deploys_started",
    "canary_pass": "canary_promotions",
    "rollback": "deploys_rolled_back",
    "complete": "deploys_completed",
    "rejected": "deploys_rejected",
}

#: ``kind="autoscale"`` record action -> registry counter (same
#: co-emission contract as :data:`DEPLOY_ACTION_COUNTERS`)
AUTOSCALE_ACTION_COUNTERS = {
    "scale_up": "replica_scale_ups",
    "scale_down": "replica_scale_downs",
}

#: checkpoint incident event -> registry counter, the
#: :class:`apex_tpu.checkpoint.RetryingCheckpointManager` event stream.
#: Each event is emitted at the same site its counter (and the
#: ``ckpt_``-prefixed ``TrainingResult.telemetry`` entry) increments, so
#: the checkpoints section reconciles key-for-key with the snapshot.
CHECKPOINT_INCIDENT_COUNTERS = {
    "checkpoint_save_retry": "ckpt_save_retries",
    "checkpoint_save_failed": "ckpt_save_failures",
    "checkpoint_save_abandoned": "ckpt_saves_abandoned",
    "checkpoint_restore_fallback": "ckpt_restore_fallbacks",
    "checkpoint_verify_failed": "ckpt_verify_failures",
    "checkpoint_deleted_corrupt": "ckpt_deleted_corrupt",
    "checkpoint_partial_cleaned": "ckpt_partials_cleaned",
}

#: drift-sentinel incident event -> registry counter — the
#: :class:`apex_tpu.observability.sentinel.DriftSentinel` fires each
#: ``anomaly`` event co-sited with one ``anomalies_total`` increment
#: (plus a per-signal ``anomalies_<signal>`` split), so the monitor's
#: anomalies section reconciles key-for-key with the counter snapshot.
#: Every key here is, by APX013, a flight-recorder trigger. Note the
#: recorder's own ``bundle_dumped`` event is deliberately NOT an
#: incident counter key: a dump must never trigger another dump.
SENTINEL_INCIDENT_COUNTERS = {
    "anomaly": "anomalies_total",
}


def read_records(path: str) -> List[dict]:
    """Parse a JSONL metric log; malformed lines are skipped (a run
    killed mid-write leaves a torn last line — the report must still
    build)."""
    records = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _stats(values: List[float]) -> Optional[dict]:
    values = [v for v in values if v == v]  # drop NaN
    if not values:
        return None
    return {"count": len(values), "mean": sum(values) / len(values),
            "min": min(values), "max": max(values),
            "p50": percentile(values, 50), "p95": percentile(values, 95)}


def _trajectory(steps: List[dict], key: str) -> List[dict]:
    """Windowed means of ``key`` over the step records, in step order —
    a coarse trend line (is throughput decaying? did MFU recover after
    the rollback?)."""
    pts = [(r["step"], r[key]) for r in steps
           if "step" in r and key in r and r[key] == r[key]]
    if not pts:
        return []
    pts.sort()
    n = max(1, (len(pts) + _TRAJECTORY_WINDOWS - 1) // _TRAJECTORY_WINDOWS)
    out = []
    for i in range(0, len(pts), n):
        window = pts[i:i + n]
        out.append({"from_step": window[0][0], "to_step": window[-1][0],
                    "mean": sum(v for _, v in window) / len(window)})
    return out


def _request_summary(requests: List[dict]) -> Optional[dict]:
    """Fold ``kind="request"`` serving rows into the report's requests
    section. ``by_finish_reason`` counts reconcile with the engine's
    ``requests_<reason>`` counters — same increment sites. Every field
    read is guarded: rows written by an older engine (no ``ttft_s`` /
    ``tpot_s``) fold into "no data" for those stats, never a KeyError."""
    if not requests:
        return None
    by_reason: Dict[str, int] = {}
    by_priority: Dict[str, int] = {}
    for r in requests:
        reason = str(r.get("finish_reason", "?"))
        by_reason[reason] = by_reason.get(reason, 0) + 1
        # priority class split (PR 20) — only rows that declare a class
        # count, so a pre-priority log folds to an empty dict and the
        # renderer skips the line entirely
        prio = r.get("priority")
        if prio is not None:
            by_priority[str(prio)] = by_priority.get(str(prio), 0) + 1

    def _field(key):
        return _stats([r[key] for r in requests
                       if isinstance(r.get(key), (int, float))])

    return {
        "count": len(requests),
        "by_finish_reason": by_reason,
        "by_priority": by_priority,
        "new_tokens": sum(int(r.get("new_tokens", 0)) for r in requests),
        "queue_s": _field("queue_s"),
        "prefill_s": _field("prefill_s"),
        "decode_s": _field("decode_s"),
        "total_s": _field("total_s"),
        "ttft_s": _field("ttft_s"),
        "tpot_s": _field("tpot_s"),
        "tokens_per_s": _field("tokens_per_s"),
        # chunked-prefill audit: sum of per-request prefill_chunks,
        # reconciling with the prefill_chunks counter (rows written by
        # pre-chunking engines simply contribute 0)
        "prefill_chunks": sum(int(r.get("prefill_chunks", 0))
                              for r in requests),
    }


def _serving_incidents(events: List[dict]) -> Optional[dict]:
    """Fold supervisor/quarantine incident events into per-type counts
    (plus the shed split by reason) — the monitor's serving-incidents
    section, reconciling with :data:`SERVING_INCIDENT_COUNTERS`."""
    counts: Dict[str, int] = {}
    shed: Dict[str, int] = {}
    for e in events:
        name = e.get("event")
        if name in SERVING_INCIDENT_COUNTERS:
            counts[name] = counts.get(name, 0) + 1
        elif name == "retrace":
            # RetraceWatchdog mirror — surfaced in the incident counts
            # but kept OUT of the strict one-inc-per-event mapping: a
            # single event can cover a batched _cache_size jump, so the
            # ``retraces`` counter may run ahead of the event count.
            counts[name] = counts.get(name, 0) + 1
        elif name == "request_shed":
            reason = str(e.get("reason", "?"))
            shed[reason] = shed.get(reason, 0) + 1
    if not counts and not shed:
        return None
    return {"counts": counts, "shed_by_reason": shed}


def _fleet_section(requests: List[dict], events: List[dict],
                   counters: Dict[str, int]) -> Optional[dict]:
    """Fold fleet telemetry into the monitor's fleet section: terminal
    requests grouped by the ``replica_id`` that retired them, dispatch
    counters (``fleet_dispatches`` and its per-replica split — the split
    sums to the total by construction), and drain/rebuild/migration
    incident counts reconciling with :data:`FLEET_INCIDENT_COUNTERS`.
    ``None`` when the log carries no fleet signal (a single-engine run,
    or a pre-fleet log whose request rows have no ``replica_id``)."""
    by_replica: Dict[str, int] = {}
    for r in requests:
        rid = r.get("replica_id")
        if isinstance(rid, int):
            by_replica[str(rid)] = by_replica.get(str(rid), 0) + 1
    counts: Dict[str, int] = {}
    for e in events:
        name = e.get("event")
        if name in FLEET_INCIDENT_COUNTERS:
            counts[name] = counts.get(name, 0) + 1
    dispatch = {name: n for name, n in counters.items()
                if name == "fleet_dispatches"
                or (name.startswith("replica")
                    and name.endswith("_dispatches"))}
    if not by_replica and not counts and not dispatch:
        return None
    return {"requests_by_replica": by_replica, "counts": counts,
            "dispatches": dispatch}


def _adapter_section(requests: List[dict], events: List[dict],
                     counters: Dict[str, int]) -> Optional[dict]:
    """Fold multi-LoRA telemetry into the monitor's adapters section:
    admissions grouped by ``adapter_id`` from the engine's
    ``adapter_request`` event stream (each event is one increment of the
    matching ``adapter<ix>_requests`` counter at the same site, so the
    two views reconcile key-for-key), terminal requests grouped by the
    ``adapter_id`` their result rows carry, and sheds from the
    ``requests_shed_adapter`` counter. ``None`` when the log carries no
    adapter signal (a base-model run, or a pre-LoRA log)."""
    admitted: Dict[str, int] = {}
    by_index: Dict[str, int] = {}
    for e in events:
        if e.get("event") != "adapter_request":
            continue
        aid = str(e.get("adapter_id", "?"))
        admitted[aid] = admitted.get(aid, 0) + 1
        ix = e.get("adapter_ix")
        if isinstance(ix, int):
            by_index[str(ix)] = by_index.get(str(ix), 0) + 1
    finished: Dict[str, int] = {}
    for r in requests:
        aid = r.get("adapter_id")
        if isinstance(aid, str):
            finished[aid] = finished.get(aid, 0) + 1
    adapter_counters = {name: n for name, n in counters.items()
                        if name.startswith("adapter")
                        and name.endswith("_requests") and n}
    shed = counters.get("requests_shed_adapter", 0)
    if not admitted and not finished and not adapter_counters and not shed:
        return None
    return {"admitted_by_adapter": admitted,
            "admitted_by_index": by_index,
            "finished_by_adapter": finished,
            "counters": adapter_counters,
            "shed_unknown": shed}


def _span_section(records: List[dict]) -> Optional[dict]:
    """Fold ``kind="span"`` rows into the monitor's tracing section:
    per-span-name counts (reconciling key-for-key with the ``spans_*``
    counters — same emission sites), the number of distinct traced
    requests, and the span-conservation verdict
    (:func:`~apex_tpu.observability.trace.check_span_conservation`).
    ``None`` for a pre-tracing log with no span rows — readers must
    tolerate logs written before trace ids existed."""
    spans = [r for r in records if r.get("kind") == "span"]
    if not spans:
        return None
    by_name: Dict[str, int] = {}
    traced = set()
    for s in spans:
        name = str(s.get("span", "?"))
        by_name[name] = by_name.get(name, 0) + 1
        traced.add(s.get("request_id"))
    return {"count": len(spans), "by_name": by_name,
            "traced_requests": len(traced),
            "violations": check_span_conservation(records)}


def _signals_section(records: List[dict]) -> Optional[dict]:
    """The last ``kind="signals"`` record's values — the fleet
    autoscaler poll the loadtest runner stamps before close. ``None``
    for single-engine runs and pre-fleet-telemetry logs."""
    signals = None
    for r in records:           # later wins, like the counter snapshots
        if r.get("kind") == "signals" and isinstance(
                r.get("values"), dict):
            signals = r["values"]
    return signals


def _autoscale_section(records: List[dict],
                       counters: Dict[str, int]) -> Optional[dict]:
    """Fold ``kind="autoscale"`` decision records into the monitor's
    autoscale section: per-action counts (reconciling key-for-key with
    :data:`AUTOSCALE_ACTION_COUNTERS` — same emission sites), the final
    replica count after the last decision, and the full decision
    timeline. ``None`` for a fixed-size or pre-autoscaler log."""
    rows = [r for r in records if r.get("kind") == "autoscale"]
    if not rows:
        return None
    by_action: Dict[str, int] = {}
    for r in rows:
        action = str(r.get("action", "?"))
        by_action[action] = by_action.get(action, 0) + 1
    return {
        "count": len(rows),
        "by_action": by_action,
        "counters": {c: counters.get(c, 0)
                     for c in sorted(set(AUTOSCALE_ACTION_COUNTERS.values()))},
        "final_replicas": rows[-1].get("n_replicas"),
        "decisions": [{k: r.get(k) for k in
                       ("action", "replica_id", "reason", "n_replicas",
                        "wall") if k in r} for r in rows],
    }


def _brownout_section(records: List[dict],
                      counters: Dict[str, int]) -> Optional[dict]:
    """Fold ``kind="brownout"`` ladder-transition records into the
    monitor's brownout section: per-action counts (reconciling
    key-for-key with the ``brownouts_escalated``/``brownouts_recovered``
    counters — same emission sites), the final rung after the last
    transition, and the transition timeline. ``None`` for a pre-brownout
    log or a run that never left rung 0 — the back-compat fixtures must
    render without this section."""
    rows = [r for r in records if r.get("kind") == "brownout"]
    if not rows:
        return None
    by_action: Dict[str, int] = {}
    for r in rows:
        action = str(r.get("action", "?"))
        by_action[action] = by_action.get(action, 0) + 1
    return {
        "count": len(rows),
        "by_action": by_action,
        "counters": {c: counters.get(c, 0)
                     for c in ("brownouts_escalated",
                               "brownouts_recovered")},
        "final_rung": rows[-1].get("rung"),
        "final_rung_name": rows[-1].get("rung_name"),
        "transitions": [{k: r.get(k) for k in
                         ("action", "rung", "rung_name", "pressure",
                          "parked", "wall") if k in r} for r in rows],
    }


def _deploy_section(records: List[dict],
                    counters: Dict[str, int]) -> Optional[dict]:
    """Fold ``kind="deploy"`` records into the monitor's deployments
    section: per-action counts (reconciling key-for-key with
    :data:`DEPLOY_ACTION_COUNTERS`), the action timeline, and the last
    canary score observed (the one that promoted or rolled back).
    ``None`` for a log with no deployment activity."""
    rows = [r for r in records if r.get("kind") == "deploy"]
    if not rows:
        return None
    by_action: Dict[str, int] = {}
    for r in rows:
        action = str(r.get("action", "?"))
        by_action[action] = by_action.get(action, 0) + 1
    last_score = None
    for r in rows:              # later wins — the decisive window
        if isinstance(r.get("score"), dict):
            last_score = r["score"]
    return {
        "count": len(rows),
        "by_action": by_action,
        "counters": {c: counters.get(c, 0)
                     for c in sorted(set(DEPLOY_ACTION_COUNTERS.values()))},
        "timeline": [{k: r.get(k) for k in
                      ("action", "target", "replica_id", "reason", "wall")
                      if k in r} for r in rows],
        "last_score": last_score,
    }


def _checkpoint_section(events: List[dict], counters: Dict[str, int],
                        histograms: Dict[str, dict]) -> Optional[dict]:
    """Fold checkpoint telemetry into the monitor's checkpoints section:
    per-type incident counts (reconciling with
    :data:`CHECKPOINT_INCIDENT_COUNTERS`), the save-volume counters
    (``ckpt_save_attempts``), and the snapshot-blocked / write-duration
    histogram summaries. ``None`` when the log carries no checkpoint
    signal (a run without a checkpoint manager, or a pre-sharded log)."""
    counts: Dict[str, int] = {}
    for e in events:
        name = e.get("event")
        if name in CHECKPOINT_INCIDENT_COUNTERS:
            counts[name] = counts.get(name, 0) + 1
    ckpt_counters = {name: n for name, n in counters.items()
                     if name.startswith("ckpt_")}
    timings = {name: h for name, h in histograms.items()
               if name in ("ckpt_snapshot_blocked_s", "ckpt_write_s")}
    if not counts and not ckpt_counters and not timings:
        return None
    return {"counts": counts, "counters": ckpt_counters,
            "timings": timings}


def _anomaly_section(records: List[dict],
                     counters: Dict[str, int]) -> Optional[dict]:
    """Fold drift-sentinel ``kind="anomaly"`` records into the
    monitor's anomalies section: per-signal counts (reconciling
    key-for-key with the ``anomalies_<signal>`` counters and the total
    with :data:`SENTINEL_INCIDENT_COUNTERS` — same emission sites) and
    the anomaly timeline. ``None`` for a pre-sentinel log, or a
    sentinel run that stayed healthy (counters present but zero still
    renders, so a clean sentinel run is visible as clean)."""
    rows = [r for r in records if r.get("kind") == "anomaly"]
    sentinel_counters = {name: n for name, n in counters.items()
                         if name == "anomalies_total"
                         or name.startswith("anomalies_")}
    if not rows and not sentinel_counters:
        return None
    by_signal: Dict[str, int] = {}
    for r in rows:
        sig = str(r.get("signal", "?"))
        by_signal[sig] = by_signal.get(sig, 0) + 1
    return {
        "count": len(rows),
        "by_signal": by_signal,
        "counters": sentinel_counters,
        "timeline": [{k: r.get(k) for k in
                      ("signal", "value", "baseline", "z", "wall")
                      if k in r} for r in rows],
    }


def _bundle_section(records: List[dict],
                    counters: Dict[str, int]) -> Optional[dict]:
    """Fold flight-recorder ``kind="bundle"`` records into the
    monitor's bundles section: one row per postmortem dump (trigger,
    file path, ring size at dump time), reconciling key-for-key with
    the ``bundles_dumped`` counter — the recorder emits record, event
    and increment from the same site. ``None`` for a pre-recorder log
    or a recorder run that never dumped (a zero counter still renders:
    "armed, nothing fired" is a result)."""
    rows = [r for r in records if r.get("kind") == "bundle"]
    dumped = counters.get("bundles_dumped")
    if not rows and dumped is None:
        return None
    return {
        "count": len(rows),
        "counter": 0 if dumped is None else dumped,
        "dumps": [{k: r.get(k) for k in
                   ("bundle_seq", "trigger", "path", "events", "wall")
                   if k in r} for r in rows],
    }


def _gauge_trajectory(records: List[dict]) -> List[dict]:
    """The ``kind="gauge_snapshot"`` samples the drift sentinel stamps
    every N polls — the live occupancy/queue trajectory ``--follow``
    renders between terminal-request rows. Empty for pre-sentinel logs
    (readers must tolerate their absence, like every other section)."""
    out = []
    for r in records:
        if r.get("kind") != "gauge_snapshot":
            continue
        sig = r.get("signals")
        if isinstance(sig, dict):
            out.append({"wall": r.get("wall"), **sig})
    return out


def build_report(path: str,
                 slo_spec: Optional[Dict[str, float]] = None) -> dict:
    """Fold one JSONL metric log into a report dict.

    ``slo_spec`` (``{metric: threshold}``, see
    :data:`apex_tpu.observability.slo.SLO_METRICS`) scores the run's SLO
    verdict; when omitted, the spec embedded in the log's
    ``kind="scenario"`` record (if any) is used — a loadtest run log
    scores itself."""
    records = read_records(path)
    steps = [r for r in records if r.get("kind") == "step"]
    events = [r for r in records if r.get("kind") == "event"]
    requests = [r for r in records if r.get("kind") == "request"]
    scenario = None
    for r in records:       # later wins, like the counter snapshots
        if r.get("kind") == "scenario":
            scenario = r
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, dict] = {}
    for r in records:  # later snapshots win: the last one is end-of-run
        if r.get("kind") == "counters":
            counters = dict(r.get("values", {}))
        elif r.get("kind") == "gauges":
            gauges = dict(r.get("values", {}))
        elif r.get("kind") == "histograms":
            histograms = dict(r.get("values", {}))

    losses = [r["loss"] for r in steps
              if "loss" in r and not r.get("skipped")
              and r["loss"] == r["loss"]]
    report = {
        "path": path,
        "records": len(records),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "steps_recorded": len(steps),
        "skipped_steps": sum(1 for r in steps if r.get("skipped")),
        "step_time_s": _stats([r["step_time_s"] for r in steps
                               if "step_time_s" in r]),
        "tokens_per_s": _stats([r["tokens_per_s"] for r in steps
                                if "tokens_per_s" in r]),
        "mfu": _stats([r["mfu"] for r in steps if "mfu" in r]),
        "loss": ({"first": losses[0], "last": losses[-1],
                  "min": min(losses)} if losses else None),
        "throughput_trajectory": _trajectory(steps, "tokens_per_s"),
        "mfu_trajectory": _trajectory(steps, "mfu"),
        "requests": _request_summary(requests),
        "serving_incidents": _serving_incidents(events),
        "fleet": _fleet_section(requests, events, counters),
        "adapters": _adapter_section(requests, events, counters),
        "spans": _span_section(records),
        "signals": _signals_section(records),
        "autoscale": _autoscale_section(records, counters),
        "brownout": _brownout_section(records, counters),
        "deploys": _deploy_section(records, counters),
        # per-tenant SLO attribution, only when the run carried adapter
        # traffic (a base-only or pre-LoRA log renders no tenant table)
        "slo_by_adapter": (
            measure_slo_metrics(records, by_adapter=True)
            if any(isinstance(r.get("adapter_id"), str) for r in requests)
            else None),
        "checkpoints": _checkpoint_section(events, counters, histograms),
        "anomalies": _anomaly_section(records, counters),
        "bundles": _bundle_section(records, counters),
        "gauge_trajectory": _gauge_trajectory(records),
        "timeline": sorted(events, key=lambda e: e.get("seq", 0)),
        "scenario": ({k: scenario[k] for k in ("name", "seed")
                      if k in scenario} if scenario else None),
        "slo": None,
    }
    spec = slo_spec
    if spec is None and scenario is not None and \
            isinstance(scenario.get("slo"), dict):
        spec = scenario["slo"]
    if spec:
        report["slo"] = evaluate_slos(records,
                                      SLOSpec.from_dict(spec)).as_dict()
    return report


def _fmt(value: float, unit: str = "") -> str:
    if value is None:
        return "-"
    if abs(value) >= 1000:
        return f"{value:,.0f}{unit}"
    return f"{value:.4g}{unit}"


def _render_stat_line(label: str, stats: Optional[dict],
                      unit: str = "") -> str:
    if not stats:
        return f"  {label:<14} (no data)"
    return (f"  {label:<14} p50={_fmt(stats['p50'], unit)} "
            f"p95={_fmt(stats['p95'], unit)} mean={_fmt(stats['mean'], unit)} "
            f"max={_fmt(stats['max'], unit)} n={stats['count']}")


def render_report(report: dict) -> str:
    lines = [f"== apex_tpu run report: {report['path']} ==",
             f"records: {report['records']}  "
             f"step records: {report['steps_recorded']}  "
             f"skipped: {report['skipped_steps']}",
             "",
             "counters:"]
    if report["counters"]:
        lines += [f"  {k} = {v}" for k, v in sorted(
            report["counters"].items())]
    else:
        lines.append("  (none — was the registry flushed?)")
    lines += ["", "step statistics:",
              _render_stat_line("step time", report["step_time_s"], "s"),
              _render_stat_line("tokens/s", report["tokens_per_s"]),
              _render_stat_line("mfu", report["mfu"])]
    if report["loss"]:
        lo = report["loss"]
        lines.append(f"  {'loss':<14} first={_fmt(lo['first'])} "
                     f"last={_fmt(lo['last'])} min={_fmt(lo['min'])}")
    req = report.get("requests")
    if req:
        reasons = " ".join(f"{k}={v}" for k, v in sorted(
            req["by_finish_reason"].items()))
        lines += ["", f"serving requests ({req['count']}, "
                      f"{req['new_tokens']} tokens generated):",
                  f"  finish: {reasons}"]
        if req.get("by_priority"):
            split = " ".join(f"{k}={v}" for k, v in sorted(
                req["by_priority"].items()))
            lines.append(f"  priority: {split}")
        lines += [_render_stat_line("queue", req["queue_s"], "s"),
                  _render_stat_line("prefill", req["prefill_s"], "s"),
                  _render_stat_line("decode", req["decode_s"], "s"),
                  _render_stat_line("total", req["total_s"], "s"),
                  _render_stat_line("ttft", req.get("ttft_s"), "s"),
                  _render_stat_line("tpot", req.get("tpot_s"), "s"),
                  _render_stat_line("tokens/s", req["tokens_per_s"])]
    gauges = report.get("gauges") or {}
    if "kv_pages_in_use" in gauges or "kv_pages_free" in gauges:
        # paged-KV engine state at the final snapshot, reconciled like
        # the slot metrics: in_use + free == n_pages by the PagePool
        # invariant, and occupancy is the per-tick mapped fraction
        if not req:
            lines += ["", "serving kv cache:"]
        occ = (report.get("histograms") or {}).get("kv_page_occupancy")
        line = (f"  kv pages: in_use={int(gauges.get('kv_pages_in_use', 0))}"
                f" free={int(gauges.get('kv_pages_free', 0))}")
        if isinstance(occ, dict) and occ.get("count"):
            line += (f"  occupancy mean={_fmt(occ.get('mean'))} "
                     f"max={_fmt(occ.get('max'))} n={occ['count']}")
        lines.append(line)
        counters = report.get("counters") or {}
        hits = counters.get("prefix_hits", 0)
        misses = counters.get("prefix_misses", 0)
        if hits or misses:
            # prefix-cache effectiveness, derived from the same counters
            # the engine reconciles against prefills (hits + misses ==
            # paged prefills when prefix_cache is on)
            rate = hits / (hits + misses)
            lines.append(
                f"  prefix cache: hits={hits} misses={misses} "
                f"hit_rate={rate:.1%} "
                f"pages_shared={counters.get('prefix_pages_shared', 0)} "
                f"evictions={counters.get('prefix_evictions', 0)}")
        if "kv_bytes_per_step" in gauges:
            # decode-roofline denominator at the final snapshot — the
            # dtype- and page-aware stream size int8 KV shrinks
            lines.append(
                f"  kv bytes/step (final): "
                f"{int(gauges['kv_bytes_per_step']):,}")
        proposed = counters.get("draft_tokens_proposed", 0)
        if proposed:
            # speculative decoding: accepted/proposed is the fleet-wide
            # acceptance rate, reconciling key-for-key with the
            # spec_accept_rate histogram's per-step observations
            accepted = counters.get("draft_tokens_accepted", 0)
            line = (f"  speculation: proposed={proposed} "
                    f"accepted={accepted} "
                    f"accept_rate={accepted / proposed:.1%}")
            acc = (report.get("histograms") or {}).get("spec_accept_rate")
            if isinstance(acc, dict) and acc.get("count"):
                line += (f" per-step mean={_fmt(acc.get('mean'))} "
                         f"n={acc['count']}")
            lines.append(line)
    chunk_counters = report.get("counters") or {}
    chunks = chunk_counters.get("prefill_chunks", 0)
    if chunks:
        # chunked prefill (both layouts — rendered outside the paged-KV
        # block): the chunk-program counter reconciles with the sum of
        # per-request prefill_chunks record fields, and the
        # prefill_tokens_per_tick histogram shows how full the
        # per-tick token budget actually ran
        line = f"  chunked prefill: chunks={chunks}"
        if req is not None:
            line += f" per-request sum={req.get('prefill_chunks', 0)}"
        tpt = (report.get("histograms") or {}).get("prefill_tokens_per_tick")
        if isinstance(tpt, dict) and tpt.get("count"):
            line += (f"  tokens/tick mean={_fmt(tpt.get('mean'))} "
                     f"max={_fmt(tpt.get('max'))} n={tpt['count']}")
        if not req and "kv_pages_in_use" not in gauges:
            lines += ["", "serving kv cache:"]
        lines.append(line)
    slo = report.get("slo")
    if slo:
        verdict = "PASS" if slo["ok"] else "FAIL"
        n_fail = sum(1 for o in slo["objectives"] if not o["ok"])
        head = (f"slo verdict: {verdict} "
                f"({len(slo['objectives'])} objectives"
                + (f", {n_fail} violated)" if n_fail else ")"))
        lines += ["", head]
        for o in slo["objectives"]:
            cmp_ = "<=" if o["direction"] == "max" else ">="
            measured = ("(no data)" if o["measured"] is None
                        else _fmt(o["measured"]))
            lines.append(
                f"  {'ok ' if o['ok'] else 'VIOLATED':<9}"
                f"{o['name']:<16} measured={measured:<10} "
                f"{cmp_} {_fmt(o['threshold'])}")
    fleet = report.get("fleet")
    if fleet:
        lines += ["", "fleet:"]
        if fleet["dispatches"]:
            total = fleet["dispatches"].get("fleet_dispatches", 0)
            split = " ".join(
                f"{k}={v}" for k, v in sorted(fleet["dispatches"].items())
                if k != "fleet_dispatches")
            lines.append(f"  dispatches: {total}"
                         + (f" ({split})" if split else ""))
        if fleet["requests_by_replica"]:
            split = " ".join(f"replica{k}={v}" for k, v in sorted(
                fleet["requests_by_replica"].items()))
            lines.append(f"  requests by replica: {split}")
        lines += [f"  {name} = {n}"
                  for name, n in sorted(fleet["counts"].items())]
    signals = report.get("signals")
    if signals:
        def _sig(key):
            return _fmt(signals.get(key)) \
                if signals.get(key) is not None else "-"

        lines += ["", "fleet signals (autoscaler):",
                  f"  replicas: {signals.get('replicas_total', '?')} total "
                  f"{signals.get('replicas_dispatchable', '?')} "
                  f"dispatchable  inflight={signals.get('inflight', '?')} "
                  f"queue_depth={signals.get('queue_depth', '?')}"
                  + (f" queued_tokens={signals['queued_tokens']}"
                     if signals.get("queued_tokens") is not None else ""),
                  f"  goodput: window={_sig('goodput_window')} "
                  f"({signals.get('window_ok', 0)}/"
                  f"{signals.get('window_terminal', 0)}"
                  + (f" over {_fmt(signals['window_s'], 's')}"
                     if signals.get("window_s") is not None else "")
                  + ") "
                  f"cumulative={_sig('goodput')} "
                  f"({signals.get('requests_ok', 0)}/"
                  f"{signals.get('requests_terminal', 0)})",
                  f"  latency: ttft_p99={_sig('ttft_p99_s')}s "
                  f"tpot_p99={_sig('tpot_p99_s')}s",
                  f"  occupancy: slots={_sig('slot_occupancy')} "
                  f"kv_pages={_sig('kv_page_occupancy')}"]
        share = signals.get("adapter_share") or {}
        if share:
            split = " ".join(f"{k}={_fmt(v)}"
                             for k, v in sorted(share.items()))
            lines.append(f"  adapter share: {split}")
    autoscale = report.get("autoscale")
    if autoscale:
        split = " ".join(f"{k}={v}"
                         for k, v in sorted(autoscale["by_action"].items()))
        final = autoscale.get("final_replicas")
        lines += ["", f"autoscale decisions ({autoscale['count']}):",
                  f"  {split}"
                  + (f"  final_replicas={final}" if final is not None
                     else "")]
        for d in autoscale["decisions"][:10]:
            wall = d.get("wall")
            stamp = f"[wall={wall:.3f}] " if isinstance(
                wall, (int, float)) else ""
            lines.append(
                f"  {stamp}{d.get('action', '?')} "
                f"replica={d.get('replica_id', '?')} "
                f"reason={d.get('reason', '?')} "
                f"-> n={d.get('n_replicas', '?')}")
        if len(autoscale["decisions"]) > 10:
            lines.append(
                f"  ... {len(autoscale['decisions']) - 10} more")
    brownout = report.get("brownout")
    if brownout:
        split = " ".join(f"{k}={v}"
                         for k, v in sorted(brownout["by_action"].items()))
        final = brownout.get("final_rung_name")
        lines += ["", f"brownout ladder ({brownout['count']} transitions):",
                  f"  {split}"
                  + (f"  final_rung={final}" if final is not None else "")]
        for t in brownout["transitions"][:10]:
            wall = t.get("wall")
            stamp = f"[wall={wall:.3f}] " if isinstance(
                wall, (int, float)) else ""
            lines.append(
                f"  {stamp}{t.get('action', '?')} "
                f"-> rung {t.get('rung', '?')} "
                f"({t.get('rung_name', '?')}) "
                f"pressure={_fmt(t.get('pressure'))} "
                f"parked={t.get('parked', 0)}")
        if len(brownout["transitions"]) > 10:
            lines.append(
                f"  ... {len(brownout['transitions']) - 10} more")
    deploys = report.get("deploys")
    if deploys:
        split = " ".join(f"{k}={v}"
                         for k, v in sorted(deploys["by_action"].items()))
        lines += ["", f"deployments ({deploys['count']} records):",
                  f"  {split}"]
        for d in deploys["timeline"][:12]:
            wall = d.get("wall")
            stamp = f"[wall={wall:.3f}] " if isinstance(
                wall, (int, float)) else ""
            extra = " ".join(
                f"{k}={d[k]}" for k in ("replica_id", "reason")
                if d.get(k) is not None)
            lines.append(f"  {stamp}{d.get('action', '?')} "
                         f"{d.get('target', '?')}"
                         + (f" {extra}" if extra else ""))
        score = deploys.get("last_score")
        if isinstance(score, dict):
            lines.append(
                f"  last canary score: "
                f"{'PASS' if score.get('pass') else 'FAIL'} "
                f"requests={score.get('requests', '?')} "
                f"errors={score.get('errors', '?')} "
                f"error_rate={_fmt(score.get('error_rate'))} "
                f"ttft_p99={_fmt(score.get('canary_ttft_p99_s'), 's')} "
                f"vs incumbent "
                f"{_fmt(score.get('incumbent_ttft_p99_s'), 's')}")
    by_adapter = report.get("slo_by_adapter")
    if by_adapter:
        lines += ["", "per-tenant slo (by adapter_id):",
                  f"  {'tenant':<10}{'reqs':>6}{'ttft_p99':>10}"
                  f"{'tpot_p99':>10}{'goodput':>9}"]
        for aid, m in sorted(by_adapter.items()):
            lines.append(
                f"  {aid:<10}{m.get('requests', 0):>6}"
                f"{_fmt(m.get('ttft_p99_s'), 's'):>10}"
                f"{_fmt(m.get('tpot_p99_s'), 's'):>10}"
                f"{_fmt(m.get('goodput')):>9}")
    spans = report.get("spans")
    if spans:
        split = " ".join(f"{k}={v}"
                         for k, v in sorted(spans["by_name"].items()))
        verdict = ("OK" if not spans["violations"]
                   else f"{len(spans['violations'])} VIOLATION(S)")
        lines += ["", f"request tracing ({spans['count']} spans over "
                      f"{spans['traced_requests']} requests):",
                  f"  {split}",
                  f"  span conservation: {verdict}"]
        lines += [f"    {v}" for v in spans["violations"][:10]]
    adapters = report.get("adapters")
    if adapters:
        lines += ["", "adapters (multi-LoRA):"]
        if adapters["admitted_by_adapter"]:
            split = " ".join(f"{k}={v}" for k, v in sorted(
                adapters["admitted_by_adapter"].items()))
            lines.append(f"  admitted by adapter: {split}")
        if adapters["finished_by_adapter"]:
            split = " ".join(f"{k}={v}" for k, v in sorted(
                adapters["finished_by_adapter"].items()))
            lines.append(f"  finished by adapter: {split}")
        lines += [f"  {name} = {n}"
                  for name, n in sorted(adapters["counters"].items())]
        if adapters["shed_unknown"]:
            lines.append(
                f"  shed (unknown adapter) = {adapters['shed_unknown']}")
    ckpt = report.get("checkpoints")
    if ckpt:
        lines += ["", "checkpoints:"]
        attempts = ckpt["counters"].get("ckpt_save_attempts")
        if attempts is not None:
            lines.append(f"  save attempts: {attempts}")
        lines += [f"  {name} = {n}"
                  for name, n in sorted(ckpt["counts"].items())]
        for name, label in (("ckpt_snapshot_blocked_s", "snapshot block"),
                            ("ckpt_write_s", "write")):
            h = ckpt["timings"].get(name)
            if isinstance(h, dict) and h.get("count"):
                lines.append(
                    f"  {label:<14} n={h['count']} "
                    f"mean={_fmt(h.get('mean'), 's')} "
                    f"max={_fmt(h.get('max'), 's')}"
                    + (f" p95={_fmt(h['p95'], 's')}"
                       if "p95" in h else ""))
    inc = report.get("serving_incidents")
    if inc:
        total = sum(inc["counts"].values()) + \
            sum(inc["shed_by_reason"].values())
        lines += ["", f"serving incidents ({total}):"]
        lines += [f"  {name} = {n}"
                  for name, n in sorted(inc["counts"].items())]
        if inc["shed_by_reason"]:
            split = " ".join(f"{k}={v}" for k, v in sorted(
                inc["shed_by_reason"].items()))
            lines.append(f"  request_shed: {split}")
    anomalies = report.get("anomalies")
    if anomalies:
        split = " ".join(f"{k}={v}" for k, v in sorted(
            anomalies["by_signal"].items())) or "(none fired)"
        lines += ["", f"drift anomalies ({anomalies['count']}):",
                  f"  {split}"]
        lines += [f"  {name} = {n}" for name, n in sorted(
            anomalies["counters"].items())]
        for a in anomalies["timeline"][:10]:
            wall = a.get("wall")
            stamp = f"[wall={wall:.3f}] " if isinstance(
                wall, (int, float)) else ""
            lines.append(
                f"  {stamp}{a.get('signal', '?')} "
                f"value={_fmt(a.get('value'))} "
                f"baseline={_fmt(a.get('baseline'))} "
                f"z={_fmt(a.get('z'))}")
        if len(anomalies["timeline"]) > 10:
            lines.append(
                f"  ... {len(anomalies['timeline']) - 10} more")
    bundles = report.get("bundles")
    if bundles:
        lines += ["", f"postmortem bundles ({bundles['count']} dumped, "
                      f"bundles_dumped = {bundles['counter']}):"]
        if not bundles["dumps"]:
            lines.append("  (recorder armed — nothing fired)")
        for b in bundles["dumps"]:
            wall = b.get("wall")
            stamp = f"[wall={wall:.3f}] " if isinstance(
                wall, (int, float)) else ""
            lines.append(
                f"  {stamp}#{b.get('bundle_seq', '?')} "
                f"trigger={b.get('trigger', '?')} "
                f"events={b.get('events', '?')}"
                + (f" -> {b['path']}" if b.get("path") else ""))
    gauge_traj = report.get("gauge_trajectory")
    if gauge_traj:
        lines += ["", f"signal trajectory ({len(gauge_traj)} "
                      "gauge snapshots):"]
        for key_, label in (("queue_depth", "queue depth"),
                            ("slot_occupancy", "slot occupancy"),
                            ("ttft_p99_s", "ttft p99 (s)"),
                            ("goodput_window", "windowed goodput")):
            pts = [g.get(key_) for g in gauge_traj]
            if not any(isinstance(p, (int, float)) for p in pts):
                continue
            shown = pts[-8:]
            arrow = " -> ".join(
                _fmt(p) if isinstance(p, (int, float)) else "-"
                for p in shown)
            prefix = "... " if len(pts) > len(shown) else ""
            lines.append(f"  {label:<18} {prefix}{arrow}")
    for key, label in (("throughput_trajectory", "tokens/s trajectory"),
                       ("mfu_trajectory", "mfu trajectory")):
        traj = report[key]
        if traj:
            arrow = " -> ".join(_fmt(w["mean"]) for w in traj)
            lines += ["", f"{label} (steps "
                          f"{traj[0]['from_step']}..{traj[-1]['to_step']}):",
                      f"  {arrow}"]
    lines += ["", f"incident timeline ({len(report['timeline'])} events):"]
    if not report["timeline"]:
        lines.append("  (clean run — no incidents)")
    for ev in report["timeline"]:
        extra = " ".join(
            f"{k}={v}" for k, v in sorted(ev.items())
            if k not in ("kind", "event", "seq", "ts", "wall"))
        lines.append(f"  [seq={ev.get('seq', '?')} "
                     f"wall={ev.get('wall', 0):.3f}] "
                     f"{ev.get('event', '?')} {extra}".rstrip())
    return "\n".join(lines)


def _print_trace(path: str, request_id: int) -> int:
    """``--trace``: print one request's span timeline. Exit 0 when the
    request has spans in the log, 2 when it does not (unknown id, or a
    pre-tracing log)."""
    records = read_records(path)
    timelines = build_timelines(records)
    if request_id not in timelines:
        print(f"apex_tpu.monitor: no spans for request {request_id} "
              f"in {path}", file=sys.stderr)
        return 2
    result = None
    for r in records:
        if r.get("kind") == "request" and \
                r.get("request_id") == request_id:
            result = r
    print(format_timeline(request_id, timelines[request_id], result))
    return 0


def _follow(path: str, *, spec: Optional[Dict[str, float]], as_json: bool,
            poll_s: float, max_polls: Optional[int]) -> int:
    """``--follow``: tail a growing run log, re-rendering the report
    whenever the file grows (size change is the signal — JSONL is
    append-only). ``max_polls`` bounds the loop for tests; the default
    ``None`` polls until interrupted."""
    last_size = -1
    polls = 0
    try:
        while max_polls is None or polls < max_polls:
            polls += 1
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1       # not written yet: keep polling
            if size != last_size and size >= 0:
                last_size = size
                report = build_report(path, slo_spec=spec)
                if as_json:
                    print(json.dumps(report, indent=2, default=str))
                else:
                    stamp = time.strftime("%H:%M:%S")
                    print(f"\n--- follow poll {polls} [{stamp}] ---")
                    print(render_report(report))
                sys.stdout.flush()
            if max_polls is None or polls < max_polls:
                time.sleep(poll_s)
    except KeyboardInterrupt:
        pass
    return 0


#: timeline rows printed either side of the trigger in the bundle view
_BUNDLE_TIMELINE_CONTEXT = 8


def render_bundle(bundle: dict) -> str:
    """Render a flight-recorder postmortem bundle as a text page: the
    trigger, a timeline window around it (ring events + typed records
    merged in ``seq`` order, trigger marked), the signal trajectories
    from the gauge-snapshot ring, per-replica engine digests, and a
    suspect attribution (the trigger's replica if it names one, else
    the digest that looks least healthy). Defensive like every reader
    here: bundles outlive the recorders that wrote them."""
    trigger = bundle.get("trigger") or {}
    lines = [f"== apex_tpu postmortem bundle "
             f"(schema {bundle.get('schema', '?')}) ==",
             f"wall: {bundle.get('wall', '?')}  "
             f"trigger: {trigger.get('event', '(manual dump)')}"]
    caps = bundle.get("capacities") or {}
    if caps:
        lines.append(
            "rings: " + " ".join(f"{k}={v}" for k, v in sorted(
                caps.items())))
    cfg = bundle.get("config") or {}
    if cfg.get("fingerprint"):
        lines.append(f"config fingerprint: {cfg['fingerprint']}")

    # -- timeline window around the trigger (events + typed records) --
    rows = [dict(r) for r in (bundle.get("events") or [])]
    rows += [dict(r) for r in (bundle.get("records") or [])]
    rows.sort(key=lambda r: r.get("seq", 0))
    trig_ix = None
    if trigger:
        for i, r in enumerate(rows):
            if r.get("seq") == trigger.get("seq") and \
                    r.get("event") == trigger.get("event"):
                trig_ix = i
    lo = 0 if trig_ix is None else max(
        0, trig_ix - _BUNDLE_TIMELINE_CONTEXT)
    hi = len(rows) if trig_ix is None else min(
        len(rows), trig_ix + _BUNDLE_TIMELINE_CONTEXT + 1)
    lines += ["", f"timeline around trigger "
                  f"({len(rows)} ring records, showing {hi - lo}):"]
    if lo > 0:
        lines.append(f"  ... {lo} earlier")
    for i in range(lo, hi):
        r = rows[i]
        mark = ">>" if i == trig_ix else "  "
        label = r.get("event") or r.get("kind", "?")
        extra = " ".join(
            f"{k}={_fmt(v) if isinstance(v, float) else v}"
            for k, v in sorted(r.items())
            if k not in ("kind", "event", "seq", "ts", "wall")
            and not isinstance(v, (dict, list)))
        lines.append(f"{mark}[seq={r.get('seq', '?')} "
                     f"wall={r.get('wall', 0):.3f}] {label} "
                     f"{extra}".rstrip())
    if hi < len(rows):
        lines.append(f"  ... {len(rows) - hi} later")

    # -- signal trajectories from the gauge-snapshot ring --
    snaps = [r.get("signals") for r in
             (bundle.get("gauge_snapshots") or [])
             if isinstance(r.get("signals"), dict)]
    if snaps:
        lines += ["", f"signal trajectories ({len(snaps)} snapshots):"]
        keys = sorted({k for s in snaps for k in s})
        for key in keys:
            pts = [s.get(key) for s in snaps]
            if not any(isinstance(p, (int, float)) for p in pts):
                continue
            arrow = " -> ".join(
                _fmt(p) if isinstance(p, (int, float)) else "-"
                for p in pts[-8:])
            lines.append(f"  {key:<18} {arrow}")
    last = bundle.get("signals")
    if isinstance(last, dict):
        lines += ["", "last signals snapshot:"]
        lines.append("  " + " ".join(
            f"{k}={_fmt(v) if isinstance(v, float) else v}"
            for k, v in sorted(last.items())
            if not isinstance(v, (dict, list))))

    # -- per-replica digests + suspect attribution --
    replicas = bundle.get("replicas") or []
    suspect = None
    suspect_why = None
    if isinstance(trigger.get("replica_id"), int):
        suspect = trigger["replica_id"]
        suspect_why = "named by trigger"
    if replicas:
        lines += ["", f"replica digests ({len(replicas)}):"]
    for d in replicas:
        rid = d.get("replica_id")
        head = (f"  replica {rid}" if rid is not None else "  engine")
        head += (f" [{d['state']}]" if d.get("state") else "")
        breaker = d.get("breaker")
        unhealthy = (breaker not in (None, "closed")
                     or (d.get("restarts") or 0) > 0)
        if suspect is None and unhealthy and rid is not None:
            suspect = rid
            suspect_why = (f"breaker={breaker}" if breaker != "closed"
                           else f"restarts={d.get('restarts')}")
        lines.append(
            head + f": breaker={breaker} restarts={d.get('restarts')} "
            f"queued={d.get('queued')} active={d.get('active')} "
            f"inflight={d.get('inflight')}")
        slots = d.get("slots")
        if isinstance(slots, dict):
            lines.append(
                f"    slots: free={slots.get('free')} "
                f"active={slots.get('active')} "
                f"occupancy={_fmt(slots.get('occupancy'))}")
        pages = d.get("pages")
        if isinstance(pages, dict):
            lines.append(
                f"    pages: free={pages.get('free')} "
                f"in_use={pages.get('in_use')} "
                f"interned={pages.get('interned')} "
                f"occupancy={_fmt(pages.get('occupancy'))} "
                f"evictions={pages.get('evictions')}")
        comp = d.get("compiles")
        if isinstance(comp, dict):
            lines.append(
                f"    compiles: prefill={comp.get('prefill')} "
                f"decode={comp.get('decode')} "
                f"chunk={comp.get('chunk')} "
                f"retraces={comp.get('decode_retraces')}")
        for r in (d.get("requests") or [])[:8]:
            lines.append(
                f"    inflight request {r.get('request_id', '?')}: "
                f"generated={r.get('generated', '?')} "
                f"submit_ts={_fmt(r.get('submit_ts'))}"
                + (f" adapter={r['adapter_id']}"
                   if r.get("adapter_id") else ""))
    lines += ["", "suspect: "
              + (f"replica {suspect} ({suspect_why})"
                 if suspect is not None
                 else "(none — no replica named or unhealthy)")]
    return "\n".join(lines)


def _bundle_main(argv: List[str]) -> int:
    """``python -m apex_tpu.monitor bundle <path> [--json]``."""
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.monitor bundle",
        description="Render a flight-recorder postmortem bundle "
                    "(the *-bundle-N.json files a FlightRecorder dumps "
                    "next to the run log).")
    parser.add_argument("path", help="path to a bundle .json file")
    parser.add_argument("--json", action="store_true",
                        help="print the raw bundle JSON instead of the "
                             "rendered page")
    args = parser.parse_args(argv)
    try:
        with open(args.path, "r", encoding="utf-8") as f:
            bundle = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"apex_tpu.monitor: cannot read bundle {args.path}: "
              f"{exc}", file=sys.stderr)
        return 2
    if not isinstance(bundle, dict):
        print(f"apex_tpu.monitor: {args.path} is not a bundle object",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(bundle, indent=2, sort_keys=True, default=str))
    else:
        print(render_bundle(bundle))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "bundle":
        return _bundle_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.monitor",
        description="Print a run report from a JSONL metric log written "
                    "by apex_tpu.observability's JsonlSink.")
    parser.add_argument("path", help="path to the run's .jsonl metric log")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON instead of text")
    parser.add_argument("--slo", metavar="SPEC.json", default=None,
                        help="score the run against this SLO spec "
                             "({metric: threshold} JSON) instead of the "
                             "one embedded in the log's scenario record")
    parser.add_argument("--trace", metavar="REQUEST_ID", type=int,
                        default=None,
                        help="print one request's span timeline instead "
                             "of the full report (exit 2 if the log has "
                             "no spans for it)")
    parser.add_argument("--follow", action="store_true",
                        help="tail a growing log: re-render the report "
                             "each time the file grows, until "
                             "interrupted (or --max-polls)")
    parser.add_argument("--poll-s", type=float, default=2.0,
                        help="--follow poll interval in seconds "
                             "(default: 2)")
    parser.add_argument("--max-polls", type=int, default=None,
                        help="--follow: stop after N polls (default: "
                             "poll until interrupted)")
    args = parser.parse_args(argv)
    spec = None
    if args.slo is not None:
        try:
            with open(args.slo, "r", encoding="utf-8") as f:
                spec = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"apex_tpu.monitor: cannot read SLO spec {args.slo}: "
                  f"{exc}", file=sys.stderr)
            return 2
    if args.trace is not None:
        try:
            return _print_trace(args.path, args.trace)
        except OSError as exc:
            print(f"apex_tpu.monitor: cannot read {args.path}: {exc}",
                  file=sys.stderr)
            return 2
    if args.follow:
        return _follow(args.path, spec=spec, as_json=args.json,
                       poll_s=args.poll_s, max_polls=args.max_polls)
    try:
        report = build_report(args.path, slo_spec=spec)
    except OSError as exc:
        print(f"apex_tpu.monitor: cannot read {args.path}: {exc}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render_report(report))
    return 0
