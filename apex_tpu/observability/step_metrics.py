"""Per-step training metrics: wall time, throughput, MFU, memory.

:class:`StepMetrics` is the layer :func:`apex_tpu.resilience.run_training`
drives when a :class:`~apex_tpu.observability.registry.MetricsRegistry`
is attached (``ResilienceConfig.metrics``). It splits each step's
telemetry across the two moments the driver actually has the data:

- ``begin_step()`` / ``end_step(step)`` bracket the step call on the
  host. The wall interval is dispatch time plus whatever the device made
  the host wait for — in steady state (the dispatch queue full, which is
  how a healthy run behaves) it converges to true device step time
  without ever forcing a sync. Throughput (``tokens_per_s``) and MFU
  follow from the knobs below; device ``memory_stats()`` gauges refresh
  every ``memory_interval_steps``.
- ``record_polled(step, loss=..., ...)`` lands later, at the driver's
  watchdog poll boundary, when loss/grad-norm/skipped/loss-scale come
  back from the device in a batch. It joins them with the buffered wall
  timing and emits one ``kind="step"`` record per step to the sinks.

MFU = ``model_flops_per_step / step_time / peak_flops`` — model FLOPs
from :mod:`apex_tpu.utils.flops` (the same estimators the benchmark
harness uses), peak from the chip table unless overridden (pass
``peak_flops`` explicitly on CPU or unlisted hardware).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from apex_tpu.utils.flops import peak_flops_per_chip

__all__ = ["StepTimer", "StepMetrics"]


class StepTimer:
    """Context manager timing one block into a histogram:
    ``with StepTimer(reg, "data_wait_s"): batch = next(it)``."""

    def __init__(self, registry, name: str,
                 clock: Callable[[], float] = time.perf_counter):
        self._registry = registry
        self.name = name
        self._clock = clock
        self.elapsed: Optional[float] = None

    def __enter__(self):
        self._t0 = self._clock()
        return self

    def __exit__(self, *exc):
        self.elapsed = self._clock() - self._t0
        self._registry.observe(self.name, self.elapsed)
        return False


class StepMetrics:
    """Feeds a registry with per-step timing/throughput/MFU/memory.

    Args:
      registry: the :class:`MetricsRegistry` to emit into.
      tokens_per_step: global tokens consumed per step — enables
        ``tokens_per_s``.
      model_flops_per_step: model FLOPs per step (see
        :mod:`apex_tpu.utils.flops`) — enables ``model_tflops`` and,
        with a known peak, ``mfu``.
      peak_flops: per-chip peak FLOP/s; defaults to the chip table
        (None on CPU — MFU then stays unset).
      memory_interval_steps: refresh device memory gauges every N steps
        (0 disables; backends without ``memory_stats`` emit nothing).
      clock: injectable monotonic clock, for deterministic tests.
    """

    def __init__(self, registry, *, tokens_per_step: Optional[int] = None,
                 model_flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 memory_interval_steps: int = 50,
                 clock: Callable[[], float] = time.perf_counter):
        self.registry = registry
        self.tokens_per_step = tokens_per_step
        self.model_flops_per_step = model_flops_per_step
        self.peak_flops = (peak_flops if peak_flops is not None
                           else peak_flops_per_chip())
        self.memory_interval_steps = int(memory_interval_steps)
        self._clock = clock
        self._t0: Optional[float] = None
        # wall timings buffered until the poll boundary delivers the
        # device-side values for the same step; bounded by the driver's
        # poll interval (entries are popped in record_polled)
        self._pending: Dict[int, dict] = {}

    # -- step-loop side ----------------------------------------------------

    def begin_step(self) -> None:
        self._t0 = self._clock()

    def end_step(self, step: int) -> None:
        """Record the wall interval for ``step`` (1-based, the value after
        the driver increments). No device sync happens here."""
        if self._t0 is None:
            return
        dt = self._clock() - self._t0
        self._t0 = None
        reg = self.registry
        reg.observe("step_time_s", dt)
        timing = {"step_time_s": dt}
        if dt > 0 and self.tokens_per_step:
            tps = self.tokens_per_step / dt
            reg.observe("tokens_per_s", tps)
            reg.set_gauge("tokens_per_s", tps)
            timing["tokens_per_s"] = tps
        if dt > 0 and self.model_flops_per_step:
            tflops = self.model_flops_per_step / dt / 1e12
            reg.set_gauge("model_tflops", tflops)
            timing["model_tflops"] = tflops
            if self.peak_flops:
                mfu = self.model_flops_per_step / dt / self.peak_flops
                reg.observe("mfu", mfu)
                reg.set_gauge("mfu", mfu)
                timing["mfu"] = mfu
        self._pending[step] = timing
        if (self.memory_interval_steps
                and step % self.memory_interval_steps == 0):
            self.record_memory()

    def record_memory(self) -> None:
        """Gauge ``memory/device<i>/<stat>`` from each local device's
        ``memory_stats()`` (a host-side query, not a sync); silently a
        no-op on backends that expose none (CPU)."""
        import jax

        for i, dev in enumerate(jax.local_devices()):
            stats = getattr(dev, "memory_stats", lambda: None)() or {}
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"):
                if key in stats:
                    self.registry.set_gauge(f"memory/device{i}/{key}",
                                            stats[key])

    # -- poll-boundary side ------------------------------------------------

    def record_polled(self, step: int, *, loss: Optional[float] = None,
                      grad_norm: Optional[float] = None,
                      skipped: bool = False,
                      loss_scale: Optional[float] = None) -> dict:
        """Join device-side values for ``step`` with its buffered wall
        timing and emit the per-step record. Returns the record."""
        record = {"kind": "step", "step": int(step),
                  **self._pending.pop(step, {})}
        reg = self.registry
        if loss is not None:
            record["loss"] = float(loss)
            reg.set_gauge("loss", float(loss))
            if not skipped and loss == loss:  # finite-ish: NaN != NaN
                reg.observe("loss", float(loss))
        if grad_norm is not None:
            record["grad_norm"] = float(grad_norm)
            if not skipped and grad_norm == grad_norm:
                reg.observe("grad_norm", float(grad_norm))
        if loss_scale is not None:
            record["loss_scale"] = float(loss_scale)
            reg.set_gauge("loss_scale", float(loss_scale))
        record["skipped"] = bool(skipped)
        reg.emit_step(record)
        return record
