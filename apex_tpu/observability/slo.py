"""SLO specs and run-log scoring — the measurable half of the serving
claims.

PRs 4–5 built a serving tier whose latency, goodput-under-shedding, and
crash-recovery behavior were asserted anecdotally (an example run, a
benchmark row). This module turns those claims into declared
**service-level objectives** evaluated from the same ``kind="request"``
/ ``kind="event"`` JSONL stream the engine already emits:

- :data:`SLO_METRICS` names every scoreable metric and its direction
  (is a bigger number better or worse?);
- :func:`measure_slo_metrics` folds a record list into measured values
  (p50/p99 TTFT and TPOT, p99 request latency, goodput, error-budget
  fraction, recovery time from a disruption to the first post-recovery
  completion);
- :class:`SLOSpec` declares thresholds (usually embedded in a loadtest
  scenario's ``"slo"`` section and echoed into the run log as the
  ``kind="scenario"`` record, so a log scores itself);
- :func:`evaluate_slos` produces the per-objective PASS/FAIL verdict the
  monitor renders and ``python -m apex_tpu.loadtest --check`` gates on.

Pure stdlib on purpose, like :mod:`~apex_tpu.observability.report`: the
verdict must be computable wherever the log file can be copied — no jax,
no serving imports (finish reasons are mirrored as string literals).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from apex_tpu.observability.registry import percentile

__all__ = ["SLO_METRICS", "OK_FINISH_REASONS", "SLOSpec", "SLOObjective",
           "SLOReport", "measure_slo_metrics", "evaluate_slos"]

#: finish reasons that count as successfully served work (mirrors
#: ``apex_tpu.serving.FINISH_EOS``/``FINISH_LENGTH`` — string literals
#: here so the scorer stays importable without jax)
OK_FINISH_REASONS = ("eos", "length")

#: every scoreable metric: name -> (direction, description). Direction
#: ``"max"`` means the spec value is an upper bound (latencies, error
#: budget — smaller is better); ``"min"`` means a lower bound (goodput).
#: The regression gate reuses the same table: a "max" metric regresses
#: by growing, a "min" metric by shrinking.
SLO_METRICS: Dict[str, tuple] = {
    "ttft_p50_s": ("max", "p50 time to first token (submit -> token #1)"),
    "ttft_p99_s": ("max", "p99 time to first token"),
    "tpot_p50_s": ("max", "p50 time per output token (inter-token mean)"),
    "tpot_p99_s": ("max", "p99 time per output token"),
    "latency_p99_s": ("max", "p99 total latency over completed requests"),
    "goodput": ("min", "fraction of submitted requests finishing "
                       "eos/length (completions per unit of offered "
                       "load — what shedding is supposed to protect)"),
    "goodput_interactive": ("min", "goodput over interactive-class "
                                   "requests only (what the brownout "
                                   "ladder and preemption protect); "
                                   "None when the log has no "
                                   "priority-stamped interactive rows"),
    "error_budget": ("max", "fraction of submitted requests finishing "
                            "error (quarantine, retry exhaustion)"),
    "recovery_s": ("max", "worst gap from a disruption (engine_restart "
                          "or breaker_open) to the first post-recovery "
                          "completion; inf when service never recovered"),
}


def measure_slo_metrics(records: List[dict], *, by_adapter: bool = False):
    """Fold a record list (:func:`~apex_tpu.observability.report.\
read_records` output) into measured values for every
    :data:`SLO_METRICS` key. ``None`` marks a metric the log cannot
    support (no requests, no disruptions, no TTFT-stamped records — e.g.
    a pre-TTFT run log); an objective declared against a ``None`` metric
    FAILS rather than silently passing.

    With ``by_adapter=True`` the same fold runs once per tenant instead:
    the return value is ``{adapter_id: metrics_dict}`` over the
    ``adapter_id`` stamped on each request record (``"base"`` for
    un-adapted traffic), each inner dict extended with a ``"requests"``
    count. Events are withheld from the per-tenant folds — a disruption
    is fleet-wide, so ``recovery_s`` stays a whole-run metric and reads
    ``None`` per tenant. Per-tenant dicts are attribution output, NOT
    baseline payloads: they must never be merged into the flat metrics
    dict that :class:`SLOSpec`/the regression gate consume.
    """
    if by_adapter:
        groups: Dict[str, List[dict]] = {}
        for r in records:
            if r.get("kind") == "request":
                groups.setdefault(str(r.get("adapter_id", "base")),
                                  []).append(r)
        out: Dict[str, Dict[str, Optional[float]]] = {}
        for adapter_id, rows in sorted(groups.items()):
            metrics = measure_slo_metrics(rows)
            metrics["requests"] = len(rows)
            out[adapter_id] = metrics
        return out
    requests = [r for r in records if r.get("kind") == "request"]
    ok = [r for r in requests
          if r.get("finish_reason") in OK_FINISH_REASONS]
    errors = [r for r in requests if r.get("finish_reason") == "error"]

    def _vals(rows, key):
        return [float(r[key]) for r in rows
                if isinstance(r.get(key), (int, float))]

    def _pct(values, p):
        return percentile(values, p) if values else None

    ttfts = _vals(requests, "ttft_s")
    tpots = _vals(requests, "tpot_s")
    latencies = _vals(ok, "total_s")

    # per-class goodput: only rows that DECLARE the class count (a
    # pre-priority log measures None, so old logs never fail the new
    # objective unless a scenario explicitly declares it)
    interactive = [r for r in requests
                   if r.get("priority") == "interactive"]
    interactive_ok = [r for r in interactive
                      if r.get("finish_reason") in OK_FINISH_REASONS]

    metrics: Dict[str, Optional[float]] = {
        "ttft_p50_s": _pct(ttfts, 50),
        "ttft_p99_s": _pct(ttfts, 99),
        "tpot_p50_s": _pct(tpots, 50),
        "tpot_p99_s": _pct(tpots, 99),
        "latency_p99_s": _pct(latencies, 99),
        "goodput": len(ok) / len(requests) if requests else None,
        "goodput_interactive": (len(interactive_ok) / len(interactive)
                                if interactive else None),
        "error_budget": len(errors) / len(requests) if requests else None,
    }

    # recovery time: for each disruption, the gap to the FIRST successful
    # completion that lands after it (wall-clock correlated — the same
    # stamps log_event/registry events carry). A disruption with no
    # later completion means the run never recovered: inf, which fails
    # any finite bound.
    disruptions = [float(r["wall"]) for r in records
                   if r.get("kind") == "event"
                   and r.get("event") in ("engine_restart", "breaker_open")
                   and isinstance(r.get("wall"), (int, float))]
    completions = sorted(float(r["wall"]) for r in ok
                         if isinstance(r.get("wall"), (int, float)))
    if disruptions:
        gaps = []
        for d in disruptions:
            later = [c for c in completions if c > d]
            gaps.append(later[0] - d if later else float("inf"))
        metrics["recovery_s"] = max(gaps)
    else:
        metrics["recovery_s"] = None
    return metrics


@dataclass(frozen=True)
class SLOSpec:
    """Declared objectives: ``{metric_name: threshold}`` over
    :data:`SLO_METRICS` keys. Direction comes from the table — a
    ``"max"`` metric must measure at or below its threshold, a ``"min"``
    metric at or above."""

    objectives: Dict[str, float]

    def __post_init__(self):
        for name, value in self.objectives.items():
            if name not in SLO_METRICS:
                raise ValueError(
                    f"unknown SLO metric {name!r}; known: "
                    f"{sorted(SLO_METRICS)}")
            if not isinstance(value, (int, float)) or value != value:
                raise ValueError(
                    f"SLO threshold for {name!r} must be a number, "
                    f"got {value!r}")

    @classmethod
    def from_dict(cls, data: Dict[str, float]) -> "SLOSpec":
        return cls(objectives=dict(data))

    def to_dict(self) -> Dict[str, float]:
        return dict(self.objectives)


@dataclass(frozen=True)
class SLOObjective:
    """One scored objective: the threshold, what the log measured, and
    the verdict. ``measured is None`` (metric unsupported by the log)
    fails — a gate must not go green on missing data."""

    name: str
    direction: str
    threshold: float
    measured: Optional[float]
    ok: bool

    def as_dict(self) -> dict:
        return {"name": self.name, "direction": self.direction,
                "threshold": self.threshold, "measured": self.measured,
                "ok": self.ok}


@dataclass(frozen=True)
class SLOReport:
    """The full verdict: every declared objective scored, plus the
    complete measured-metrics dict (also the regression-gate baseline
    payload — ``python -m apex_tpu.loadtest --update-baseline`` commits
    exactly these values)."""

    objectives: List[SLOObjective]
    metrics: Dict[str, Optional[float]]

    @property
    def ok(self) -> bool:
        return all(o.ok for o in self.objectives)

    @property
    def failures(self) -> List[SLOObjective]:
        return [o for o in self.objectives if not o.ok]

    def as_dict(self) -> dict:
        return {"ok": self.ok,
                "objectives": [o.as_dict() for o in self.objectives],
                "metrics": dict(self.metrics)}


def evaluate_slos(records: List[dict], spec: SLOSpec) -> SLOReport:
    """Score ``records`` against ``spec``. Deterministic in the record
    list; objectives are reported in the spec's declaration order."""
    metrics = measure_slo_metrics(records)
    objectives = []
    for name, threshold in spec.objectives.items():
        direction = SLO_METRICS[name][0]
        measured = metrics.get(name)
        if measured is None:
            ok = False      # no data never passes a declared objective
        elif direction == "max":
            ok = measured <= threshold
        else:
            ok = measured >= threshold
        objectives.append(SLOObjective(
            name=name, direction=direction, threshold=float(threshold),
            measured=measured, ok=ok))
    return SLOReport(objectives=objectives, metrics=metrics)
