"""The metrics registry: counters, gauges, bounded histograms, sinks.

The hub of :mod:`apex_tpu.observability`. Producers (the resilience
driver's step loop, the retrace watchdog, span timers — some on other
threads) call :meth:`MetricsRegistry.inc` / :meth:`set_gauge` /
:meth:`observe` / :meth:`event`; consumers are pluggable sinks
(:mod:`apex_tpu.observability.sinks`) that receive a stream of plain-dict
records:

- ``{"kind": "event", "event": <name>, "seq": n, "ts": <monotonic>,
  "wall": <epoch>, ...fields}`` — one per incident, emitted immediately;
- ``{"kind": "step", "step": i, ...}`` — one per training step
  (:class:`~apex_tpu.observability.step_metrics.StepMetrics` builds these);
- ``{"kind": "counters"|"gauges"|"histograms", "wall": ...,
  "values": {...}}`` — full snapshots, emitted on :meth:`flush`.

Everything is host-side Python — nothing here touches a device or a
trace, so it is safe to call from watchdog threads and from inside the
step loop without perturbing XLA. Histograms keep running aggregates
(count/sum/min/max) exactly plus a **bounded** ring buffer of recent
values for percentiles, so registry memory does not grow with step count.

A single re-entrant lock serializes state mutation *and* sink writes:
sinks need not be thread-safe, and a snapshot never interleaves with a
half-applied update. Like ``log_event``, every event carries a strictly
increasing ``seq`` plus monotonic ``ts`` and epoch ``wall`` stamps so
incidents totally order and correlate across hosts.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

__all__ = ["MetricsRegistry", "HistogramSnapshot", "percentile"]


def percentile(values: List[float], p: float) -> float:
    """Nearest-rank percentile of ``values`` (need not be sorted).
    ``p`` in [0, 100]. Raises ValueError on an empty list."""
    if not values:
        raise ValueError("percentile of empty sequence")
    ordered = sorted(values)
    if p <= 0:
        return ordered[0]
    if p >= 100:
        return ordered[-1]
    # nearest-rank: smallest value with at least p% of the mass at or below
    rank = max(1, -(-int(p * len(ordered)) // 100))  # ceil(p*n/100)
    return ordered[rank - 1]


class HistogramSnapshot:
    """Immutable view of a histogram: exact running aggregates plus
    percentiles over the bounded ring of recent observations."""

    __slots__ = ("name", "count", "sum", "min", "max", "_recent")

    def __init__(self, name: str, count: int, total: float,
                 lo: float, hi: float, recent: List[float]):
        self.name = name
        self.count = count
        self.sum = total
        self.min = lo
        self.max = hi
        self._recent = recent

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    @property
    def recent(self) -> List[float]:
        """The bounded window of recent observations (a copy) — what
        percentiles are computed over, and what
        :func:`~apex_tpu.observability.fleet_metrics.merge_histograms`
        concatenates when combining per-replica snapshots."""
        return list(self._recent)

    def percentile(self, p: float) -> float:
        return percentile(self._recent, p)

    def as_dict(self) -> dict:
        d = {"count": self.count, "sum": self.sum,
             "min": self.min, "max": self.max, "mean": self.mean}
        if self._recent:
            d["p50"] = self.percentile(50)
            d["p95"] = self.percentile(95)
        return d


class _Histogram:
    __slots__ = ("count", "total", "min", "max", "ring")

    def __init__(self, bound: int):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        # percentiles come from a bounded window of recent values: memory
        # is O(bound) no matter how many steps a run observes
        self.ring: deque = deque(maxlen=bound)

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        self.ring.append(value)

    def snapshot(self, name: str) -> HistogramSnapshot:
        return HistogramSnapshot(name, self.count, self.total,
                                 self.min, self.max, list(self.ring))


class MetricsRegistry:
    """Thread-safe counters/gauges/histograms with pluggable sinks.

    Args:
      sinks: initial sinks (see :mod:`apex_tpu.observability.sinks`);
        more can be attached with :meth:`add_sink`.
      histogram_bound: ring-buffer size per histogram — the memory bound
        behind percentile estimates.
    """

    def __init__(self, sinks: Iterable = (), *, histogram_bound: int = 1024):
        self._lock = threading.RLock()
        self._sinks = list(sinks)
        self._histogram_bound = int(histogram_bound)
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, _Histogram] = {}
        self._seq = 0

    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    # -- producers ---------------------------------------------------------

    def declare_counters(self, *names: str) -> None:
        """Zero-initialize counters so snapshots carry every expected key
        even when an incident type never fires during the run."""
        with self._lock:
            for n in names:
                self._counters.setdefault(n, 0)

    def inc(self, name: str, n: int = 1) -> int:
        """Increment (and implicitly declare) a counter; returns the new
        value."""
        with self._lock:
            value = self._counters.get(name, 0) + int(n)
            self._counters[name] = value
            return value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Record one observation into the named bounded histogram."""
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = _Histogram(
                    self._histogram_bound)
            hist.observe(float(value))

    def event(self, name: str, **fields) -> dict:
        """Emit one incident record to every sink, stamped with
        ``seq``/``ts``/``wall`` (mirrors ``log_event``'s stamps so JSONL
        events and log lines correlate). Returns the record."""
        with self._lock:
            self._seq += 1
            record = {"kind": "event", "event": name, "seq": self._seq,
                      "ts": time.monotonic(), "wall": time.time(),
                      **fields}
            self._write(record)
            return record

    def emit_record(self, record: dict) -> None:
        """Forward one pre-built record to the sinks — the generic form
        behind :meth:`emit_step`; serving uses it for its per-request
        ``kind="request"`` rows."""
        with self._lock:
            self._write(record)

    def emit_step(self, record: dict) -> None:
        """Forward one per-step record (``kind="step"``) to the sinks."""
        self.emit_record(record)

    # -- consumers ---------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def histogram(self, name: str) -> Optional[HistogramSnapshot]:
        with self._lock:
            hist = self._histograms.get(name)
            return None if hist is None else hist.snapshot(name)

    def histograms(self) -> Dict[str, HistogramSnapshot]:
        with self._lock:
            return {n: h.snapshot(n) for n, h in self._histograms.items()}

    # -- lifecycle ---------------------------------------------------------

    def flush(self) -> None:
        """Write counter/gauge/histogram snapshots to every sink and flush
        the sinks. Call at poll boundaries and at end of run — the final
        counters snapshot is what ``python -m apex_tpu.monitor`` reconciles
        against ``TrainingResult.telemetry``."""
        with self._lock:
            wall = time.time()
            self._write({"kind": "counters", "wall": wall,
                         "values": dict(self._counters)})
            self._write({"kind": "gauges", "wall": wall,
                         "values": dict(self._gauges)})
            self._write({"kind": "histograms", "wall": wall,
                         "values": {n: h.snapshot(n).as_dict()
                                    for n, h in self._histograms.items()}})
            for sink in self._sinks:
                sink.flush()

    def close(self) -> None:
        """Flush, then close every attached sink."""
        with self._lock:
            self.flush()
            for sink in self._sinks:
                sink.close()

    def _write(self, record: dict) -> None:
        for sink in self._sinks:
            sink.write(record)
