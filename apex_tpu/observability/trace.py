"""Request-level tracing: typed spans over the serving request lifecycle.

Every :class:`~apex_tpu.serving.Request` is minted with a ``trace_id``
at construction; the serving tier (scheduler admission through engine
prefill/decode, supervisor restarts, fleet migration) stamps typed
spans into the :class:`~apex_tpu.observability.MetricsRegistry` as
``kind="span"`` JSONL rows, one timeline per request:

- **phase spans** (:data:`PHASE_SPANS` — ``queued``, ``prefill``,
  ``decode``, ``shed``) are disjoint and contiguous; their durations sum
  to the request's measured ``total_s``. They are emitted together at
  the request's single terminal choke point (the engine/supervisor/
  fleet ``_finish``-style retirement that also writes the
  ``kind="request"`` record), from the *same* timestamps that produce
  ``queue_s``/``prefill_s``/``decode_s`` — so conservation holds by
  construction and exactly-once holds under supervisor restarts (a dead
  engine incarnation emits neither a record nor spans).
- **mark spans** (:data:`MARK_SPANS` — ``spec_verify``, ``migration``,
  ``quarantine``, ``preempt``, ``resume``) annotate the timeline
  (speculation totals, a migration handoff, a quarantine scrub, a
  priority preemption park and its later resume) and are excluded from
  the conservation sum — they overlap the phases they explain.

Every span increments a ``spans_<name>`` counter, so the final counters
snapshot reconciles key-for-key with the span rows in the log —
:func:`check_span_conservation` asserts both invariants and is wired
into ``python -m apex_tpu.loadtest --check``.

Pure stdlib on purpose: the monitor/gate read path stays jax-free.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Sequence

__all__ = [
    "SPAN_QUEUED", "SPAN_PREFILL", "SPAN_DECODE", "SPAN_SHED",
    "SPAN_SPEC_VERIFY", "SPAN_MIGRATION", "SPAN_QUARANTINE",
    "SPAN_PREEMPT", "SPAN_RESUME",
    "PHASE_SPANS", "MARK_SPANS", "SPAN_COUNTER_PREFIX",
    "new_trace_id", "emit_span", "emit_request_spans",
    "build_timelines", "format_timeline", "check_span_conservation",
]

#: phase spans: disjoint, contiguous, sum == the request's ``total_s``
SPAN_QUEUED = "queued"
SPAN_PREFILL = "prefill"
SPAN_DECODE = "decode"
SPAN_SHED = "shed"
PHASE_SPANS = (SPAN_QUEUED, SPAN_PREFILL, SPAN_DECODE, SPAN_SHED)

#: mark spans: overlapping annotations, excluded from the conservation sum.
#: ``preempt`` is a zero-width mark the engine stamps when it parks a
#: running slot for a higher class; ``resume`` is its zero-width partner
#: the supervisor stamps when the parked request's continuation is
#: resubmitted — both carry the request's ORIGINAL trace_id, so a
#: preempted request's timeline reads queued/prefill/decode with the
#: park/resume gap annotated, and conservation stays exact (the terminal
#: record is emitted by the finishing incarnation from its own clock).
SPAN_SPEC_VERIFY = "spec_verify"
SPAN_MIGRATION = "migration"
SPAN_QUARANTINE = "quarantine"
SPAN_PREEMPT = "preempt"
SPAN_RESUME = "resume"
MARK_SPANS = (SPAN_SPEC_VERIFY, SPAN_MIGRATION, SPAN_QUARANTINE,
              SPAN_PREEMPT, SPAN_RESUME)

#: every emitted span increments ``f"{SPAN_COUNTER_PREFIX}{name}"``
SPAN_COUNTER_PREFIX = "spans_"


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id. Unlike ``request_id`` (a process-
    local monotonic int), a trace id survives supervisor restarts and
    fleet migration verbatim — continuations are built with the original
    request's trace id — and is unique across processes, so merged fleet
    logs never collide."""
    return uuid.uuid4().hex[:16]


def emit_span(registry, name: str, *, trace_id: str, request_id: int,
              start_s: float, end_s: float, wall: float,
              replica_id: Optional[int] = None,
              detail: Optional[str] = None, **fields) -> dict:
    """Stamp one span row into ``registry`` (and bump its
    ``spans_<name>`` counter). ``start_s``/``end_s`` are on the
    process-monotonic clock — the same clock as the request timestamps
    the terminal record's durations are computed from."""
    record = {
        "kind": "span", "span": name, "trace_id": trace_id,
        "request_id": request_id, "start_s": start_s, "end_s": end_s,
        "duration_s": end_s - start_s, "wall": wall,
    }
    if replica_id is not None:
        record["replica_id"] = replica_id
    if detail is not None:
        record["detail"] = detail
    record.update(fields)
    registry.inc(SPAN_COUNTER_PREFIX + name)
    registry.emit_record(record)
    return record


def emit_request_spans(registry, *, trace_id: str, request_id: int,
                       submit_ts: float, now: float, wall: float,
                       prefill_start: float = 0.0,
                       prefill_end: float = 0.0,
                       replica_id: Optional[int] = None,
                       prefill_segments: Sequence[float] = (),
                       detail: Optional[str] = None) -> List[dict]:
    """Emit the request's phase-span timeline at its terminal choke
    point, from the same timestamps that produced the terminal record's
    ``queue_s``/``prefill_s``/``decode_s`` decomposition:

    - a request that reached prefill gets the full
      ``queued -> prefill -> decode`` trio;
    - a request shed before prefill gets a single span: ``shed`` when a
      shed ``detail`` is given (queue_full/deadline_expired/...), else
      ``queued`` (cancelled or expired while waiting).

    ``prefill_segments`` are the INTERIOR chunk-boundary timestamps of a
    chunked prefill (docs/serving.md#chunked-prefill): the prefill phase
    is then emitted as one span per chunk — contiguous by construction,
    covering exactly ``[prefill_start, prefill_end]``, so the
    conservation invariants (gap-free, sum == ``total_s``) hold
    unchanged while the timeline shows every chunk the tick budget
    carved. Empty for a monolithic prefill (one span, the pre-chunking
    timeline bit-for-bit).
    """
    if prefill_start:
        spans = [
            emit_span(registry, SPAN_QUEUED, trace_id=trace_id,
                      request_id=request_id, start_s=submit_ts,
                      end_s=prefill_start, wall=wall,
                      replica_id=replica_id),
        ]
        bounds = [prefill_start, *prefill_segments, prefill_end]
        for seg, (seg_start, seg_end) in enumerate(
                zip(bounds, bounds[1:])):
            spans.append(emit_span(
                registry, SPAN_PREFILL, trace_id=trace_id,
                request_id=request_id, start_s=seg_start, end_s=seg_end,
                wall=wall, replica_id=replica_id,
                **({"chunk": seg} if len(bounds) > 2 else {})))
        spans.append(
            emit_span(registry, SPAN_DECODE, trace_id=trace_id,
                      request_id=request_id, start_s=prefill_end,
                      end_s=now, wall=wall, replica_id=replica_id))
        return spans
    name = SPAN_SHED if detail is not None else SPAN_QUEUED
    return [emit_span(registry, name, trace_id=trace_id,
                      request_id=request_id, start_s=submit_ts,
                      end_s=now, wall=wall, replica_id=replica_id,
                      detail=detail)]


# -- read path (monitor / gate) -------------------------------------------

def build_timelines(records: Sequence[dict]) -> Dict[int, List[dict]]:
    """Group ``kind="span"`` rows by ``request_id``, each timeline
    sorted by ``start_s`` (phase spans before marks at equal starts, so
    a rendered timeline reads causally)."""
    timelines: Dict[int, List[dict]] = {}
    for rec in records:
        if rec.get("kind") != "span":
            continue
        timelines.setdefault(rec.get("request_id"), []).append(rec)
    for spans in timelines.values():
        spans.sort(key=lambda s: (s.get("start_s", 0.0),
                                  s.get("span") in MARK_SPANS))
    return timelines


def format_timeline(request_id: int, spans: Sequence[dict],
                    result: Optional[dict] = None) -> str:
    """Human rendering of one request's span timeline (the monitor's
    ``--trace`` output). Offsets are relative to the first span start."""
    if not spans:
        return f"request {request_id}: no spans recorded"
    t0 = min(s.get("start_s", 0.0) for s in spans)
    lines = [f"request {request_id}  trace_id="
             f"{spans[0].get('trace_id', '?')}"]
    if result is not None:
        lines[0] += (f"  finish={result.get('finish_reason', '?')}"
                     f"  total={result.get('total_s', 0.0):.4f}s")
    for s in spans:
        start = s.get("start_s", 0.0) - t0
        dur = s.get("duration_s", 0.0)
        mark = " (mark)" if s.get("span") in MARK_SPANS else ""
        extra = ""
        if s.get("detail"):
            extra += f"  detail={s['detail']}"
        if s.get("replica_id") is not None:
            extra += f"  replica={s['replica_id']}"
        for key in ("chunk", "proposed", "accepted", "from_replica",
                    "tokens_carried", "tokens_parked", "priority"):
            if key in s:
                extra += f"  {key}={s[key]}"
        lines.append(f"  +{start:9.4f}s  {s.get('span', '?'):<11}"
                     f" {dur:9.4f}s{mark}{extra}")
    phases = [s for s in spans if s.get("span") in PHASE_SPANS]
    lines.append(f"  span sum: "
                 f"{sum(s.get('duration_s', 0.0) for s in phases):.4f}s"
                 f" over {len(phases)} phase span(s)")
    return "\n".join(lines)


def check_span_conservation(records: Sequence[dict], *,
                            rel_tol: float = 0.02,
                            abs_tol: float = 0.002) -> List[str]:
    """Validate the tracing invariants over a record stream; returns a
    list of human-readable violations (empty == conserved).

    For every terminal ``kind="request"`` row that carries a
    ``trace_id`` (pre-tracing logs are vacuously conserved):

    1. the request has at least one phase span, all stamped with the
       request's own trace id;
    2. phase spans are disjoint and gap-free: sorted by start, each
       begins where the previous ended (within ``abs_tol``);
    3. phase durations sum to the record's ``total_s`` within
       ``rel_tol * total_s + abs_tol``.

    Additionally the last ``kind="counters"`` snapshot's ``spans_*``
    entries must reconcile key-for-key with the span rows in the
    stream.
    """
    violations: List[str] = []
    timelines = build_timelines(records)
    counters: Optional[dict] = None
    for rec in records:
        if rec.get("kind") == "counters":
            counters = rec.get("values", {})
    for rec in records:
        if rec.get("kind") != "request" or not rec.get("trace_id"):
            continue
        rid = rec.get("request_id")
        trace_id = rec["trace_id"]
        spans = timelines.get(rid, [])
        phases = [s for s in spans if s.get("span") in PHASE_SPANS]
        if not phases:
            violations.append(
                f"request {rid}: terminal record has trace_id "
                f"{trace_id} but no phase spans")
            continue
        for s in spans:
            if s.get("trace_id") != trace_id:
                violations.append(
                    f"request {rid}: span {s.get('span')!r} trace_id "
                    f"{s.get('trace_id')} != record trace_id {trace_id}")
        for prev, nxt in zip(phases, phases[1:]):
            gap = abs(nxt.get("start_s", 0.0) - prev.get("end_s", 0.0))
            if gap > abs_tol:
                violations.append(
                    f"request {rid}: {gap:.6f}s gap between "
                    f"{prev.get('span')!r} and {nxt.get('span')!r}")
        total = rec.get("total_s", 0.0)
        span_sum = sum(s.get("duration_s", 0.0) for s in phases)
        tol = rel_tol * abs(total) + abs_tol
        if abs(span_sum - total) > tol:
            violations.append(
                f"request {rid}: phase span sum {span_sum:.6f}s != "
                f"total_s {total:.6f}s (tol {tol:.6f}s)")
    # counter reconciliation: spans_* in the final snapshot vs the rows
    if counters is not None:
        by_name: Dict[str, int] = {}
        for spans in timelines.values():
            for s in spans:
                name = s.get("span")
                by_name[name] = by_name.get(name, 0) + 1
        names = set(by_name) | {
            k[len(SPAN_COUNTER_PREFIX):] for k in counters
            if k.startswith(SPAN_COUNTER_PREFIX)}
        for name in sorted(names):
            counted = counters.get(SPAN_COUNTER_PREFIX + name, 0)
            seen = by_name.get(name, 0)
            if counted != seen:
                violations.append(
                    f"span counter {SPAN_COUNTER_PREFIX}{name}="
                    f"{counted} but {seen} span row(s) in the log")
    return violations
