"""Metric sinks: where :class:`~apex_tpu.observability.registry.
MetricsRegistry` records land.

A sink is any object with ``write(record: dict)``, ``flush()`` and
``close()``; the registry serializes all calls under its own lock, so
sinks need not be thread-safe. Three implementations:

- :class:`InMemorySink` — keeps records in a list; for tests and
  notebook inspection.
- :class:`JsonlSink` — one JSON object per line; the durable run log the
  ``python -m apex_tpu.monitor`` CLI reads back into a run report.
- :class:`PrometheusTextfileSink` — renders the latest counter/gauge/
  histogram snapshots in Prometheus text exposition format on ``flush``,
  atomically (write temp + rename), for the node-exporter textfile
  collector to scrape.
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional

__all__ = ["InMemorySink", "JsonlSink", "PrometheusTextfileSink"]


class InMemorySink:
    """Record list in memory — the test double."""

    def __init__(self):
        self.records: List[dict] = []
        self.closed = False

    def write(self, record: dict) -> None:
        self.records.append(record)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self.closed = True

    def of_kind(self, kind: str) -> List[dict]:
        return [r for r in self.records if r.get("kind") == kind]


class JsonlSink:
    """Append records as JSON lines to ``path`` (parent dirs created).

    Non-JSON-serializable field values degrade to ``str(value)`` rather
    than killing the training loop — a telemetry write must never be the
    thing that takes a run down.
    """

    def __init__(self, path: str):
        self.path = os.fspath(path)
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        self._file = open(self.path, "a", encoding="utf-8")

    def write(self, record: dict) -> None:
        try:
            line = json.dumps(record)
        except (TypeError, ValueError):
            line = json.dumps({k: _jsonable(v) for k, v in record.items()})
        self._file.write(line + "\n")

    def flush(self) -> None:
        self._file.flush()

    def close(self) -> None:
        if not self._file.closed:
            self._file.flush()
            self._file.close()


def _jsonable(value):
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return str(value)


# Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*
_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _split_labels(name: str) -> tuple:
    """Split ``kv_pages_free{replica="1"}`` into the bare metric name
    and its ``{...}`` label block (empty string when unlabeled) — the
    registry stores labeled gauges as flat keys in this form."""
    brace = name.find("{")
    if brace == -1 or not name.endswith("}"):
        return name, ""
    return name[:brace], name[brace:]


def _prom_name(name: str, suffix: str = "") -> str:
    base, labels = _split_labels(name)
    base = _PROM_BAD.sub("_", base)
    if not re.match(r"[a-zA-Z_:]", base):
        base = "_" + base
    return f"apex_tpu_{base}{suffix}{labels}"


class PrometheusTextfileSink:
    """Textfile-collector exporter: keeps the most recent snapshot records
    and renders them to ``path`` on ``flush``. Counters render with a
    ``_total`` suffix, histograms as ``_count``/``_sum`` plus ``p50``/
    ``p95`` quantile gauges. Per-record writes other than snapshots are
    ignored — Prometheus scrapes state, not a stream."""

    def __init__(self, path: str):
        self.path = os.fspath(path)
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        self._counters: Optional[dict] = None
        self._gauges: Optional[dict] = None
        self._histograms: Optional[dict] = None

    def write(self, record: dict) -> None:
        kind = record.get("kind")
        if kind == "counters":
            self._counters = record.get("values", {})
        elif kind == "gauges":
            self._gauges = record.get("values", {})
        elif kind == "histograms":
            self._histograms = record.get("values", {})

    def flush(self) -> None:
        lines: List[str] = []
        typed: set = set()  # one TYPE line per metric family, not per label set
        for name, value in sorted((self._counters or {}).items()):
            metric = _prom_name(name, "_total")
            family = _prom_name(_split_labels(name)[0], "_total")
            if family not in typed:
                typed.add(family)
                lines.append(f"# TYPE {family} counter")
            lines.append(f"{metric} {value}")
        for name, value in sorted((self._gauges or {}).items()):
            metric = _prom_name(name)
            family = _prom_name(_split_labels(name)[0])
            if family not in typed:
                typed.add(family)
                lines.append(f"# TYPE {family} gauge")
            lines.append(f"{metric} {value}")
        for name, summ in sorted((self._histograms or {}).items()):
            base, labels = _split_labels(name)
            family = _prom_name(base)
            if family not in typed:
                typed.add(family)
                lines.append(f"# TYPE {family} summary")
            lines.append(f"{family}_count{labels} {summ.get('count', 0)}")
            lines.append(f"{family}_sum{labels} {summ.get('sum', 0.0)}")
            for q in ("p50", "p95"):
                if q in summ:
                    # fold quantile into the existing label block: a
                    # labeled series must stay one series per label set
                    quantile = f'quantile="0.{q[1:]}"'
                    block = (f"{labels[:-1]},{quantile}}}" if labels
                             else f"{{{quantile}}}")
                    lines.append(f"{family}{block} {summ[q]}")
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        os.replace(tmp, self.path)  # atomic: scrapers never see a torn file

    def close(self) -> None:
        self.flush()
