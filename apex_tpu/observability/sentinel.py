"""Drift sentinel: online anomaly detection over the fleet's health
signals.

Breakers and watchdogs catch *hard* failures — a hung tick, a crashed
engine. What they miss is **drift**: TTFT p99 creeping up as a replica's
page pool fragments, windowed goodput sagging under a slow memory leak,
queue depth climbing because one replica quietly serves at half speed.
Nothing trips, the loadtest gate fails hours later, and the evidence is
gone.

The :class:`DriftSentinel` polls ``FleetMetrics.signals()`` on the fleet
tick (same cadence seam as the autoscaler) and keeps, per monitored
signal, an EWMA baseline plus an EWMA of absolute deviation — a robust,
O(1)-memory scale estimate that one outlier can't crater. Each poll's
robust z-score ``|x - mean| / max(dev, floor)`` is compared against
``z_threshold`` **directionally** (high TTFT is an anomaly; low TTFT is
a good day): ``hysteresis_polls`` consecutive breaches arm the trigger,
a per-signal ``cooldown_s`` stops re-firing on the same excursion, and
``warmup_polls`` keeps the sentinel silent while the baseline learns.

Firing follows the observability plane's reconcile contract: one
``anomalies_total`` + ``anomalies_<signal>`` counter increment co-sited
with an ``event("anomaly", ...)`` and a typed ``kind="anomaly"`` record
(wall-stamped through the clock seam so replays are deterministic).
The ``anomaly`` event is an incident-class trigger for the
:class:`~apex_tpu.observability.recorder.FlightRecorder`, so a drift
that never trips a breaker still leaves a postmortem bundle.

As a satellite duty the sentinel also samples a ``kind="gauge_snapshot"``
record every ``snapshot_every_polls`` polls (labeled gauges + signals
excerpt, paired with a ``gauge_snapshots`` counter) — the live
trajectory feed for ``monitor --follow`` and the bundle's
signal-history section.

Pure stdlib; the detector core (:meth:`DriftSentinel.observe`) takes a
plain signals dict, so tests drive it without a fleet or jax.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from apex_tpu.observability.fleet_metrics import FleetMetrics
from apex_tpu.utils.logging import get_logger, log_event

__all__ = ["SentinelConfig", "DriftSentinel", "DEGRADE_DIRECTION"]

_LOG = get_logger(__name__)

#: which way each monitored signal degrades: ``"up"`` fires on values
#: above baseline, ``"down"`` on values below. Signals absent here are
#: treated two-sided.
DEGRADE_DIRECTION: Dict[str, str] = {
    "ttft_p99_s": "up",
    "tpot_p99_s": "up",
    "queue_depth": "up",
    "queued_tokens": "up",
    "goodput_window": "down",
    "spec_accept_rate": "down",
}

#: compact per-poll excerpt stamped into gauge_snapshot records — the
#: trajectory axes the monitor plots and bundles replay
_SNAPSHOT_SIGNALS = ("ttft_p99_s", "tpot_p99_s", "goodput_window",
                     "queue_depth", "inflight", "slot_occupancy",
                     "kv_page_occupancy", "spec_accept_rate")


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
    """Drift-detection policy knobs (validated up front — a bad config
    fails at construction, not at the 400th poll).

    ``signals`` names the ``FleetMetrics.signals()`` keys to watch;
    ``ewma_alpha`` is the baseline learning rate (higher = faster
    adaptation, lower = longer memory); ``z_threshold`` the robust
    z-score that counts as a breach; ``min_abs_dev`` floors the scale
    estimate so a perfectly-flat warmup can't make z explode on the
    first real wiggle. ``snapshot_every_polls=0`` disables the periodic
    gauge_snapshot feed."""

    poll_interval_s: float = 0.25
    warmup_polls: int = 8
    ewma_alpha: float = 0.2
    z_threshold: float = 4.0
    hysteresis_polls: int = 2
    cooldown_s: float = 10.0
    min_abs_dev: float = 1e-3
    snapshot_every_polls: int = 4
    signals: Tuple[str, ...] = ("ttft_p99_s", "tpot_p99_s",
                                "goodput_window", "queue_depth",
                                "spec_accept_rate")

    def __post_init__(self):
        if self.poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s must be > 0, "
                f"got {self.poll_interval_s}")
        if self.warmup_polls < 1:
            raise ValueError(
                f"warmup_polls must be >= 1, got {self.warmup_polls}")
        if not (0.0 < self.ewma_alpha <= 1.0):
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {self.ewma_alpha}")
        if self.z_threshold <= 0:
            raise ValueError(
                f"z_threshold must be > 0, got {self.z_threshold}")
        if self.hysteresis_polls < 1:
            raise ValueError(
                f"hysteresis_polls must be >= 1, "
                f"got {self.hysteresis_polls}")
        if self.cooldown_s < 0:
            raise ValueError(
                f"cooldown_s must be >= 0, got {self.cooldown_s}")
        if self.min_abs_dev <= 0:
            raise ValueError(
                f"min_abs_dev must be > 0, got {self.min_abs_dev}")
        if self.snapshot_every_polls < 0:
            raise ValueError(
                f"snapshot_every_polls must be >= 0, "
                f"got {self.snapshot_every_polls}")
        if not self.signals:
            raise ValueError("signals must name at least one "
                             "FleetMetrics.signals() key")


class _Tracker:
    """One signal's online baseline: EWMA mean + EWMA absolute
    deviation, warmup counter, breach streak, last-fire stamp."""

    __slots__ = ("mean", "dev", "samples", "streak", "last_fire_ts")

    def __init__(self):
        self.mean: Optional[float] = None
        self.dev = 0.0
        self.samples = 0
        self.streak = 0
        self.last_fire_ts: Optional[float] = None

    def update(self, value: float, alpha: float) -> None:
        if self.mean is None:
            self.mean = value
        else:
            self.dev += alpha * (abs(value - self.mean) - self.dev)
            self.mean += alpha * (value - self.mean)
        self.samples += 1

    def z(self, value: float, floor: float) -> float:
        if self.mean is None:
            return 0.0
        return abs(value - self.mean) / max(self.dev, floor)


class DriftSentinel:
    """Online drift detector over ``FleetMetrics.signals()``.

    Mirrors the :class:`~apex_tpu.serving.fleet.autoscale.Autoscaler`
    seam: the fleet tick calls :meth:`maybe_poll(fleet, now)`; the
    sentinel gates on ``poll_interval_s``, holds its own
    :class:`FleetMetrics` (window deltas are per-instance state), and
    emits through the fleet's registry. The pure core,
    :meth:`observe(signals, now)`, returns the anomalies a signals dict
    provokes — unit-testable with no fleet at all.
    """

    def __init__(self, config: Optional[SentinelConfig] = None):
        self.config = config or SentinelConfig()
        self._trackers: Dict[str, _Tracker] = {
            name: _Tracker() for name in self.config.signals}
        self._fm: Optional[FleetMetrics] = None
        self._last_poll: Optional[float] = None
        self._polls = 0
        self._declared = False

    @property
    def polls(self) -> int:
        """Completed observation polls (after the interval gate)."""
        return self._polls

    # -- pure detector core ------------------------------------------------

    def observe(self, signals: Dict[str, object],
                now: float) -> List[dict]:
        """Feed one signals sample; return the anomaly dicts it fires
        (``signal`` / ``value`` / ``baseline`` / ``deviation`` / ``z``).
        Missing or ``None`` signals are skipped — an idle window's
        ``ttft_p99_s=None`` is absence of evidence, not a zero."""
        self._polls += 1
        cfg = self.config
        fired: List[dict] = []
        for name, tracker in self._trackers.items():
            value = signals.get(name)
            if not isinstance(value, (int, float)) or isinstance(
                    value, bool):
                continue
            value = float(value)
            z = tracker.z(value, cfg.min_abs_dev)
            direction = DEGRADE_DIRECTION.get(name)
            degrading = (
                tracker.mean is not None
                and z >= cfg.z_threshold
                and (direction is None
                     or (direction == "up" and value > tracker.mean)
                     or (direction == "down" and value < tracker.mean)))
            armed = tracker.samples >= cfg.warmup_polls
            cooling = (tracker.last_fire_ts is not None
                       and now - tracker.last_fire_ts < cfg.cooldown_s)
            if degrading and armed and not cooling:
                tracker.streak += 1
                if tracker.streak >= cfg.hysteresis_polls:
                    tracker.streak = 0
                    tracker.last_fire_ts = now
                    fired.append({
                        "signal": name,
                        "value": value,
                        "baseline": tracker.mean,
                        "deviation": max(tracker.dev,
                                         cfg.min_abs_dev),
                        "z": z,
                    })
                # a breach is evidence about the incident, not about
                # the healthy baseline: don't absorb it into the EWMA
                continue
            tracker.streak = 0
            tracker.update(value, cfg.ewma_alpha)
        return fired

    # -- fleet-facing seam -------------------------------------------------

    def maybe_poll(self, fleet, now: float) -> List[dict]:
        """Tick-driven entry point: interval-gate, sample the fleet's
        signals, emit any anomalies + the periodic gauge_snapshot.
        Returns the anomalies fired this poll (``[]`` when gated)."""
        if (self._last_poll is not None
                and now - self._last_poll < self.config.poll_interval_s):
            return []
        self._last_poll = now
        if self._fm is None or self._fm.fleet is not fleet:
            self._fm = FleetMetrics(fleet)
        registry = fleet.metrics
        if not self._declared:
            registry.declare_counters(
                "anomalies_total", "gauge_snapshots",
                *(f"anomalies_{name}" for name in self.config.signals))
            self._declared = True
        signals = self._fm.signals()
        fired = self.observe(signals, now)
        from apex_tpu.serving import clock
        for anomaly in fired:
            # counter + event + typed record co-sited: the reconcile
            # contract (counters move iff their event was emitted)
            registry.inc("anomalies_total")
            registry.inc(f"anomalies_{anomaly['signal']}")
            log_event(_LOG, "anomaly", **anomaly)
            registry.event("anomaly", **anomaly)
            registry.emit_record({"kind": "anomaly",
                                  "wall": clock.wall(), **anomaly})
        every = self.config.snapshot_every_polls
        if every and self._polls % every == 0:
            registry.inc("gauge_snapshots")
            registry.emit_record({
                "kind": "gauge_snapshot", "wall": clock.wall(),
                "signals": {k: signals.get(k)
                            for k in _SNAPSHOT_SIGNALS},
                "gauges": self._fm.labeled_gauges()})
        return fired
