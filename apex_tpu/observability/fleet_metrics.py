"""Fleet telemetry plane: per-replica registry views + one merged
snapshot with live autoscaler signals.

A :class:`~apex_tpu.serving.fleet.ReplicaFleet` shares ONE
:class:`~apex_tpu.observability.MetricsRegistry` across its replicas,
which keeps the JSONL stream totally ordered and the global counters
reconcilable — but erases *which replica* a counter increment or
histogram observation came from. This module adds the split without
changing the global view:

- :class:`ReplicaRegistry` — the registry each replica's supervisor/
  engine is handed. Every producer call (``inc`` / ``set_gauge`` /
  ``observe`` / ``declare_counters``) lands on BOTH the replica-local
  state and the shared parent; record/event emission and ``flush`` are
  parent-only (one stream, one ``seq`` order, one final snapshot —
  byte-identical logs to the pre-split fleet).
- :class:`FleetMetrics` — the polled view over a fleet: merged
  counters/gauges/histograms (:func:`merge_histograms`),
  :meth:`FleetMetrics.signals` (goodput window, queue depth, p99
  TTFT/TPOT, slot and kv-page occupancy, per-adapter share — the exact
  dict the autoscaler consumes), and
  :meth:`FleetMetrics.write_prometheus` (the merged view in Prometheus
  textfile format, gauges labeled ``{replica="i"}``).

Everything here is host-side stdlib: polling the plane never touches a
device, a trace, or the decode program.
"""

from __future__ import annotations

import re
import time
from typing import Dict, Iterable, List, Optional

from apex_tpu.observability.registry import (
    HistogramSnapshot,
    MetricsRegistry,
)
from apex_tpu.observability.sinks import PrometheusTextfileSink

__all__ = ["ReplicaRegistry", "FleetMetrics", "merge_histograms"]

#: mirrors ``apex_tpu.serving.FINISH_*`` as literals (this module must
#: import without jax/serving, same convention as slo.py)
_OK_REASONS = ("eos", "length")
_TERMINAL_REASONS = ("eos", "length", "cancelled", "timeout",
                     "rejected", "error")

_ADAPTER_COUNTER = re.compile(r"^adapter(\d+)_requests$")


class ReplicaRegistry(MetricsRegistry):
    """A per-replica view over a shared fleet registry.

    Producer calls update the local state AND forward to ``parent``;
    event/record emission, sink attachment, and flush/close delegate to
    the parent outright (single JSONL stream with the parent's ``seq``
    stamps; snapshots always render the PARENT's global state). Local
    ``counters()``/``gauges()``/``histograms()`` therefore read this
    replica's share — what :class:`FleetMetrics` merges.

    A view survives engine rebuilds (the fleet reuses it per replica
    id), so replica-local counters are cumulative over the replica's
    whole slot in the fleet, like the parent's.
    """

    def __init__(self, parent: MetricsRegistry, replica_id: int):
        super().__init__(sinks=(),
                         histogram_bound=parent._histogram_bound)
        self.parent = parent
        self.replica_id = replica_id

    def add_sink(self, sink) -> None:
        self.parent.add_sink(sink)

    def declare_counters(self, *names: str) -> None:
        super().declare_counters(*names)
        self.parent.declare_counters(*names)

    def inc(self, name: str, n: int = 1) -> int:
        super().inc(name, n)
        return self.parent.inc(name, n)

    def set_gauge(self, name: str, value: float) -> None:
        super().set_gauge(name, value)
        self.parent.set_gauge(name, value)

    def observe(self, name: str, value: float) -> None:
        super().observe(name, value)
        self.parent.observe(name, value)

    def event(self, name: str, **fields) -> dict:
        return self.parent.event(name, **fields)

    def emit_record(self, record: dict) -> None:
        self.parent.emit_record(record)

    def flush(self) -> None:
        self.parent.flush()

    def close(self) -> None:
        self.parent.close()


def merge_histograms(snaps: Iterable[HistogramSnapshot],
                     name: str) -> HistogramSnapshot:
    """Combine per-replica snapshots of the same histogram: exact
    aggregates add (count/sum) or extremize (min/max); the percentile
    windows concatenate — so a merged p99 sees every replica's recent
    observations, not just the loudest replica's."""
    count, total = 0, 0.0
    lo, hi = float("inf"), float("-inf")
    recent: List[float] = []
    for s in snaps:
        count += s.count
        total += s.sum
        lo = min(lo, s.min)
        hi = max(hi, s.max)
        recent.extend(s.recent)
    return HistogramSnapshot(name, count, total, lo, hi, recent)


class FleetMetrics:
    """Polled telemetry view over a ``ReplicaFleet`` (duck-typed: any
    object with ``metrics``, ``replica_metrics``, ``replicas``,
    ``dispatch_set()`` and ``inflight_count``).

    :meth:`signals` is the autoscaler interface: a flat dict of live
    load signals recomputed on every poll, with a *windowed* goodput
    (terminal outcomes since the previous poll) so a scale-up decision
    reacts to what is happening now, not the run-lifetime average.
    """

    def __init__(self, fleet):
        self.fleet = fleet
        self._window_ok = 0         # terminal counts at the last poll
        self._window_terminal = 0
        self._window_ts = time.monotonic()   # when the window opened

    # -- merged views ------------------------------------------------------

    def replica_counters(self) -> Dict[int, Dict[str, int]]:
        """Per-LIVE-replica counter split: retired replica ids never
        appear here (or in :meth:`labeled_gauges`) — a scale-down removes
        the id from every per-replica view, it does not leave a ghost."""
        return {rid: reg.counters()
                for rid, reg in sorted(self.fleet.replica_metrics.items())}

    def _all_registries(self) -> Iterable[MetricsRegistry]:
        """Live AND retired replica views — what the merged (fleet-total)
        folds read, so scaling a replica away never un-counts the work it
        did: merged counters keep equaling the parent's for every
        replica-incremented key."""
        regs = list(self.fleet.replica_metrics.values())
        regs.extend(getattr(self.fleet, "retired_replica_metrics",
                            {}).values())
        return regs

    def merged_counters(self) -> Dict[str, int]:
        """Sum of the replica-local counters (retired replicas
        included). For every counter a replica increments this equals
        the parent's value; parent-only keys (``fleet_dispatches``,
        ``requests_shed_fleet``, ...) are absent here — the difference
        IS the fleet-level contribution."""
        merged: Dict[str, int] = {}
        for reg in self._all_registries():
            for name, value in reg.counters().items():
                merged[name] = merged.get(name, 0) + value
        return merged

    def merged_histograms(self) -> Dict[str, HistogramSnapshot]:
        per_replica: Dict[str, List[HistogramSnapshot]] = {}
        for reg in self._all_registries():
            for name, snap in reg.histograms().items():
                per_replica.setdefault(name, []).append(snap)
        return {name: merge_histograms(snaps, name)
                for name, snaps in sorted(per_replica.items())}

    def labeled_gauges(self) -> Dict[str, float]:
        """Per-replica gauges under Prometheus-style labels
        (``kv_pages_free{replica="1"}``) plus the parent's unlabeled
        (fleet-level / last-writer) gauges."""
        gauges: Dict[str, float] = dict(self.fleet.metrics.gauges())
        for rid, reg in sorted(self.fleet.replica_metrics.items()):
            for name, value in reg.gauges().items():
                gauges[f'{name}{{replica="{rid}"}}'] = value
        return gauges

    def labeled_histograms(self) -> Dict[str, HistogramSnapshot]:
        """The merged (fleet-wide, unlabeled) histograms plus each LIVE
        replica's own under ``name{replica="i"}`` labels — same labeling
        convention as :meth:`labeled_gauges`, so the Prometheus export
        carries both the fleet summary and the per-replica split of the
        same family (a drifting replica is visible next to the merged
        p99 that hides it)."""
        hists: Dict[str, HistogramSnapshot] = dict(
            self.merged_histograms())
        for rid, reg in sorted(self.fleet.replica_metrics.items()):
            for name, snap in reg.histograms().items():
                hists[f'{name}{{replica="{rid}"}}'] = snap
        return hists

    def snapshot(self) -> dict:
        """One merged, JSON-ready view: global counters (the parent's —
        replica sums plus fleet-level keys), the per-replica counter
        split, labeled gauges, and merged histogram summaries."""
        return {
            "counters": self.fleet.metrics.counters(),
            "replica_counters": {
                str(rid): c
                for rid, c in self.replica_counters().items()},
            "gauges": self.labeled_gauges(),
            "histograms": {name: snap.as_dict()
                           for name, snap
                           in self.merged_histograms().items()},
        }

    # -- the autoscaler interface -----------------------------------------

    def signals(self) -> dict:
        """The live signal dict the SLO-driven autoscaler polls
        (ROADMAP: train->serve loop). Derived entirely from the merged
        counters/histograms plus live queue/slot state — every value is
        recomputable from :meth:`snapshot`, which the acceptance test
        reconciles."""
        fleet = self.fleet
        counters = fleet.metrics.counters()
        hists = self.merged_histograms()
        ok = sum(counters.get(f"requests_{r}", 0) for r in _OK_REASONS)
        terminal = sum(counters.get(f"requests_{r}", 0)
                       for r in _TERMINAL_REASONS)
        window_ok = ok - self._window_ok
        window_terminal = terminal - self._window_terminal
        self._window_ok, self._window_terminal = ok, terminal
        now = time.monotonic()
        window_s = now - self._window_ts
        self._window_ts = now

        def _p99(name: str) -> Optional[float]:
            snap = hists.get(name)
            if snap is None or not snap.recent:
                return None
            return snap.percentile(99)

        replicas = list(fleet.replicas)
        # supervisor.queued_count folds in its restart backlog, so a
        # replica mid-restart still reports its waiting work
        queue_depth = sum(r.supervisor.queued_count for r in replicas)
        queue_depth += len(getattr(fleet, "_backlog", ()))
        # token-weighted backlog: the same prompt-token sum the
        # supervisor's admission surcharge prices, so the autoscaler can
        # tell a queue of long prompts from the same depth of short ones
        queued_tokens = sum(
            getattr(r.supervisor, "queued_prompt_tokens", 0)
            for r in replicas)
        active_slots = sum(r.supervisor.active_count for r in replicas)
        total_slots = len(replicas) * fleet.config.max_slots
        pages_in_use = pages_total = 0.0
        for reg in fleet.replica_metrics.values():
            gauges = reg.gauges()
            if "kv_pages_in_use" in gauges:
                pages_in_use += gauges["kv_pages_in_use"]
                pages_total += (gauges["kv_pages_in_use"]
                                + gauges.get("kv_pages_free", 0.0))
        adapter_requests = {
            f"adapter{m.group(1)}": value
            for name, value in counters.items()
            if (m := _ADAPTER_COUNTER.match(name)) and value}
        adapter_total = sum(adapter_requests.values())
        return {
            "replicas_total": len(replicas),
            "replicas_dispatchable": len(fleet.dispatch_set()),
            "inflight": fleet.inflight_count,
            "queue_depth": queue_depth,
            "queued_tokens": queued_tokens,
            "requests_submitted": counters.get("requests_submitted", 0),
            "requests_ok": ok,
            "requests_terminal": terminal,
            "goodput": ok / terminal if terminal else None,
            # an idle window is 0.0, never None/NaN: "nothing completed"
            # must rate-normalize cleanly in the autoscaler (which guards
            # on window_terminal before treating 0.0 as degradation)
            "goodput_window": (window_ok / window_terminal
                               if window_terminal else 0.0),
            "window_ok": window_ok,
            "window_terminal": window_terminal,
            "window_s": window_s,
            "ttft_p99_s": _p99("request_ttft_s"),
            "tpot_p99_s": _p99("request_tpot_s"),
            # speculative-decoding health over the recent window (None
            # when no engine speculates — absence, not a zero rate)
            "spec_accept_rate": (
                (lambda s: sum(s.recent) / len(s.recent)
                 if s is not None and s.recent else None)(
                     hists.get("spec_accept_rate"))),
            "slot_occupancy": (active_slots / total_slots
                               if total_slots else None),
            "kv_page_occupancy": (pages_in_use / pages_total
                                  if pages_total else None),
            # share of adapter-attributed arrivals per bank row — base
            # traffic has no per-adapter counter and is excluded from
            # the denominator
            "adapter_share": {
                name: value / adapter_total
                for name, value in sorted(adapter_requests.items())},
        }

    # -- export ------------------------------------------------------------

    def write_prometheus(self, path: str) -> None:
        """Render the merged view to ``path`` in Prometheus textfile
        format (atomic replace): global counters as ``_total``, labeled
        per-replica + fleet gauges, and histograms as label-aware
        summary families — the merged (unlabeled) series next to each
        replica's ``{replica="i"}`` split."""
        sink = PrometheusTextfileSink(path)
        wall = time.time()
        snap = self.snapshot()
        sink.write({"kind": "counters", "wall": wall,
                    "values": snap["counters"]})
        sink.write({"kind": "gauges", "wall": wall,
                    "values": snap["gauges"]})
        sink.write({"kind": "histograms", "wall": wall,
                    "values": {name: h.as_dict() for name, h
                               in self.labeled_histograms().items()}})
        sink.flush()
