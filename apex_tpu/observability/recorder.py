"""Flight recorder: bounded rings of recent telemetry + incident
postmortem bundles.

The observability plane (registry -> sinks -> monitor) can say *that*
an incident happened — counters and event streams reconcile key-for-key
— but by the time a counter moves, the surrounding evidence (the last
few hundred events, the signal trajectory, each replica's slot/page
state, the in-flight request cursors) is gone. The
:class:`FlightRecorder` keeps exactly that evidence, always, at
near-zero cost: it is a registry **sink** (attach it with
``registry.add_sink``) holding three bounded ``deque`` rings — recent
``kind="event"`` records, recent ``kind="gauge_snapshot"`` samples, and
recent typed records (requests, spans, autoscale/deploy decisions,
anomalies). Memory is O(capacity) no matter how long the run is.

When an **incident-class** event flows through the sink — any event
named in :data:`TRIGGER_EVENTS`: quarantines, engine restarts, breaker
opens, deploy rollbacks, retraces, sentinel anomalies — the recorder
:meth:`dump`\\ s a self-contained JSON postmortem bundle: the trigger
record, the full ring contents, a per-replica engine digest (slot
table, PagePool stats, in-flight request cursors), the last signals
snapshot, the live counter totals, and a config fingerprint. The
bundle lands next to the run log (``bundle_dir``) and is rendered by
``python -m apex_tpu.monitor bundle <path>``. ``max_bundles`` (default
1) latches the dump — the FIRST incident is the evidence worth
keeping; later incidents are usually its consequences.

The dump emits a ``bundle_dumped`` event co-sited with a
``bundles_dumped`` counter increment and a ``kind="bundle"`` record, so
the monitor's bundle section reconciles key-for-key like every other
incident class. ``bundle_dumped`` is deliberately NOT a trigger.

:data:`TRIGGER_EVENTS` is built **by construction** from the monitor's
``*_INCIDENT_COUNTERS`` maps (plus the recorder-only extras below), and
the APX013 lint rule re-checks the inclusion tree-wide: an incident
class the monitor reconciles but the recorder would sleep through is a
lint error, not a 3 a.m. surprise.

Wall stamps go through the serving clock seam
(:mod:`apex_tpu.serving.clock`, imported lazily to keep this module
stdlib-importable), so bundles are deterministic under
``VirtualClock``. Everything here is host-side and defensive: a dump
failure degrades to a logged error — telemetry must never take the
serving path down.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Dict, List, Optional

from apex_tpu.observability.report import (
    CHECKPOINT_INCIDENT_COUNTERS,
    FLEET_INCIDENT_COUNTERS,
    SENTINEL_INCIDENT_COUNTERS,
    SERVING_INCIDENT_COUNTERS,
)
from apex_tpu.utils.logging import get_logger

__all__ = ["FlightRecorder", "TRIGGER_EVENTS", "RECORDER_TRIGGER_EXTRAS"]

_LOG = get_logger(__name__)

#: incident-class triggers that have no ``*_INCIDENT_COUNTERS`` entry:
#: ``retrace`` is deliberately outside the strict one-inc-per-event
#: mapping (a batched cache-size jump can cover several compiles), yet a
#: serving recompile is exactly the incident a bundle should survive.
RECORDER_TRIGGER_EXTRAS = frozenset({"retrace"})

#: every event name that triggers a postmortem dump — the union of the
#: monitor's incident maps (kept in lockstep by APX013 and its lock
#: test) plus :data:`RECORDER_TRIGGER_EXTRAS`. ``bundle_dumped`` must
#: never appear here: a dump must not trigger a dump.
TRIGGER_EVENTS = frozenset(
    set(SERVING_INCIDENT_COUNTERS)
    | set(FLEET_INCIDENT_COUNTERS)
    | set(CHECKPOINT_INCIDENT_COUNTERS)
    | set(SENTINEL_INCIDENT_COUNTERS)
    | RECORDER_TRIGGER_EXTRAS)

#: flush-snapshot kinds — retained last-wins, never ring-buffered (one
#: snapshot can be large; the ring holds the *stream*, not the state)
_SNAPSHOT_KINDS = ("counters", "gauges", "histograms")

_CLOCK = None


def _wall() -> float:
    """Epoch stamp through the serving clock seam — lazily imported so
    this module stays importable without jax (the monitor/analysis
    planes read bundles on hosts far from the TPU that wrote them)."""
    global _CLOCK
    if _CLOCK is None:
        try:
            from apex_tpu.serving import clock as _CLOCK  # noqa: F811
        except Exception:                                 # pragma: no cover
            import time as _CLOCK  # duck-typed: time.time == clock.wall
    return _CLOCK.wall() if hasattr(_CLOCK, "wall") else _CLOCK.time()


def _safe(fn, default=None):
    """Evaluate a digest thunk defensively: postmortem evidence is
    best-effort by contract — a half-torn engine mid-incident must not
    make the dump itself raise."""
    try:
        return fn()
    except Exception:
        return default


class FlightRecorder:
    """Bounded-ring telemetry recorder + incident bundle dumper.

    Args:
      events_capacity / records_capacity / gauges_capacity: ring sizes
        (``deque(maxlen=...)``) for event records, typed records, and
        ``kind="gauge_snapshot"`` samples respectively.
      max_bundles: dump latch — at most this many bundles per recorder
        lifetime (default 1: the first incident is the postmortem).
      bundle_dir: where bundle files land (created on demand); ``None``
        keeps bundles in memory only (:attr:`bundles`).
      bundle_prefix: filename stem — bundles are named
        ``<prefix>-bundle-<n>.json`` (deterministic: no timestamp).
      triggers: override :data:`TRIGGER_EVENTS` (tests; production code
        should extend the incident maps instead so APX013 sees it).

    Use: ``registry.add_sink(recorder)`` then
    ``recorder.attach(fleet_or_supervisor, registry)``. The registry's
    re-entrant lock makes the in-``write`` dump safe: the recorder reads
    registry state and emits the bundle record from the same thread that
    holds the lock.
    """

    def __init__(self, *, events_capacity: int = 256,
                 records_capacity: int = 256,
                 gauges_capacity: int = 64,
                 max_bundles: int = 1,
                 bundle_dir: Optional[str] = None,
                 bundle_prefix: str = "flight",
                 triggers: Optional[frozenset] = None):
        for knob, value in (("events_capacity", events_capacity),
                            ("records_capacity", records_capacity),
                            ("gauges_capacity", gauges_capacity)):
            if value < 1:
                raise ValueError(f"{knob} must be >= 1, got {value}")
        if max_bundles < 0:
            raise ValueError(
                f"max_bundles must be >= 0, got {max_bundles}")
        self.events: deque = deque(maxlen=int(events_capacity))
        self.records: deque = deque(maxlen=int(records_capacity))
        self.gauge_snapshots: deque = deque(maxlen=int(gauges_capacity))
        self.max_bundles = int(max_bundles)
        self.bundle_dir = bundle_dir
        self.bundle_prefix = bundle_prefix
        self.triggers = (TRIGGER_EVENTS if triggers is None
                         else frozenset(triggers))
        #: dumped bundle dicts, in order (bounded by ``max_bundles``)
        self.bundles: List[dict] = []
        #: file paths of dumped bundles (empty when ``bundle_dir=None``)
        self.bundle_paths: List[str] = []
        self._target: Any = None
        self._registry: Any = None
        self._last_signals: Optional[dict] = None
        self._last_snapshots: Dict[str, dict] = {}
        self._dumping = False

    def attach(self, target, registry=None) -> "FlightRecorder":
        """Point the recorder at the serving object whose state a dump
        digests (a ``ReplicaFleet`` or an ``EngineSupervisor``) and the
        registry it reconciles through. Returns ``self`` for chaining."""
        self._target = target
        self._registry = registry
        if registry is not None:
            registry.declare_counters("bundles_dumped")
        return self

    # -- the sink protocol -------------------------------------------------

    def write(self, record: dict) -> None:
        kind = record.get("kind")
        if kind == "event":
            self.events.append(record)
            if (record.get("event") in self.triggers
                    and not self._dumping
                    and len(self.bundles) < self.max_bundles):
                self.dump(record)
        elif kind == "gauge_snapshot":
            self.gauge_snapshots.append(record)
            if isinstance(record.get("signals"), dict):
                self._last_signals = record["signals"]
        elif kind == "signals":
            if isinstance(record.get("values"), dict):
                self._last_signals = record["values"]
        elif kind in _SNAPSHOT_KINDS:
            self._last_snapshots[kind] = record.get("values", {})
        else:
            self.records.append(record)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- the dump ----------------------------------------------------------

    def dump(self, trigger: Optional[dict] = None) -> Optional[dict]:
        """Write one self-contained postmortem bundle. Called
        automatically from :meth:`write` on a trigger event; callable
        directly (``trigger=None``) for an on-demand snapshot. Never
        raises — a failed dump is a logged error, not an outage."""
        self._dumping = True
        try:
            bundle = self._build_bundle(trigger)
            self.bundles.append(bundle)
            path = None
            if self.bundle_dir is not None:
                path = os.path.join(
                    self.bundle_dir,
                    f"{self.bundle_prefix}-bundle-"
                    f"{len(self.bundles)}.json")
                os.makedirs(self.bundle_dir, exist_ok=True)
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(bundle, f, indent=2, sort_keys=True,
                              default=str)
                    f.write("\n")
                bundle["path"] = path
                self.bundle_paths.append(path)
            if self._registry is not None:
                # one counter increment co-sited with its event and the
                # typed record — the reconcile contract every other
                # incident class already follows
                self._registry.inc("bundles_dumped")
                trigger_name = (trigger or {}).get("event")
                self._registry.event("bundle_dumped",
                                     trigger=trigger_name, path=path)
                self._registry.emit_record({
                    "kind": "bundle", "bundle_seq": len(self.bundles),
                    "trigger": trigger_name, "path": path,
                    "events": len(bundle.get("events", ())),
                    "wall": bundle.get("wall")})
            return bundle
        except Exception:
            _LOG.exception("flight recorder dump failed")
            return None
        finally:
            self._dumping = False

    def _build_bundle(self, trigger: Optional[dict]) -> dict:
        counters = None
        if self._registry is not None:
            counters = _safe(self._registry.counters)
        if counters is None:
            counters = self._last_snapshots.get("counters", {})
        return {
            "schema": 1,
            "kind": "flight_bundle",
            "wall": _wall(),
            "trigger": dict(trigger) if trigger else None,
            "capacities": {
                "events": self.events.maxlen,
                "records": self.records.maxlen,
                "gauge_snapshots": self.gauge_snapshots.maxlen},
            "events": [dict(r) for r in self.events],
            "records": [dict(r) for r in self.records],
            "gauge_snapshots": [dict(r) for r in self.gauge_snapshots],
            "signals": self._last_signals,
            "counters": counters,
            "replicas": _safe(lambda: _target_digest(self._target), []),
            "config": _safe(lambda: _config_fingerprint(self._target)),
        }


# -- digests ---------------------------------------------------------------


def _target_digest(target) -> List[dict]:
    """Per-replica engine digests of a fleet (or the single digest of a
    bare supervisor). Every field is best-effort: a replica mid-rebuild
    digests to whatever is still reachable."""
    if target is None:
        return []
    if hasattr(target, "replicas"):
        out = []
        for replica in list(target.replicas):
            d = _replica_digest(replica.supervisor)
            d["replica_id"] = _safe(lambda r=replica: r.replica_id)
            d["state"] = _safe(lambda r=replica: r.state)
            d["dispatches"] = _safe(lambda r=replica: r.dispatches)
            out.append(d)
        return out
    return [_replica_digest(target)]


def _replica_digest(sup) -> dict:
    """One supervised engine's postmortem digest: breaker/restart
    state, queue/slot cursors, the slot table and PagePool stats, and
    every in-flight request's position."""
    d = {
        "breaker": _safe(lambda: sup.breaker_state),
        "restarts": _safe(lambda: sup.restarts),
        "queued": _safe(lambda: sup.queued_count),
        "active": _safe(lambda: sup.active_count),
        "inflight": _safe(lambda: sup.inflight_count),
        "queued_prompt_tokens": _safe(
            lambda: sup.queued_prompt_tokens),
        "service_estimate_s": _safe(lambda: sup.service_estimate_s),
    }
    engine = getattr(sup, "engine", None)
    if engine is None:
        return d
    d["compiles"] = {
        "prefill": _safe(lambda: engine.prefill_compiles),
        "decode": _safe(lambda: engine.decode_compiles),
        "chunk": _safe(lambda: engine.chunk_compiles),
        "decode_retraces": _safe(lambda: engine.decode_retraces),
    }
    slots = getattr(engine, "slots", None)
    if slots is not None:
        d["slots"] = {
            "free": _safe(lambda: slots.free_count),
            "active": _safe(lambda: slots.active_count),
            "occupancy": _safe(lambda: slots.occupancy),
        }
    pages = getattr(engine, "pages", None)
    if pages is not None:
        d["pages"] = {
            "free": _safe(lambda: pages.free_count),
            "in_use": _safe(lambda: pages.in_use_count),
            "owned": _safe(lambda: pages.owned_count),
            "reclaimable": _safe(lambda: pages.reclaimable_count),
            "interned": _safe(lambda: pages.interned_count),
            "occupancy": _safe(lambda: pages.occupancy),
            "evictions": _safe(lambda: pages.evictions),
        }
    d["requests"] = _safe(lambda: [
        {"request_id": _safe(lambda r=req: r.request_id),
         "trace_id": _safe(lambda r=req: r.trace_id),
         "adapter_id": _safe(
             lambda r=req: r.sampling.adapter_id),
         "generated": len(tokens),
         "submit_ts": submit_ts}
        for req, tokens, submit_ts in engine.inflight()], [])
    return d


def _config_fingerprint(target) -> Optional[dict]:
    """A JSON-able identity card for the serving configuration under
    incident — enough to answer "was the postmortem's fleet built like
    production's?" without shipping weights."""
    if target is None:
        return None
    import dataclasses
    import hashlib

    def _cfg(obj) -> Optional[dict]:
        if obj is None:
            return None
        if dataclasses.is_dataclass(obj):
            out = {}
            for f in dataclasses.fields(obj):
                value = getattr(obj, f.name, None)
                if dataclasses.is_dataclass(value):
                    value = _cfg(value)
                elif not isinstance(value, (int, float, str, bool,
                                            type(None))):
                    value = str(value)
                out[f.name] = value
            return out
        return {"repr": str(obj)}

    card = {
        "engine": _cfg(getattr(target, "config", None)),
        "supervisor": _cfg(getattr(target, "supervisor_config", None)
                           or getattr(target, "_config", None)),
        "fleet": _cfg(getattr(target, "fleet", None)),
    }
    blob = json.dumps(card, sort_keys=True, default=str)
    card["fingerprint"] = hashlib.sha256(
        blob.encode("utf-8")).hexdigest()[:16]
    return card
