"""apex_tpu.observability — metrics, tracing, and run reports.

The third leg of the production triangle next to ``resilience``
(survive) and ``analysis`` (lint): *observe*. TorchTitan (PAPERS.md,
arXiv:2410.06511) treats metrics/logging/profiling as a first-class
subsystem of a pre-training stack; this package is that subsystem here.

- :class:`MetricsRegistry` — thread-safe counters, gauges, and
  bounded-memory histograms with pluggable sinks
  (:class:`JsonlSink`, :class:`PrometheusTextfileSink`,
  :class:`InMemorySink`).
- :class:`StepMetrics` / :class:`StepTimer` — per-step wall time,
  tokens/s, and MFU (FLOP math shared with the benchmark harness via
  :mod:`apex_tpu.utils.flops`), plus device ``memory_stats`` gauges.
  ``ResilienceConfig(metrics=registry)`` wires the whole layer into
  :func:`apex_tpu.resilience.run_training`.
- :func:`span` / :class:`ProfilerCapture` — named scopes that also
  record host durations, and windowed ``jax.profiler`` captures
  (every-N-steps or on watchdog incident).
- :func:`build_report` / :func:`render_report` — fold a run's JSONL log
  into the report ``python -m apex_tpu.monitor`` prints.
"""

from apex_tpu.observability.registry import (
    HistogramSnapshot,
    MetricsRegistry,
    percentile,
)
from apex_tpu.observability.sinks import (
    InMemorySink,
    JsonlSink,
    PrometheusTextfileSink,
)
from apex_tpu.observability.step_metrics import StepMetrics, StepTimer
from apex_tpu.observability.tracing import ProfilerCapture, span
from apex_tpu.observability.report import (
    build_report,
    read_records,
    render_report,
)

__all__ = [
    "MetricsRegistry",
    "HistogramSnapshot",
    "percentile",
    "InMemorySink",
    "JsonlSink",
    "PrometheusTextfileSink",
    "StepMetrics",
    "StepTimer",
    "ProfilerCapture",
    "span",
    "build_report",
    "read_records",
    "render_report",
]
